"""Skyline traffic generator: seeded, replayable synthetic load shapes.

"Heavy traffic from millions of users" as a first-class, *measurable*
input: a traffic spec declares a rate envelope (steady or diurnal, with
optional flash crowds riding on top) plus a multi-tenant mix of
heavy-tailed prompt/output length distributions, and
:func:`generate_trace` turns it into a concrete arrival trace — every
request with an arrival offset, tenant, prompt length, output budget
and per-request prompt seed. The trace is pure data: serializable to
JSONL (:func:`trace_to_jsonl`, canonical ``sort_keys`` form, so the
same spec + seed is **byte-identical** on every machine) and replayable
against a live :class:`serve.server.InferenceServer` or
:class:`serve.fleet.Fleet` (:func:`replay_trace`), or against the
deterministic service model in :mod:`obs.capacity` for capacity
planning without an accelerator.

Spec grammar (the chaos-spec contract — ``;``-joined shapes, each
``kind@key=value:key=value``; unknown kinds/keys/bad values raise):

    TPUNN_TRAFFIC="diurnal@rps=8:duration_s=60:amplitude=0.6:period_s=30"
    TPUNN_TRAFFIC="steady@rps=4:duration_s=10;flash@at_s=5:peak=4:ramp_s=1:hold_s=2"
    TPUNN_TRAFFIC="steady@rps=8:duration_s=20;\
tenant@name=chat:weight=4:prompt=lognormal:prompt_med=24:prompt_sigma=0.7;\
tenant@name=batch:weight=1:prompt=zipf:prompt_a=1.4:prompt_max=192:out_med=48"

Shape kinds:

- ``steady`` — constant rate envelope. Keys: ``rps`` (required),
  ``duration_s``.
- ``diurnal`` — sinusoidal day/night cycle:
  ``rate(t) = rps * (1 + amplitude * sin(2π(t/period_s + phase)))``.
  Keys: ``rps`` (required), ``duration_s``, ``amplitude``,
  ``period_s``, ``phase``.
- ``flash`` — a flash crowd *multiplier* on the base envelope: ramps
  linearly 1→``peak`` over ``ramp_s`` ending at ``at_s``, holds
  ``peak`` for ``hold_s``, ramps back down over ``ramp_s``. Several
  ``flash`` shapes compose multiplicatively. Keys: ``at_s`` (required),
  ``peak`` (required), ``ramp_s``, ``hold_s``.
- ``tenant`` — one tenant class in the mix, picked per-arrival with
  probability ∝ ``weight``. Length distributions per tenant:
  ``prompt``/``out`` ∈ {``lognormal``, ``zipf``} with
  ``prompt_med``/``prompt_sigma`` (lognormal: median, log-σ) or
  ``prompt_a`` (zipf exponent, heavy tail over 1..``prompt_max``), and
  the ``out_*`` twins; ``prompt_min``/``prompt_max``/``out_min``/
  ``out_max`` clamp. ``prefix_len``/``n_prefixes`` model shared
  system prompts: each arrival's prompt starts with one of the
  tenant's ``n_prefixes`` (default 1) fixed seeded prefixes of
  ``prefix_len`` tokens (picked uniformly per arrival), followed by a
  unique suffix — the load shape prefix caching is built for.
  Prism decode-policy keys (serve/decoding.py): ``temperature=`` /
  ``n=`` mark a tenant's requests sampled / best-of-n (each record
  then carries an arithmetic per-arrival ``decode_seed``, so replays
  reproduce the same sampled streams byte-for-byte); ``stream=p``
  flags each arrival streaming with probability ``p`` (one extra
  seeded draw, ONLY for tenants that set the key — the ``prefix_len``
  byte-identity precedent: older specs generate byte-identical
  traces). Keys: ``name`` (required), ``weight``, dist keys, prefix
  keys, decode keys.

Arrivals are a non-homogeneous Poisson process sampled by thinning
(Lewis-Shedler) from a single ``random.Random(seed)`` stream — exact
for any bounded rate envelope, and deterministic because *every* random
decision (candidate gaps, thinning accepts, tenant picks, lengths)
comes from that one seeded stream in a fixed order.
"""

from __future__ import annotations

import bisect
import dataclasses
import json
import logging
import math
import os
import random
import time
import zlib
from typing import Callable, Optional

import numpy as np

log = logging.getLogger(__name__)

ENV_TRAFFIC = "TPUNN_TRAFFIC"

TRAFFIC_KINDS = ("steady", "diurnal", "flash", "tenant")

# typed key tables (the chaos parse_spec contract: every key is named
# here or the spec fails loudly)
_INT_KEYS = ("prompt_min", "prompt_max", "out_min", "out_max",
             "prefix_len", "n_prefixes", "n")
_FLOAT_KEYS = ("rps", "duration_s", "amplitude", "period_s", "phase",
               "at_s", "peak", "ramp_s", "hold_s", "weight",
               "prompt_med", "prompt_sigma", "prompt_a",
               "out_med", "out_sigma", "out_a",
               "temperature", "stream")
_STR_KEYS = ("name", "prompt", "out")

_DISTS = ("lognormal", "zipf")


@dataclasses.dataclass
class Shape:
    """One parsed ``kind@...`` clause."""

    kind: str
    args: dict

    def describe(self) -> str:
        body = ":".join(f"{k}={v}" for k, v in sorted(self.args.items()))
        return f"{self.kind}@{body}" if body else self.kind


def _validate(shape: Shape) -> None:
    a = shape.args
    need = {"steady": ("rps",), "diurnal": ("rps",),
            "flash": ("at_s", "peak"), "tenant": ("name",)}[shape.kind]
    for key in need:
        if key not in a:
            raise ValueError(
                f"traffic shape {shape.kind!r} requires key {key!r} "
                f"(got {sorted(a)})")
    if a.get("rps", 1.0) <= 0:
        raise ValueError(f"traffic {shape.kind!r}: rps must be > 0")
    if not 0.0 <= a.get("amplitude", 0.0) < 1.0:
        raise ValueError("traffic diurnal: amplitude must be in [0, 1) "
                         "(the envelope may not go negative)")
    if a.get("period_s", 1.0) <= 0 or a.get("duration_s", 1.0) <= 0:
        raise ValueError(f"traffic {shape.kind!r}: period_s/duration_s "
                         f"must be > 0")
    if shape.kind == "flash" and a["peak"] <= 0:
        raise ValueError("traffic flash: peak must be > 0")
    if a.get("weight", 1.0) <= 0:
        raise ValueError("traffic tenant: weight must be > 0")
    if a.get("prefix_len", 0) < 0:
        raise ValueError("traffic tenant: prefix_len must be >= 0")
    if a.get("n_prefixes", 1) < 1:
        raise ValueError("traffic tenant: n_prefixes must be >= 1")
    if "n_prefixes" in a and a.get("prefix_len", 0) <= 0:
        raise ValueError(
            "traffic tenant: n_prefixes without prefix_len is "
            "meaningless (set prefix_len > 0)")
    if a.get("temperature", 0.0) < 0:
        raise ValueError("traffic tenant: temperature must be >= 0")
    if a.get("n", 1) < 1:
        raise ValueError("traffic tenant: n must be >= 1")
    if not 0.0 <= a.get("stream", 0.0) <= 1.0:
        raise ValueError("traffic tenant: stream must be a "
                         "probability in [0, 1]")
    if "stream" in a and a.get("n", 1) > 1:
        raise ValueError(
            "traffic tenant: stream= with n > 1 is invalid — n-best "
            "ranking needs every full stream before picking a winner "
            "(the scheduler rejects the combination too)")
    for side in ("prompt", "out"):
        dist = a.get(side, "lognormal")
        if dist not in _DISTS:
            raise ValueError(
                f"traffic tenant {side}= must be one of {_DISTS}, "
                f"got {dist!r}")
        if a.get(f"{side}_a", 1.1) <= 1.0:
            raise ValueError(
                f"traffic tenant {side}_a (zipf exponent) must be > 1")
        lo = a.get(f"{side}_min", 1)
        hi = a.get(f"{side}_max", 1 << 20)
        if not 1 <= lo <= hi:
            raise ValueError(
                f"traffic tenant needs 1 <= {side}_min <= {side}_max")


def parse_spec(spec: str) -> "TrafficSpec":
    """Parse a ``TPUNN_TRAFFIC`` spec. Exactly one base envelope
    (``steady`` or ``diurnal``) is required; a typo'd spec raises — the
    chaos contract: a load test that silently generates the wrong load
    is worse than one that refuses to start."""
    shapes: list[Shape] = []
    for clause in filter(None,
                         (c.strip() for c in (spec or "").split(";"))):
        kind, _, body = clause.partition("@")
        kind = kind.strip()
        if kind not in TRAFFIC_KINDS:
            raise ValueError(f"unknown traffic shape {kind!r} in "
                             f"{spec!r}; have {TRAFFIC_KINDS}")
        args: dict = {}
        for field in filter(None, body.split(":")):
            key, eq, value = field.partition("=")
            key = key.strip()
            value = value.strip()
            if not eq:
                raise ValueError(f"malformed traffic field {field!r} "
                                 f"in {clause!r} (want key=value)")
            try:
                if key in _INT_KEYS:
                    args[key] = int(value)
                elif key in _FLOAT_KEYS:
                    args[key] = float(value)
                elif key in _STR_KEYS:
                    args[key] = value
                else:
                    raise KeyError(key)
            except KeyError:
                raise ValueError(
                    f"unknown traffic key {key!r} for shape {kind!r} "
                    f"in {spec!r}") from None
            except ValueError:
                raise ValueError(
                    f"bad value for traffic key {key!r}: {value!r}"
                ) from None
        shape = Shape(kind, args)
        _validate(shape)
        shapes.append(shape)
    bases = [s for s in shapes if s.kind in ("steady", "diurnal")]
    if len(bases) != 1:
        raise ValueError(
            f"traffic spec needs exactly one base envelope "
            f"(steady|diurnal), got {len(bases)} in {spec!r}")
    return TrafficSpec(shapes=tuple(shapes))


def maybe_from_env() -> Optional["TrafficSpec"]:
    """Parse ``TPUNN_TRAFFIC`` when set and non-"0", else None."""
    spec = os.environ.get(ENV_TRAFFIC, "").strip()
    if not spec or spec == "0":
        return None
    return parse_spec(spec)


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """A parsed traffic spec: one base envelope + flash/tenant shapes."""

    shapes: tuple

    @property
    def base(self) -> Shape:
        return next(s for s in self.shapes
                    if s.kind in ("steady", "diurnal"))

    @property
    def flashes(self) -> list[Shape]:
        return [s for s in self.shapes if s.kind == "flash"]

    @property
    def tenants(self) -> list[Shape]:
        ts = [s for s in self.shapes if s.kind == "tenant"]
        return ts or [Shape("tenant", {"name": "default"})]

    @property
    def duration_s(self) -> float:
        return float(self.base.args.get("duration_s", 10.0))

    @property
    def base_rps(self) -> float:
        return float(self.base.args["rps"])

    @property
    def shape_name(self) -> str:
        """Report label: base kind plus a +flash marker."""
        name = self.base.kind
        if self.flashes:
            name += "+flash"
        return name

    def describe(self) -> str:
        return ";".join(s.describe() for s in self.shapes)

    # -- rate envelope ---------------------------------------------------

    def rate_at(self, t: float, *, rps_scale: float = 1.0) -> float:
        """Instantaneous offered rate (req/s) at trace time ``t``."""
        base = self.base
        rate = base.args["rps"] * rps_scale
        if base.kind == "diurnal":
            amp = base.args.get("amplitude", 0.5)
            period = base.args.get("period_s", 60.0)
            phase = base.args.get("phase", 0.0)
            rate *= 1.0 + amp * math.sin(2 * math.pi
                                         * (t / period + phase))
        for fl in self.flashes:
            at = fl.args["at_s"]
            peak = fl.args["peak"]
            ramp = fl.args.get("ramp_s", 1.0)
            hold = fl.args.get("hold_s", 0.0)
            if at - ramp <= t < at:            # ramp up
                frac = (t - (at - ramp)) / max(ramp, 1e-9)
                rate *= 1.0 + (peak - 1.0) * frac
            elif at <= t <= at + hold:          # hold the crest
                rate *= peak
            elif at + hold < t <= at + hold + ramp:  # ramp down
                frac = (t - (at + hold)) / max(ramp, 1e-9)
                rate *= peak + (1.0 - peak) * frac
        return max(rate, 0.0)

    def rate_max(self, *, rps_scale: float = 1.0) -> float:
        """Analytic upper bound on the envelope — the thinning
        majorant. Flash multipliers compose, so bound with their
        product (conservative; thinning stays exact)."""
        base = self.base
        peak = base.args["rps"] * rps_scale
        if base.kind == "diurnal":
            peak *= 1.0 + base.args.get("amplitude", 0.5)
        for fl in self.flashes:
            peak *= max(fl.args["peak"], 1.0)
        return peak


# ---------------------------------------------------------------------------
# Trace generation (all randomness from one seeded stdlib stream)
# ---------------------------------------------------------------------------


def _zipf_cdf(a: float, n: int) -> list[float]:
    weights = [k ** -a for k in range(1, n + 1)]
    total = sum(weights)
    acc, cdf = 0.0, []
    for w in weights:
        acc += w
        cdf.append(acc / total)
    return cdf


def _sample_len(rng: random.Random, args: dict, side: str,
                *, default_med: float, default_max: int) -> int:
    dist = args.get(side, "lognormal")
    lo = args.get(f"{side}_min", 1)
    hi = args.get(f"{side}_max", default_max)
    if dist == "zipf":
        a = args.get(f"{side}_a", 1.3)
        cdf = _zipf_cdf(a, hi)
        val = bisect.bisect_left(cdf, rng.random()) + 1
    else:
        med = args.get(f"{side}_med", default_med)
        sigma = args.get(f"{side}_sigma", 0.6)
        val = int(round(med * math.exp(sigma * rng.gauss(0.0, 1.0))))
    return max(lo, min(val, hi))


def generate_trace(spec: TrafficSpec, *, seed: int = 0,
                   rps_scale: float = 1.0,
                   max_requests: int = 1_000_000) -> list[dict]:
    """Spec + seed → arrival trace, deterministically. Each record:

    ``{"i", "t", "tenant", "prompt_len", "max_new", "prompt_seed"}``

    ``rps_scale`` multiplies the whole envelope — the capacity sweep's
    offered-load knob — while keeping the same seed, so rungs of one
    sweep are directly comparable shapes, not unrelated traces."""
    rng = random.Random(seed)
    tenants = spec.tenants
    cum, acc = [], 0.0
    for ten in tenants:
        acc += ten.args.get("weight", 1.0)
        cum.append(acc)
    rmax = spec.rate_max(rps_scale=rps_scale)
    duration = spec.duration_s
    trace: list[dict] = []
    t = 0.0
    while len(trace) < max_requests:
        t += rng.expovariate(rmax)
        if t >= duration:
            break
        # thinning: accept the candidate with prob rate(t)/rmax. The
        # rejected draw still consumes rng state — that ordering IS the
        # determinism contract, do not reorder draws.
        if rng.random() * rmax > spec.rate_at(t, rps_scale=rps_scale):
            continue
        ten = tenants[bisect.bisect_left(cum, rng.random() * acc)]
        idx = len(trace)
        rec = {
            "i": idx,
            "t": round(t, 6),
            "tenant": ten.args.get("name", "default"),
            "prompt_len": _sample_len(rng, ten.args, "prompt",
                                      default_med=24.0, default_max=256),
            "max_new": _sample_len(rng, ten.args, "out",
                                   default_med=16.0, default_max=128),
            "prompt_seed": (seed * 1_000_003 + idx) & 0x7FFFFFFF,
        }
        prefix_len = int(ten.args.get("prefix_len", 0))
        if prefix_len > 0:
            # shared-system-prompt shape: pick one of the tenant's
            # fixed prefixes. The extra rng draw happens ONLY for
            # prefix tenants, so specs without prefix_len generate
            # byte-identical traces to older versions.
            pidx = rng.randrange(int(ten.args.get("n_prefixes", 1)))
            tenant_ns = zlib.crc32(rec["tenant"].encode())
            rec["prefix_len"] = prefix_len
            rec["prefix_seed"] = ((seed * 1_000_033 + tenant_ns * 31
                                   + pidx) & 0x7FFFFFFF)
            # the prompt must extend past its prefix by >= 1 token
            # (a cached prefix still needs a suffix to prefill)
            rec["prompt_len"] = max(rec["prompt_len"], prefix_len + 1)
        # Prism decode-policy keys: present ONLY when the tenant set
        # them, so specs without them generate byte-identical traces.
        # decode_seed is arithmetic (prompt_seed's scheme, different
        # multiplier) — no rng draw, so it perturbs nothing.
        temp = float(ten.args.get("temperature", 0.0))
        n_best = int(ten.args.get("n", 1))
        if temp > 0.0 or n_best > 1:
            if temp > 0.0:
                rec["temperature"] = temp
            if n_best > 1:
                rec["n"] = n_best
            rec["decode_seed"] = (seed * 1_000_081 + idx) & 0x7FFFFFFF
        if "stream" in ten.args:
            # the ONE extra rng draw, only for tenants using stream=
            # (the prefix_len byte-identity precedent)
            if rng.random() < float(ten.args["stream"]):
                rec["stream"] = True
        trace.append(rec)
    return trace


# ---------------------------------------------------------------------------
# Canonical JSONL serialization (byte-identical replay unit)
# ---------------------------------------------------------------------------


def trace_to_jsonl(trace: list[dict]) -> str:
    """Canonical serialization: one ``sort_keys`` JSON object per line.
    Same spec + seed → the same bytes, on every run and machine."""
    return "".join(json.dumps(rec, sort_keys=True) + "\n"
                   for rec in trace)


def write_trace(path: str, trace: list[dict]) -> None:
    with open(path, "w") as f:
        f.write(trace_to_jsonl(trace))


def load_trace(path: str) -> list[dict]:
    trace = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                trace.append(json.loads(line))
    return trace


def prompt_tokens(rec: dict, vocab_size: int) -> np.ndarray:
    """The prompt for a trace record — derived from its
    ``prompt_seed``, so replay regenerates identical tokens without
    serializing them. Records carrying ``prefix_seed`` (the
    ``prefix_len=`` tenant grammar) start with the shared seeded
    prefix — every record with the same prefix_seed gets the same
    leading tokens, which is what makes replayed traffic exercise the
    prefix cache — followed by a per-request suffix."""
    total = int(rec["prompt_len"])
    rng = np.random.default_rng(int(rec["prompt_seed"]))
    if "prefix_seed" in rec:
        plen = min(int(rec["prefix_len"]), total - 1)
        prng = np.random.default_rng(int(rec["prefix_seed"]))
        prefix = prng.integers(0, vocab_size, size=(plen,))
        suffix = rng.integers(0, vocab_size, size=(total - plen,))
        return np.concatenate([prefix, suffix]).astype(np.int32)
    return rng.integers(0, vocab_size,
                        size=(total,)).astype(np.int32)


# ---------------------------------------------------------------------------
# Replay drivers
# ---------------------------------------------------------------------------


def replay_trace(trace: list[dict], submit: Callable,
                 *, vocab_size: int, realtime: bool = True,
                 time_scale: float = 1.0,
                 on_tick: Optional[Callable] = None) -> list:
    """Drive a live service with a trace. ``submit(prompt, max_new)``
    adapts the target — ``lambda p, n: server.submit(p, n)`` or
    ``lambda p, n: fleet.submit(p, n)``. ``realtime=True`` sleeps to
    each record's arrival offset (``time_scale`` compresses/stretches
    the clock); ``realtime=False`` submits the backlog at once (the
    saturation probe). ``on_tick(t)`` — called once per arrival with
    the record's *virtual* trace time, before it submits — gives a
    controller a deterministic clock on the replay thread (Helm's
    ``FleetAutoscaler.step`` rides it in ``bench.py --autoscale``;
    workers must never drive control themselves). Returns the submit
    handles in trace order.

    Records carrying Prism decode keys (``temperature``/``n`` +
    ``decode_seed``, or ``stream``) submit with the matching
    ``decode=DecodeSpec(...)`` / ``stream=True`` kwargs; records
    without them call the plain two-argument form, so existing
    ``lambda p, n: ...`` adapters replay older traces unchanged."""
    from pytorch_distributed_nn_tpu.serve.decoding import DecodeSpec

    handles = []
    t0 = time.monotonic()
    for rec in trace:
        if realtime:
            wait = float(rec["t"]) / time_scale - (time.monotonic() - t0)
            if wait > 0:
                time.sleep(wait)
        if on_tick is not None:
            on_tick(float(rec["t"]))
        kw = {}
        if "temperature" in rec or "n" in rec:
            kw["decode"] = DecodeSpec(
                temperature=float(rec.get("temperature", 0.0)),
                n=int(rec.get("n", 1)),
                seed=int(rec.get("decode_seed", 0)))
        if rec.get("stream"):
            kw["stream"] = True
        if kw:
            handles.append(submit(prompt_tokens(rec, vocab_size),
                                  int(rec["max_new"]), **kw))
        else:
            handles.append(submit(prompt_tokens(rec, vocab_size),
                                  int(rec["max_new"])))
    return handles
