"""Paged KV-cache pool: block allocator + per-sequence page table.

Serving memory is KV-cache memory. A naive engine sizes every sequence
for the worst case (max prompt + max generation) and admits
``HBM / worst_case`` sequences; vLLM's observation is that paging the
cache in fixed-size blocks and admitting against the *pool* lets the
scheduler pack many more sequences because most finish early or short.
This module is that accounting layer for the continuous-batching engine
(:mod:`serve.engine`).

Design (and its honest scope):

- the pool is ``num_blocks`` blocks of ``block_size`` token slots; a
  sequence admitted with prompt length L and generation budget n
  **reserves** ``ceil((L + n) / block_size)`` blocks up front and holds
  them until it is freed. Reservation-at-admission means a running
  sequence can NEVER hit an out-of-blocks wall mid-decode —
  :meth:`KVPool.extend` only moves the sequence's high-water mark
  inside its own reservation, so there is no eviction/swap path to get
  wrong (the classic continuous-batching deadlock: every running
  sequence needs one more block and none can finish);
- each sequence's reservation is tracked as an explicit **block table**
  (logical block -> physical block id), the structure a true paged
  attention kernel would consume. The current engine stores K/V rows
  slot-contiguously in a dense ``(slots, S_max)`` cache (XLA-friendly;
  no gather in the attention hot loop on CPU/TPU without a custom
  kernel), so the table governs *admission and accounting*, not the
  physical layout — the honest reading is "paged admission control over
  a dense cache". The allocator API is the kernel-ready one so a Pallas
  paged-attention kernel can slot in without scheduler changes;
- utilization lands in the metric registry as gauges
  (``serve_kv_blocks_total`` / ``serve_kv_blocks_reserved`` /
  ``serve_kv_blocks_used``) every time the pool changes, so dashboards
  and :mod:`scripts.obs_report` see cache pressure without polling.

Thread-safety: one lock around every mutation — the scheduler thread
and submitting client threads both touch the pool.
"""

from __future__ import annotations

import threading

from pytorch_distributed_nn_tpu.obs.registry import get_registry


class KVPool:
    """Fixed-size block pool with per-sequence reservations."""

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._lock = threading.Lock()
        self._free: list[int] = list(range(num_blocks - 1, -1, -1))
        # seq_id -> block table (physical block ids, allocation order)
        self._tables: dict[str, list[int]] = {}
        # seq_id -> tokens actually written (high-water mark)
        self._used_tokens: dict[str, int] = {}
        reg = get_registry()
        self._g_total = reg.gauge(
            "serve_kv_blocks_total", "KV pool size in blocks")
        self._g_reserved = reg.gauge(
            "serve_kv_blocks_reserved", "KV blocks reserved by admitted "
            "sequences")
        self._g_used = reg.gauge(
            "serve_kv_blocks_used", "KV blocks backing written tokens")
        self._g_total.set(num_blocks)
        self._publish_locked()

    # -- accounting helpers ------------------------------------------------

    def blocks_for(self, tokens: int) -> int:
        """ceil(tokens / block_size) — the reservation for a sequence
        whose cache will hold at most ``tokens`` rows."""
        return -(-max(int(tokens), 0) // self.block_size)

    def _publish_locked(self) -> None:
        reserved = self.num_blocks - len(self._free)
        used = sum(self.blocks_for(t) for t in self._used_tokens.values())
        self._g_reserved.set(reserved)
        self._g_used.set(used)

    # -- allocator ---------------------------------------------------------

    def can_reserve(self, tokens: int) -> bool:
        with self._lock:
            return self.blocks_for(tokens) <= len(self._free)

    def reserve(self, seq_id: str, tokens: int) -> bool:
        """Reserve blocks for a sequence's worst-case ``tokens`` rows.
        False (and no state change) when the pool can't cover it — the
        scheduler's backpressure signal. A second reserve for a live
        ``seq_id`` is a programming error and raises."""
        n = self.blocks_for(tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already holds a "
                                 f"reservation")
            if n > len(self._free):
                return False
            self._tables[seq_id] = [self._free.pop() for _ in range(n)]
            self._used_tokens[seq_id] = 0
            self._publish_locked()
            return True

    def extend(self, seq_id: str, tokens: int) -> None:
        """Advance a sequence's written-token high-water mark. Never
        fails inside the reservation (the no-mid-decode-wall invariant);
        raises if the engine tries to write past what was reserved —
        that is a scheduler bug, not a capacity condition."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise KeyError(f"sequence {seq_id!r} has no reservation")
            if self.blocks_for(tokens) > len(table):
                raise ValueError(
                    f"sequence {seq_id!r} wrote {tokens} tokens past its "
                    f"{len(table)}-block reservation"
                )
            if tokens > self._used_tokens[seq_id]:
                self._used_tokens[seq_id] = int(tokens)
                self._publish_locked()

    def free(self, seq_id: str) -> int:
        """Return a finished sequence's blocks to the pool; returns the
        block count released. Freeing an unknown id is a no-op (retire
        paths race benignly with cancel paths)."""
        with self._lock:
            table = self._tables.pop(seq_id, None)
            self._used_tokens.pop(seq_id, None)
            if not table:
                return 0
            self._free.extend(reversed(table))
            self._publish_locked()
            return len(table)

    # -- introspection -----------------------------------------------------

    def block_table(self, seq_id: str) -> tuple[int, ...]:
        with self._lock:
            return tuple(self._tables.get(seq_id, ()))

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def live_sequences(self) -> int:
        with self._lock:
            return len(self._tables)

    def utilization(self) -> float:
        """Reserved fraction of the pool, in [0, 1]."""
        with self._lock:
            return (self.num_blocks - len(self._free)) / self.num_blocks
