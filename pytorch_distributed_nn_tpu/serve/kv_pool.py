"""Paged KV-cache pool: block allocator + per-sequence page table.

Serving memory is KV-cache memory. A naive engine sizes every sequence
for the worst case (max prompt + max generation) and admits
``HBM / worst_case`` sequences; vLLM's observation is that paging the
cache in fixed-size blocks and admitting against the *pool* lets the
scheduler pack many more sequences because most finish early or short.
This module is that accounting layer for the continuous-batching engine
(:mod:`serve.engine`).

Design (and its honest scope):

- the pool is ``num_blocks`` blocks of ``block_size`` token slots; a
  sequence admitted with prompt length L and generation budget n
  **reserves** ``ceil((L + n) / block_size)`` blocks up front and holds
  them until it is freed. Reservation-at-admission means a running
  sequence can NEVER hit an out-of-blocks wall mid-decode —
  :meth:`KVPool.extend` only moves the sequence's high-water mark
  inside its own reservation, so there is no eviction/swap path to get
  wrong (the classic continuous-batching deadlock: every running
  sequence needs one more block and none can finish);
- each sequence's reservation is tracked as an explicit **block table**
  (logical block -> physical block id), the structure a true paged
  attention kernel would consume. The current engine stores K/V rows
  slot-contiguously in a dense ``(slots, S_max)`` cache (XLA-friendly;
  no gather in the attention hot loop on CPU/TPU without a custom
  kernel), so the table governs *admission and accounting*, not the
  physical layout — the honest reading is "paged admission control over
  a dense cache". The allocator API is the kernel-ready one so a Pallas
  paged-attention kernel can slot in without scheduler changes;
- **prefix sharing** (the Mosaic tentpole): a block has three lives.
  *Live-exclusive* — inside exactly one sequence's table (the classic
  case above). *Live-shared* — inside several tables at once via
  ``reserve(shared=...)``, refcounted; the blocks return to circulation
  only when the last sharer frees them. *Cached* — refcount-zero blocks
  a retiring sequence donated with ``free(retain=...)`` park in an LRU
  ring instead of the free list, so :mod:`serve.prefix_cache` can hand
  them to a later request that shares the prefix. The free list stays
  the backpressure truth (``free_blocks`` never counts cached blocks);
  the prefix cache sheds cached blocks with :meth:`release_cached` when
  a cold reservation needs them back, honoring :meth:`pin` (a
  copy-on-write tail mid-restore must not vanish under the engine);
- utilization lands in the metric registry as gauges
  (``serve_kv_blocks_total`` / ``serve_kv_blocks_reserved`` /
  ``serve_kv_blocks_used`` / ``serve_kv_blocks_cached``) every time the
  pool changes, so dashboards and :mod:`scripts.obs_report` see cache
  pressure without polling.

Thread-safety: one lock around every mutation — the scheduler thread
and submitting client threads both touch the pool.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Iterable

from pytorch_distributed_nn_tpu.obs import meter
from pytorch_distributed_nn_tpu.obs.registry import get_registry


class KVPool:
    """Fixed-size block pool with per-sequence reservations."""

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._lock = threading.Lock()
        self._free: list[int] = list(range(num_blocks - 1, -1, -1))
        # seq_id -> block table (physical block ids, allocation order)
        self._tables: dict[str, list[int]] = {}
        # seq_id -> tokens actually written (high-water mark)
        self._used_tokens: dict[str, int] = {}
        # phys block -> live sharer count (only blocks entered via
        # reserve(shared=); exclusively-owned blocks have no entry)
        self._ref: dict[int, int] = {}
        # refcount-0 donated blocks, LRU order (oldest first)
        self._cached: OrderedDict[int, None] = OrderedDict()
        # cached blocks the prefix cache is mid-restore on: eviction-proof
        self._pinned: set[int] = set()
        reg = get_registry()
        self._g_total = reg.gauge(
            "serve_kv_blocks_total", "KV pool size in blocks")
        self._g_reserved = reg.gauge(
            "serve_kv_blocks_reserved", "KV blocks reserved by admitted "
            "sequences")
        self._g_used = reg.gauge(
            "serve_kv_blocks_used", "KV blocks backing written tokens")
        self._g_cached = reg.gauge(
            "serve_kv_blocks_cached", "refcount-0 prefix blocks parked "
            "in the cached-LRU ring")
        self._c_branches = reg.counter(
            "serve_branches_total", "n-best decode branches forked off "
            "a primary reservation (COW prompt sharing)")
        self._g_total.set(num_blocks)
        self._publish_locked()

    # -- accounting helpers ------------------------------------------------

    def blocks_for(self, tokens: int) -> int:
        """ceil(tokens / block_size) — the reservation for a sequence
        whose cache will hold at most ``tokens`` rows."""
        return -(-max(int(tokens), 0) // self.block_size)

    def _publish_locked(self) -> None:
        reserved = self.num_blocks - len(self._free) - len(self._cached)
        used = sum(self.blocks_for(t) for t in self._used_tokens.values())
        self._g_reserved.set(reserved)
        self._g_used.set(used)
        self._g_cached.set(len(self._cached))

    # -- allocator ---------------------------------------------------------

    def can_reserve(self, tokens: int) -> bool:
        with self._lock:
            return self.blocks_for(tokens) <= len(self._free)

    def reserve(self, seq_id: str, tokens: int,
                shared: Iterable[int] = ()) -> bool:
        """Reserve blocks for a sequence's worst-case ``tokens`` rows.
        False (and no state change) when the pool can't cover it — the
        scheduler's backpressure signal. A second reserve for a live
        ``seq_id`` is a programming error and raises.

        ``shared`` prepends already-materialized prefix blocks (from
        the cached ring or another live sharer's table) to this
        sequence's block table instead of allocating fresh ones: a
        cached block leaves the ring and becomes live with refcount 1;
        an already-live shared block just gains a sharer. Only the
        remainder ``blocks_for(tokens) - len(shared)`` comes off the
        free list, which is the whole prefix-cache win."""
        shared = list(shared)
        n = self.blocks_for(tokens)
        n_fresh = n - len(shared)
        if n_fresh < 0:
            raise ValueError(
                f"sequence {seq_id!r}: {len(shared)} shared blocks exceed "
                f"the {n}-block reservation for {tokens} tokens")
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already holds a "
                                 f"reservation")
            for b in shared:
                if b not in self._cached and b not in self._ref \
                        and not any(b in t for t in self._tables.values()):
                    raise ValueError(
                        f"shared block {b} is neither cached nor live — "
                        f"the prefix index is stale")
            if n_fresh > len(self._free):
                return False
            for b in shared:
                if b in self._cached:
                    del self._cached[b]
                    self._ref[b] = 1
                else:
                    self._ref[b] = self._ref.get(b, 1) + 1
            table = self._tables[seq_id] = shared + [
                self._free.pop() for _ in range(n_fresh)]
            self._used_tokens[seq_id] = 0
            self._publish_locked()
        # Abacus residency start (outside the lock: the meter has its
        # own; inert one-comparison no-op unless TPUNN_METER armed)
        meter.on_kv_reserve(seq_id, table)
        return True

    def fork(self, parent_id: str, child_id: str, tokens: int, *,
             shared_tokens: int) -> bool:
        """COW-fork a decode branch off a live parent reservation (the
        Prism n-best choke point — exactly one package call site,
        lint-pinned). The parent's *full* blocks covering
        ``shared_tokens`` prompt rows join the child's table by
        reference (refcounted, exactly like a prefix-cache share: an
        exclusively-owned parent block becomes live-shared, an
        already-shared one gains a sharer); only the child's tail —
        the partial prompt block plus its own generated tokens — comes
        off the free list. n branches therefore hold ONE prompt block
        set + n tails, not n full reservations. False (and no state
        change) when the free list can't cover the tail — the
        scheduler's backpressure signal, same as :meth:`reserve`."""
        with self._lock:
            table = self._tables.get(parent_id)
            if table is None:
                raise KeyError(
                    f"fork parent {parent_id!r} has no reservation")
            shared = list(table[:max(int(shared_tokens), 0)
                                // self.block_size])
        if not self.reserve(child_id, tokens, shared=shared):
            return False
        self._c_branches.inc()
        return True

    def extend(self, seq_id: str, tokens: int) -> None:
        """Advance a sequence's written-token high-water mark. Never
        fails inside the reservation (the no-mid-decode-wall invariant);
        raises if the engine tries to write past what was reserved —
        that is a scheduler bug, not a capacity condition."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise KeyError(f"sequence {seq_id!r} has no reservation")
            if self.blocks_for(tokens) > len(table):
                raise ValueError(
                    f"sequence {seq_id!r} wrote {tokens} tokens past its "
                    f"{len(table)}-block reservation"
                )
            if tokens > self._used_tokens[seq_id]:
                self._used_tokens[seq_id] = int(tokens)
                self._publish_locked()

    def free(self, seq_id: str,
             retain: frozenset[int] = frozenset()) -> int:
        """Return a finished sequence's blocks to the pool; returns the
        block count that reached the free list. Freeing an unknown id
        is a no-op (retire paths race benignly with cancel paths).

        Blocks still held by another sharer just drop a refcount and
        stay live. Zero-ref blocks named in ``retain`` park in the
        cached-LRU ring (table order, so the prefix chain ages
        coherently) instead of going free — the donation half of the
        prefix cache."""
        with self._lock:
            table = self._tables.pop(seq_id, None)
            self._used_tokens.pop(seq_id, None)
            if not table:
                return 0
            released = []
            parked = []
            for b in table:
                if b in self._ref:
                    self._ref[b] -= 1
                    if self._ref[b] > 0:
                        continue  # another sharer keeps it live
                    del self._ref[b]
                if b in retain:
                    self._cached[b] = None
                    self._cached.move_to_end(b)
                    parked.append(b)
                else:
                    released.append(b)
            self._free.extend(reversed(released))
            self._publish_locked()
        # Abacus residency end: parked (donated) blocks keep billing
        # the donating tenant from the cached ring
        meter.on_kv_free(seq_id, cached=tuple(parked))
        return len(released)

    # -- cached-LRU ring ---------------------------------------------------

    def is_cached(self, block: int) -> bool:
        with self._lock:
            return block in self._cached

    def refcount(self, block: int) -> int:
        """Live sharer count for a shared block (0: cached, free, or
        exclusively owned)."""
        with self._lock:
            return self._ref.get(block, 0)

    def cached_lru(self) -> list[int]:
        """Cached blocks, least-recently-touched first — the prefix
        cache's eviction scan order."""
        with self._lock:
            return list(self._cached)

    def touch_cached(self, block: int) -> None:
        """Refresh a cached block's recency (a peek/partial match that
        did not promote it to live still proves it is useful)."""
        with self._lock:
            if block in self._cached:
                self._cached.move_to_end(block)

    def pin(self, block: int) -> None:
        """Make a cached block eviction-proof while the engine copies
        its rows (the COW-tail restore window)."""
        with self._lock:
            self._pinned.add(block)

    def unpin(self, block: int) -> None:
        with self._lock:
            self._pinned.discard(block)

    def adopt_cached(self) -> int | None:
        """Pop one free block and park it directly in the cached-LRU
        ring (most-recent end), returning its id — the receiving side
        of KV block streaming (:mod:`serve.disagg`): a peer's prefix
        block lands here already materialized, never owned by a live
        sequence on this replica, and is handed out later exactly like
        a locally-donated block (``reserve(shared=...)``). None — and
        no state change — when the free list is empty: streamed warmth
        must never displace live reservations' headroom."""
        with self._lock:
            if not self._free:
                return None
            b = self._free.pop()
            self._cached[b] = None
            self._cached.move_to_end(b)
            self._publish_locked()
        meter.on_kv_adopt(b)
        return b

    def release_cached(self, block: int) -> bool:
        """Evict one cached block to the free list. False — and no
        state change — when the block is pinned or not cached (already
        evicted, or promoted to live by a sharer in between): the
        prefix cache's eviction scan treats False as "pick another"."""
        with self._lock:
            if block in self._pinned or block not in self._cached:
                return False
            del self._cached[block]
            self._free.append(block)
            self._publish_locked()
        meter.on_kv_evict(block)
        return True

    # -- introspection -----------------------------------------------------

    def block_table(self, seq_id: str) -> tuple[int, ...]:
        with self._lock:
            return tuple(self._tables.get(seq_id, ()))

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def cached_blocks(self) -> int:
        with self._lock:
            return len(self._cached)

    @property
    def live_sequences(self) -> int:
        with self._lock:
            return len(self._tables)

    def utilization(self) -> float:
        """Live-reserved fraction of the pool, in [0, 1]. Cached blocks
        are reclaimable, so they count as headroom here even though
        they are off the free list."""
        with self._lock:
            return (self.num_blocks - len(self._free)
                    - len(self._cached)) / self.num_blocks
