"""Helm: SLO burn-rate autoscaler — the watchtower → fleet closed loop.

PR 11 (Skyline) answered "how many replicas does this traffic need?"
offline; the watchtower pages when the error budget burns. Helm closes
the loop: a control policy that grows and shrinks the
:class:`serve.fleet.Fleet` replica set from signals the stack already
emits — no new transport, no new probes:

- **scale up** when any SLO's *fast-window* burn rate
  (:meth:`obs.watchtower.Watchtower.burn_rates`, the very windows the
  pager reads) crosses ``burn_up``, or queue depth / KV headroom
  (:func:`serve.router.fleet_pressure`, the router's own gauges)
  shows sustained pressure — the goal is to act *before* the
  multi-window page would fire;
- **scale down** only on sustained multi-window headroom: every burn
  under ``burn_down`` on BOTH windows, queue near-empty, KV free —
  and never below the Skyline forecast (``plan_capacity``'s
  ``replicas_needed``), so the steady state converges to the offline
  answer instead of oscillating around it;
- **no flapping**: consecutive-evaluation streaks (``up_consecutive``
  / ``down_consecutive``), per-direction cooldowns, and min/max
  bounds. A chaos blip or a flash-crowd edge moves a streak counter,
  not the fleet.

Every decision — including every *hold* — is explainable: a
:class:`Decision` journals the full evidence snapshot (per-SLO
fast/slow burns, fleet queue/KV fractions, ready count, forecast,
pre-decision hysteresis state, the spec that parameterized the
policy) plus the action and a named reason. The journal is the
byte-identical-replay unit (``as_json()`` is canonical, event-time
only — no wall clock), so ``scripts/obs_watch.py --autoscale`` can
shadow-replay a recorded run through :func:`decide` offline and diff
what Helm *would* have done against what it did.

Design contracts (lint-enforced by tests/test_quality.py):

- **inert when unset** — every module-level ``on_*`` hook opens with a
  literal ``if _helm is None: return``; an unarmed autoscaler performs
  zero registry or flight-ring writes (the chaos/watch/xray
  precedent), and instruments register lazily on the first decision;
- **emit-first** — :meth:`Autoscaler._emit`'s first statement is the
  flight-ring record, so a post-mortem can never miss the decision
  that preceded a crash.

Env contract: ``TPUNN_AUTOSCALE=1`` arms the defaults;
``TPUNN_AUTOSCALE=max_replicas=6:burn_up=1.5`` overrides
:class:`AutoscaleConfig` fields (``:``-separated ``key=value``; a
typo'd key fails loudly, never silently scales nothing). Validation:
``bench.py --autoscale`` (live fleet) and
``bench.py --autoscale --selftest`` (simulated fleet, tier-1).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Optional

from pytorch_distributed_nn_tpu.obs import flight, watchtower
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.serve import router as _router

log = logging.getLogger(__name__)

ENV_AUTOSCALE = "TPUNN_AUTOSCALE"

SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"
ACTIONS = (SCALE_UP, SCALE_DOWN, HOLD)

# the pool a pool-less decision governs: the decode pool IS the
# unified pool (pre-disagg journals replay unchanged — an absent
# "pool" field means decode)
DEFAULT_POOL = "decode"


@dataclasses.dataclass
class AutoscaleConfig:
    """Control-policy knobs; every field is overridable through the
    ``TPUNN_AUTOSCALE`` spec (see :func:`parse_spec`)."""

    min_replicas: int = 1
    max_replicas: int = 8
    # pressure lines (scale-up triggers; any one of them counts)
    burn_up: float = 1.0           # fast-window burn to call pressure
    queue_up: float = 0.5          # fleet queue_depth/max_queue
    kv_up: float = 0.1             # fleet free/total KV at-or-under
    # headroom lines (scale-down gates; ALL must hold)
    burn_down: float = 0.5         # both windows at-or-under
    queue_down: float = 0.1
    kv_down: float = 0.5
    # hysteresis: consecutive evaluations before acting
    up_consecutive: int = 2
    down_consecutive: int = 5
    # step sizes and cooldowns
    up_step: int = 1
    down_step: int = 1
    cooldown_up_s: float = 5.0     # between consecutive scale-ups
    cooldown_down_s: float = 30.0  # after ANY change before shrinking
    # evaluation cadence (maybe_evaluate debounce, event time)
    eval_interval_s: float = 1.0


_FIELD_TYPES = {f.name: f.type
                for f in dataclasses.fields(AutoscaleConfig)}


def parse_spec(spec: str) -> AutoscaleConfig:
    """``TPUNN_AUTOSCALE`` spec → :class:`AutoscaleConfig`. ``"1"`` /
    ``"on"`` mean defaults; otherwise ``:``-separated ``key=value``
    overrides. Unknown keys raise (a typo'd autoscale spec must fail
    loudly, not silently hold the fleet flat — the chaos-spec
    contract)."""
    cfg = AutoscaleConfig()
    spec = (spec or "").strip()
    if spec in ("", "1", "on", "true"):
        return cfg
    for field in filter(None, spec.split(":")):
        key, eq, value = field.partition("=")
        key = key.strip()
        if not eq or key not in _FIELD_TYPES:
            raise ValueError(
                f"unknown autoscale key {key!r} in {spec!r}; have "
                f"{sorted(_FIELD_TYPES)}")
        try:
            kind = _FIELD_TYPES[key]
            setattr(cfg, key,
                    int(value) if kind in (int, "int") else float(value))
        except ValueError:
            raise ValueError(f"bad value for autoscale key {key!r}: "
                             f"{value!r}") from None
    if cfg.min_replicas < 1:
        raise ValueError(
            f"autoscale min_replicas must be >= 1, got "
            f"{cfg.min_replicas}")
    if cfg.max_replicas < cfg.min_replicas:
        raise ValueError(
            f"autoscale max_replicas ({cfg.max_replicas}) < "
            f"min_replicas ({cfg.min_replicas})")
    return cfg


@dataclasses.dataclass
class Decision:
    """One journaled control decision. ``evidence`` is the complete
    input snapshot, ``state`` the PRE-decision hysteresis state, and
    ``spec`` the policy parameterization — together they make the
    record self-contained: :func:`replay_decision` re-derives
    ``action``/``reason``/``to_replicas`` from the record alone."""

    seq: int
    t: float                # event time (trace-relative; never wall)
    action: str             # SCALE_UP | SCALE_DOWN | HOLD
    reason: str             # named cause ("burn:ttft+queue", "at_max")
    from_replicas: int      # READY count when evaluated
    to_replicas: int        # size intent after this decision
    evidence: dict
    state: dict
    spec: str
    # which coordinator life wrote the record: a recovered coordinator
    # CONTINUES the journal (seq keeps counting, state chains) rather
    # than forking it, and this field marks where the boundary fell
    coordinator_incarnation: int = 0
    # which replica pool this decision sizes: "decode" (the unified
    # pool's name — legacy journals replay unchanged) or "prefill" on
    # a disaggregated process fleet (Breakwater). Hysteresis state
    # chains per pool; seq stays contiguous across pools.
    pool: str = DEFAULT_POOL

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def as_json(self) -> str:
        """Canonical serialization — the byte-identical-replay unit."""
        return json.dumps(self.as_dict(), sort_keys=True)


def decide(cfg: AutoscaleConfig, evidence: dict, state: dict,
           t: float) -> tuple:
    """The pure policy core: ``(evidence, state, t)`` →
    ``(action, reason, to_replicas, new_state)``. No clocks, no I/O,
    no globals — live control, the Skyline simulation, and the
    ``obs_watch --autoscale`` shadow replay all run exactly this.

    ``state`` carries the hysteresis memory: ``up_streak`` /
    ``down_streak`` (consecutive pressure/headroom evaluations) and
    ``last_up_t`` / ``last_change_t`` (cooldown anchors, event
    time)."""
    target = int(evidence["target"])
    burns = evidence.get("burn", {})
    queue_frac = float(evidence.get("queue_frac", 0.0))
    kv_free = float(evidence.get("kv_free_frac", 1.0))

    pressure = []
    for slo in sorted(burns):
        if float(burns[slo]["fast"]) >= cfg.burn_up:
            pressure.append(f"burn:{slo}")
    if queue_frac >= cfg.queue_up:
        pressure.append("queue")
    if kv_free <= cfg.kv_up and int(evidence.get("ready", 0)) > 0:
        pressure.append("kv")
    headroom = (not pressure
                and all(float(b["fast"]) <= cfg.burn_down
                        and float(b["slow"]) <= cfg.burn_down
                        for b in burns.values())
                and queue_frac <= cfg.queue_down
                and kv_free >= cfg.kv_down)

    new_state = dict(state)
    new_state["up_streak"] = state.get("up_streak", 0) + 1 \
        if pressure else 0
    new_state["down_streak"] = state.get("down_streak", 0) + 1 \
        if headroom else 0

    last_up = state.get("last_up_t")
    last_change = state.get("last_change_t")
    action, reason, to = HOLD, "steady", target
    if pressure:
        if target >= cfg.max_replicas:
            reason = "at_max"
        elif new_state["up_streak"] < cfg.up_consecutive:
            reason = "pressure_building"
        elif last_up is not None and t - last_up < cfg.cooldown_up_s:
            reason = "cooldown_up"
        else:
            action = SCALE_UP
            reason = "+".join(pressure)
            to = min(target + cfg.up_step, cfg.max_replicas)
            new_state["last_up_t"] = t
            new_state["last_change_t"] = t
            new_state["up_streak"] = 0
    elif headroom:
        forecast = evidence.get("forecast_replicas")
        floor = max(cfg.min_replicas, int(forecast or 0))
        if target <= floor:
            reason = "at_floor"
        elif new_state["down_streak"] < cfg.down_consecutive:
            reason = "headroom_building"
        elif (last_change is not None
                and t - last_change < cfg.cooldown_down_s):
            reason = "cooldown_down"
        else:
            action = SCALE_DOWN
            reason = "headroom"
            to = max(target - cfg.down_step, floor)
            new_state["last_change_t"] = t
            new_state["down_streak"] = 0
    return action, reason, to, new_state


def replay_decision(rec: dict) -> tuple:
    """Re-run one journaled ``autoscale_decision`` record through
    :func:`decide`, purely from its own evidence/pre-state/spec —
    the shadow-replay unit ``scripts/obs_watch.py --autoscale`` diffs
    against what the journal says Helm actually did. Returns
    ``(action, reason, to_replicas)``."""
    cfg = parse_spec(rec.get("spec", ""))
    action, reason, to, _ = decide(
        cfg, rec["evidence"], rec["state"], float(rec["t"]))
    return action, reason, int(to)


def _fresh_state() -> dict:
    return {"up_streak": 0, "down_streak": 0,
            "last_up_t": None, "last_change_t": None}


class Autoscaler:
    """The decision engine: tracks pressure evidence, consults the
    watchtower's burn windows, runs :func:`decide` on a debounced
    cadence, and journals/emits every outcome. Deliberately fleet-
    agnostic — :class:`FleetAutoscaler` binds it to a live fleet,
    :class:`SimController` to the Skyline discrete-event model.

    ``feed_tower=True`` forwards every observed event into the
    attached tower (simulation: the Autoscaler owns a private
    Watchtower). Live, the global tower is fed by its own hooks and
    Helm only *reads* its burn windows — never double-feed one."""

    def __init__(self, config: Optional[AutoscaleConfig] = None, *,
                 tower=None, feed_tower: bool = False,
                 forecast_replicas: Optional[int] = None,
                 metrics=None, spec: str = "") -> None:
        self.cfg = config or AutoscaleConfig()
        self.spec = spec
        self.metrics = metrics
        self.forecast_replicas = forecast_replicas
        self._tower = tower
        self._feed_tower = feed_tower
        self.decisions: list[Decision] = []
        self.state = _fresh_state()
        # journal-continuity anchors: a recovered coordinator resumes
        # seq numbering past the persisted journal (resume_from) and
        # stamps its own incarnation into every new record
        self.seq_offset = 0
        self.coordinator_incarnation = 0
        self._last_eval_t: Optional[float] = None
        self._queue_frac = 0.0
        self._kv_free_frac = 1.0
        # non-default pools (disagg prefill): each carries its own
        # hysteresis state, debounce anchor, and pressure sample; the
        # attributes above remain the DEFAULT_POOL's (back-compat)
        self._pool_states: dict[str, dict] = {}
        self._pool_eval_t: dict[str, float] = {}
        self._pool_pressure: dict[str, tuple] = {}
        # instruments register lazily on the first decision so an
        # armed-but-idle Helm leaves the registry untouched
        self._g_target = None
        self._g_ready = None
        self._g_burn = None
        self._c_decisions = None

    # -- evidence intake ---------------------------------------------------

    def observe(self, ev: dict) -> None:
        """Watchtower-shaped event intake: ``serve_round`` events
        update the instantaneous queue/KV fractions; with
        ``feed_tower`` every event also drives the attached tower's
        burn windows (the simulation path)."""
        if self._feed_tower and self._tower is not None:
            self._tower.observe(ev)
        if ev.get("ev") == "serve_round" and ev.get("queue_max"):
            self._queue_frac = (float(ev["queue_depth"])
                                / float(ev["queue_max"]))
            if ev.get("kv_total"):
                self._kv_free_frac = (float(ev["kv_free"])
                                      / float(ev["kv_total"]))

    def set_pressure(self, *, queue_frac: float, kv_free_frac: float,
                     pool: str = DEFAULT_POOL) -> None:
        """Authoritative fleet-wide pressure (from
        :func:`serve.router.fleet_pressure`) — overrides the last
        single-replica ``serve_round`` sample. ``pool=`` scopes the
        sample to one disaggregated pool's evidence stream."""
        if pool == DEFAULT_POOL:
            self._queue_frac = float(queue_frac)
            self._kv_free_frac = float(kv_free_frac)
        else:
            self._pool_pressure[pool] = (float(queue_frac),
                                         float(kv_free_frac))

    def _state_for(self, pool: str) -> dict:
        if pool == DEFAULT_POOL:
            return self.state
        return self._pool_states.setdefault(pool, _fresh_state())

    def _set_state_for(self, pool: str, state: dict) -> None:
        if pool == DEFAULT_POOL:
            self.state = state
        else:
            self._pool_states[pool] = state

    # -- evaluation --------------------------------------------------------

    def maybe_evaluate(self, t: float, *, ready: int, target: int,
                       pool: str = DEFAULT_POOL) -> Optional[Decision]:
        """Debounced :meth:`evaluate` — at most one decision per
        ``eval_interval_s`` of *event* time *per pool*. Returns None
        between evaluations."""
        last = (self._last_eval_t if pool == DEFAULT_POOL
                else self._pool_eval_t.get(pool))
        if last is not None and t - last < self.cfg.eval_interval_s:
            return None
        if pool == DEFAULT_POOL:
            self._last_eval_t = t
        else:
            self._pool_eval_t[pool] = t
        return self.evaluate(t, ready=ready, target=target, pool=pool)

    def evaluate(self, t: float, *, ready: int, target: int,
                 pool: str = DEFAULT_POOL) -> Decision:
        """Snapshot the evidence, run :func:`decide`, journal and emit
        the outcome. The journaled ``state`` is the PRE-decision
        hysteresis state so the record replays standalone; on a
        disaggregated fleet each pool chains its own state while seq
        stays contiguous across pools (one journal, interleaved)."""
        burn = (self._tower.burn_rates(t)
                if self._tower is not None else {})
        if pool == DEFAULT_POOL:
            queue_frac, kv_free_frac = self._queue_frac, \
                self._kv_free_frac
        else:
            queue_frac, kv_free_frac = self._pool_pressure.get(
                pool, (0.0, 1.0))
        evidence = {
            "burn": burn,
            "queue_frac": round(queue_frac, 6),
            "kv_free_frac": round(kv_free_frac, 6),
            "ready": int(ready),
            "target": int(target),
            "forecast_replicas": self.forecast_replicas,
        }
        state = self._state_for(pool)
        pre_state = dict(state)
        action, reason, to, new_state = decide(
            self.cfg, evidence, state, t)
        self._set_state_for(pool, new_state)
        d = Decision(
            seq=self.seq_offset + len(self.decisions),
            t=round(float(t), 6),
            action=action, reason=reason, from_replicas=int(ready),
            to_replicas=int(to), evidence=evidence, state=pre_state,
            spec=self.spec,
            coordinator_incarnation=self.coordinator_incarnation,
            pool=pool)
        self.decisions.append(d)
        self._emit(d)
        return d

    def resume_from(self, records: list) -> None:
        """Continue a persisted decision journal instead of forking it.

        ``records`` are the parsed ``autoscale_decision`` dicts a prior
        coordinator journaled (same shape :func:`replay_decision`
        takes). The journaled ``state`` is PRE-decision, so the resumed
        hysteresis state is re-derived by running the last record back
        through :func:`decide` — exactly the post-state an
        uninterrupted Autoscaler would carry. Sequence numbers continue
        from the journal's tail and the debounce anchor is the last
        journaled event time, so the concatenated journal (old lines +
        new lines) is indistinguishable from one life's: seq contiguous
        and every record's ``state`` equal to its predecessor's
        post-state across the restart boundary."""
        if not records:
            return
        by_pool: dict[str, dict] = {}
        for rec in records:  # last record per pool wins
            by_pool[rec.get("pool", DEFAULT_POOL)] = rec
        for pool, last in by_pool.items():
            cfg = parse_spec(last.get("spec", ""))
            _, _, _, post = decide(cfg, last["evidence"],
                                   last["state"], float(last["t"]))
            self._set_state_for(pool, post)
            if pool == DEFAULT_POOL:
                self._last_eval_t = float(last["t"])
            else:
                self._pool_eval_t[pool] = float(last["t"])
        self.seq_offset = max(int(r["seq"]) for r in records) + 1

    def _emit(self, d: Decision) -> None:
        """Every decision lands in the flight ring FIRST (lint-
        enforced: a crash right after a scaling action must still show
        the decision post-mortem), then the lazily-registered metrics
        and the JSONL stream."""
        flight.record("autoscale", d.action,
                      note=f"{d.reason} ready={d.from_replicas} "
                           f"target={d.evidence['target']}"
                           f"->{d.to_replicas}")
        self._ensure_instruments()
        self._g_target.set(float(d.to_replicas))
        self._g_ready.set(float(d.from_replicas))
        self._c_decisions.inc(action=d.action, reason=d.reason)
        for slo in sorted(d.evidence.get("burn", {})):
            b = d.evidence["burn"][slo]
            self._g_burn.set(float(b["fast"]), slo=slo, window="fast")
            self._g_burn.set(float(b["slow"]), slo=slo, window="slow")
        if self.metrics is not None:
            self.metrics.emit("autoscale_decision", **d.as_dict())
        if d.action != HOLD:
            log.info("helm %s -> %d replicas (%s)", d.action,
                     d.to_replicas, d.reason)

    def _ensure_instruments(self) -> None:
        if self._g_target is not None:
            return
        reg = get_registry()
        self._g_target = reg.gauge(
            "autoscale_replicas_target",
            "helm size intent (last decision's to_replicas)")
        self._g_ready = reg.gauge(
            "autoscale_replicas_ready",
            "READY replicas at the last helm evaluation")
        self._c_decisions = reg.counter(
            "autoscale_decisions_total", "helm decisions by outcome",
            labels=("action", "reason"))
        self._g_burn = reg.gauge(
            "autoscale_burn_input",
            "per-SLO burn rates helm last decided on",
            labels=("slo", "window"))

    # -- introspection -----------------------------------------------------

    def journal_jsonl(self) -> str:
        """The full decision journal, one canonical JSON per line —
        the unit the determinism tests diff byte-for-byte."""
        return "\n".join(d.as_json() for d in self.decisions)

    def summary(self) -> dict:
        by_action: dict[str, int] = {}
        for d in self.decisions:
            by_action[d.action] = by_action.get(d.action, 0) + 1
        return {
            "decisions": len(self.decisions),
            "by_action": by_action,
            "target": (self.decisions[-1].to_replicas
                       if self.decisions else None),
            "forecast_replicas": self.forecast_replicas,
        }


class SimController:
    """Adapter between :func:`obs.capacity.simulate_autoscaled_fleet`
    and an :class:`Autoscaler`. Duck-typed on the capacity side
    (``feed`` / ``desired``) so :mod:`obs.capacity` never imports this
    module — the obs package reaches serve code lazily only."""

    def __init__(self, scaler: Autoscaler, *, target: int) -> None:
        self.scaler = scaler
        self.target = int(target)

    def feed(self, ev: dict) -> None:
        self.scaler.observe(ev)

    def desired(self, t: float, ready: int, *,
                queue_frac: float = 0.0,
                kv_free_frac: float = 1.0) -> Optional[int]:
        """One control tick at sim time ``t`` with the service model's
        own pressure fractions; returns the new replica target when
        the policy acts, None on hold/debounce."""
        self.scaler.set_pressure(queue_frac=queue_frac,
                                 kv_free_frac=kv_free_frac)
        d = self.scaler.maybe_evaluate(t, ready=int(ready),
                                       target=self.target)
        if d is not None and d.action != HOLD:
            self.target = d.to_replicas
            return d.to_replicas
        return None


class FleetAutoscaler:
    """Helm bound to a live :class:`serve.fleet.Fleet`: each
    :meth:`step` refreshes fleet-wide pressure from the router's own
    gauges, consults the watchtower's burn windows, and applies any
    resulting decision through :meth:`Fleet.scale_to`. Drive it from
    the thread that owns the fleet (bench's replay tick, a serving
    front-end's poll loop) — never from a replica worker, which must
    not take the fleet lock."""

    def __init__(self, fleet, scaler: Autoscaler) -> None:
        self.fleet = fleet
        self.scaler = scaler

    def step(self, now: Optional[float] = None) -> Optional[Decision]:
        """One control tick; returns the decision (None when
        debounced). ``now`` defaults to wall time for live use; pass
        trace-relative time for deterministic replays. On a
        disaggregated fleet this is the first of :meth:`step_all`'s
        per-pool decisions — callers that journal every decision
        should use :meth:`step_all`."""
        decisions = self.step_all(now)
        return decisions[0] if decisions else None

    def step_all(self, now: Optional[float] = None) -> list:
        """One control tick across every scalable pool; returns the
        decisions made (empty when every pool debounced).

        Fleets that expose ``scalable_pools()`` (the disaggregated
        process fleet) get one decision per pool — each from that
        pool's own :func:`serve.router.fleet_pressure` evidence and
        hysteresis chain, applied through
        ``scale_to(n, reason=, pool=)`` (the Breakwater satellite:
        prefill queue-depth pressure grows the prefill pool). Fleets
        without pools keep the legacy single-target path unchanged."""
        t = time.time() if now is None else now
        pools_fn = getattr(self.fleet, "scalable_pools", None)
        pools = list(pools_fn()) if pools_fn is not None else []
        if not pools:
            pressure = _router.fleet_pressure(self.fleet.replicas)
            self.scaler.set_pressure(
                queue_frac=pressure["queue_frac"],
                kv_free_frac=pressure["kv_free_frac"])
            d = self.scaler.maybe_evaluate(
                t, ready=pressure["ready"],
                target=self.fleet.target_replicas)
            if d is not None and d.action != HOLD:
                self.fleet.scale_to(d.to_replicas, reason=d.reason)
            return [d] if d is not None else []
        decisions = []
        for pool in pools:
            pressure = _router.fleet_pressure(self.fleet.replicas,
                                              role=pool)
            self.scaler.set_pressure(
                queue_frac=pressure["queue_frac"],
                kv_free_frac=pressure["kv_free_frac"], pool=pool)
            d = self.scaler.maybe_evaluate(
                t, ready=pressure["ready"],
                target=self.fleet.pool_target(pool), pool=pool)
            if d is None:
                continue
            if d.action != HOLD:
                self.fleet.scale_to(d.to_replicas, reason=d.reason,
                                    pool=pool)
            decisions.append(d)
        return decisions


# -- process-global arming (mirrors obs.watchtower / runtime.chaos) --------

_helm: Optional[FleetAutoscaler] = None


def maybe_init(spec: Optional[str] = None, *, fleet=None,
               forecast_replicas: Optional[int] = None,
               metrics=None) -> bool:
    """Arm Helm for this process when ``TPUNN_AUTOSCALE`` (or an
    explicit ``spec``) says so AND a fleet is provided to act on.
    The burn-rate source is the process-global watchtower when armed
    (Helm reads its windows; it never feeds them — the watchtower's
    own hooks do). Returns True when armed."""
    global _helm
    raw = spec if spec is not None else os.environ.get(ENV_AUTOSCALE, "")
    raw = (raw or "").strip()
    # "0"/"off"/"false" = explicitly disarmed (the TPUNN_* convention)
    if raw in ("", "0", "off", "false") or fleet is None:
        return False
    cfg = parse_spec(raw)
    tower = watchtower.tower() if watchtower.enabled() else None
    scaler = Autoscaler(cfg, tower=tower, feed_tower=False,
                        forecast_replicas=forecast_replicas,
                        metrics=metrics, spec=raw)
    _helm = FleetAutoscaler(fleet, scaler)
    log.info("helm armed: %s", raw)
    return True


def enabled() -> bool:
    return _helm is not None


def helm() -> Optional[FleetAutoscaler]:
    return _helm


def reset() -> None:
    """Disarm (tests)."""
    global _helm
    _helm = None


def on_serve_round(round_: int, wall_s: float, *, queue_depth: int,
                   queue_max: int, kv_free: int, kv_total: int) -> None:
    """Serving-engine per-round hook (instantaneous queue/KV evidence
    between control ticks). Called from ``ServingEngine.step`` right
    after the watchtower's hook — never from the ``_decode_round``
    hot loop."""
    if _helm is None:
        return
    _helm.scaler.observe({"ev": "serve_round", "t": time.time(),
                          "round": int(round_),
                          "wall_s": float(wall_s),
                          "queue_depth": int(queue_depth),
                          "queue_max": int(queue_max),
                          "kv_free": int(kv_free),
                          "kv_total": int(kv_total)})
