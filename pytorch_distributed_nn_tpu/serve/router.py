"""Fleet request router: admit once, place on the best replica.

The fleet's policy half (the supervisor half is :mod:`serve.fleet`):
given the live replica set, pick where one request should run. The
router never talks to a replica — it only *scores* them from the
health/state the fleet maintains and the queue/KV gauges each replica's
scheduler and pool already expose, and returns the chosen handle. The
fleet then performs the actual (single) admission on that replica's
scheduler, so a request is admitted exactly once fleet-wide; the
replica's own bounded queue and KV reservation-at-admission stay the
real backpressure.

Placement score, higher is better::

    score = kv_headroom_frac - queue_frac + prefix_affinity

- ``kv_headroom_frac`` — the replica's free KV blocks *after* this
  request's worst-case reservation (``ceil((L + max_new) / block)``),
  as a fraction of its pool. A replica that cannot reserve the blocks
  scores negative and is only chosen when every ready replica is in
  the same state (the request then queues there, FIFO);
- ``queue_frac`` — waiting requests over ``max_queue``: deep queues
  repel new work even when KV is free (TTFT lives in the queue);
- ``prefix_affinity`` — when the caller passes the ``prompt``, the
  fraction of its tokens already resident in the replica's prefix
  cache (``PrefixCache.peek`` — read-only, no counters, no LRU touch).
  A replica holding a tenant's system-prompt blocks beats an equally
  idle cold one: the hit saves real prefill FLOPs and KV blocks, worth
  more than a few percent of raw headroom. Weight 1.0: a full-prompt
  hit outbids any headroom gap < 100% of a pool.

Only ``READY`` replicas are candidates: ``starting``/``reloading``
replicas are warming, ``draining`` replicas are being rolled, ``dead``
replicas are the failover path's business. Ties break on the lowest
replica index, so placement is deterministic for a given fleet state.

Two-stage placement (disaggregated fleets, :mod:`serve.disagg`): when
the caller passes ``stage="prefill"`` or ``stage="decode"``, only
replicas of that role are candidates and the score specializes to the
stage's bottleneck — prefill is compute-bound, so
:meth:`Router._score_prefill` is pure queue depth (shallowest queue
reaches the prefill GEMMs first); decode is KV/bandwidth-bound, so
:meth:`Router._score_decode` is headroom-after-reservation plus the
prefix-affinity term (a decode replica already holding the streamed
prompt blocks skips the restore transfer entirely). ``stage=None``
keeps the unified single-pool behavior above.

Design contract (lint-enforced by tests/test_quality.py, mirroring the
scheduler's ``_transition``): EVERY placement decision goes through
:meth:`Router.place`, which bumps the
``serve_router_placements_total{outcome}`` counter — no caller can
pick a replica off the books — and the scoring helpers (``_score``,
``_score_prefill``, ``_score_decode``) are called from nowhere else.
"""

from __future__ import annotations

from pytorch_distributed_nn_tpu.obs.registry import get_registry

# replica lifecycle (the fleet's _set_state is the only writer —
# lint-enforced, see tests/test_quality.py). QUARANTINED is Lighthouse's
# isolation state (obs/audit.py): a confirmed output-diverging replica
# is excluded from placement like DEAD but never restarted — its
# process may still be healthy by every liveness signal, which is
# exactly why it must not serve.
STARTING = "starting"
READY = "ready"
DRAINING = "draining"
RELOADING = "reloading"
DEAD = "dead"
QUARANTINED = "quarantined"

REPLICA_STATES = (STARTING, READY, DRAINING, RELOADING, DEAD,
                  QUARANTINED)


def fleet_pressure(replicas, *, role: str | None = None) -> dict:
    """Aggregate placement pressure over the READY replica set — the
    Helm autoscaler's queue/KV evidence (:mod:`serve.autoscale`).

    Returns ``{"queue_frac", "kv_free_frac", "ready"}`` where the
    fractions are fleet-wide (summed depths over summed capacities),
    not per-replica averages: one drowning replica in a fleet of idle
    ones is real headroom for the router, and the aggregate reflects
    that. ``role=`` narrows the aggregate to one disaggregated pool
    (``"prefill"`` / ``"decode"``) so Helm can scale each pool on its
    own pressure; ``None`` keeps the fleet-wide view. Reads the same
    scheduler/pool gauges :meth:`Router._score` does, but computes the
    raw fractions directly — it is evidence for the decision journal,
    not a placement decision, so it stays outside the ``place``-only
    scoring choke point."""
    queue_depth = queue_cap = 0
    kv_free = kv_total = 0
    ready = 0
    for handle in replicas:
        if handle.state != READY:
            continue
        if role is not None \
                and getattr(handle, "role", "unified") != role:
            continue
        ready += 1
        sched = handle.engine.scheduler
        pool = sched.pool
        queue_depth += sched.queue_depth
        queue_cap += sched.max_queue
        kv_free += pool.free_blocks
        kv_total += pool.num_blocks
    return {
        "queue_frac": queue_depth / max(queue_cap, 1),
        "kv_free_frac": kv_free / max(kv_total, 1) if ready else 0.0,
        "ready": ready,
    }


class Router:
    """Scores replicas and picks one; one counted choke point."""

    def __init__(self) -> None:
        reg = get_registry()
        self._c_placements = reg.counter(
            "serve_router_placements_total",
            "router placement decisions", labels=("outcome",))

    @staticmethod
    def _kv_need(pool, total_tokens: int, branches: int,
                 prompt_tokens: int) -> int:
        """Worst-case KV blocks for one request, COW-aware: a best-of-n
        request reserves the prompt's blocks ONCE (branches share them
        via ``KVPool.fork``) plus ``branches`` divergent tails — the
        same arithmetic the bench accounting line proves, so placement
        never overcharges n-way requests by ``n×`` the prompt."""
        bs = pool.block_size
        need = -(-int(total_tokens) // bs)
        if branches > 1:
            shared = max(int(prompt_tokens), 0) // bs
            need += (branches - 1) * max(need - shared, 0)
        return need

    def _score(self, handle, total_tokens: int, branches: int = 1,
               prompt_tokens: int = 0) -> float:
        """Higher is better; negative means the replica cannot reserve
        this request's KV budget right now (it would queue)."""
        pool = handle.engine.scheduler.pool
        sched = handle.engine.scheduler
        need = self._kv_need(pool, total_tokens, branches, prompt_tokens)
        headroom = (pool.free_blocks - need) / max(pool.num_blocks, 1)
        queue_frac = sched.queue_depth / max(sched.max_queue, 1)
        return headroom - queue_frac

    def _score_prefill(self, handle) -> float:
        """Prefill-stage score: pure queue depth. Prefill is
        compute-bound — the leg runs one prompt-sized GEMM batch and
        retires, so KV residency is transient and the only thing that
        moves TTFT is how many requests are already waiting for the
        prefill slots."""
        sched = handle.engine.scheduler
        return -sched.queue_depth / max(sched.max_queue, 1)

    def _score_decode(self, handle, total_tokens: int,
                      branches: int = 1,
                      prompt_tokens: int = 0) -> float:
        """Decode-stage score: KV headroom after this request's
        worst-case reservation. Decode is bandwidth/KV-bound — the leg
        holds its blocks for the whole emission — so free blocks after
        reservation is the real capacity signal; the queue term stays
        as the tiebreak pressure and ``place`` adds prefix affinity on
        top (a replica already holding the streamed blocks wins)."""
        pool = handle.engine.scheduler.pool
        sched = handle.engine.scheduler
        need = self._kv_need(pool, total_tokens, branches, prompt_tokens)
        headroom = (pool.free_blocks - need) / max(pool.num_blocks, 1)
        queue_frac = sched.queue_depth / max(sched.max_queue, 1)
        return headroom - queue_frac

    def place(self, replicas, total_tokens: int, *, prompt=None,
              adapter: int = 0, stage: str | None = None,
              branches: int = 1):
        """Pick the best READY replica for a request of
        ``total_tokens`` worst-case KV footprint; None when no replica
        is ready (the fleet rejects the request as ``no_replica``).
        ``prompt`` (optional token array) turns on prefix affinity:
        replicas whose prefix cache already holds a chunk of the
        prompt (for this ``adapter``) score higher. ``stage`` narrows
        candidates to one disaggregated pool (``"prefill"`` /
        ``"decode"``) and switches to that stage's scoring; prefill
        placement ignores affinity (the leg is one shot — queue depth
        dominates). ``branches`` (best-of-n requests) charges the COW
        footprint: one prompt + n tails, never n full sequences.

        THE placement choke point: every decision — including the
        failure to make one — lands in
        ``serve_router_placements_total{outcome}``."""
        best = None
        best_score = 0.0
        prompt_tokens = len(prompt) if prompt is not None else 0
        for handle in replicas:
            if handle.state != READY:
                continue
            if stage is not None \
                    and getattr(handle, "role", "unified") != stage:
                continue
            if stage == "prefill":
                score = self._score_prefill(handle)
            elif stage == "decode":
                score = self._score_decode(handle, total_tokens,
                                           branches, prompt_tokens)
            else:
                score = self._score(handle, total_tokens, branches,
                                    prompt_tokens)
            if stage != "prefill" and prompt is not None \
                    and len(prompt) > 0:
                pc = getattr(handle.engine, "prefix_cache", None)
                if pc is not None:
                    score += pc.peek(prompt, adapter) / len(prompt)
            if best is None or score > best_score:
                best, best_score = handle, score
        self._c_placements.inc(
            outcome="placed" if best is not None else "no_replica")
        return best

    def place_shadow(self, replicas, total_tokens: int, *, exclude,
                     prompt=None, adapter: int = 0):
        """Lighthouse shadow-replay placement (obs/audit.py): pick a
        READY replica for the duplicate leg, excluding the primary's
        index (``exclude`` is an index or an iterable of indexes).
        Funnels through :meth:`place`, so the shadow decision is
        counted like any other and rides the same scoring — never the
        ``_score*`` helpers directly (their caller lint)."""
        if isinstance(exclude, int):
            exclude = (exclude,)
        banned = set(exclude)
        cands = [h for h in replicas if h.index not in banned]
        return self.place(cands, total_tokens, prompt=prompt,
                          adapter=adapter)
