"""Continuous-batching decode engine.

The data plane of the serving stack: a dense batched KV cache of
``max_slots`` rows, stepped one token per round for every active row,
with finished rows retired *mid-batch* and newly admitted requests
prefilled into the freed rows — the batch never drains to admit work.
Policy (who gets in, who waits) is the scheduler's
(:mod:`serve.scheduler`); this module only executes its decisions.

Correctness contract: greedy decode through the engine is
**bit-identical** to sequential ``inference.generate.generate`` for the
same prompt (tests/test_serve.py golden test). Both paths run the same
per-row math — prefill via :func:`inference.generate.prefill_ragged`
(batch of one) and per-round steps via the same per-row decode apply,
where every row's attention is masked to exactly its own filled cache
prefix; masked slots contribute exact 0.0 after softmax, so sharing a
batch with strangers cannot perturb a row's floats.

Hot-loop discipline (lint-enforced): :meth:`ServingEngine._decode_round`
contains the per-round device work and performs NO host->device
transfers and no jnp/jax array construction — slot state (last token,
per-row cache depth, active mask) lives on device across rounds, and
the one device->host fetch per round (the sampled tokens the scheduler
must see to detect eos/budget) is a single ``np.asarray`` of a (slots,)
array. Slot mutations (admission, retirement) happen outside the hot
method and push the refreshed slot arrays once.

Observability: TTFT + per-token latency histograms, batch-occupancy /
queue-depth / KV-utilization gauges, one flight-ring ``serve`` event
per decode round (a wedged loop is visible to the doctor as a stalled
round counter), per-request retroactive spans when tracing is on, and
per-request ``serve_request`` JSONL records through MetricsLogger.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_nn_tpu import obs
from pytorch_distributed_nn_tpu.inference.generate import (
    _apply_decode_ragged,
    init_cache,
)
from pytorch_distributed_nn_tpu.nn.lora import num_adapters
from pytorch_distributed_nn_tpu.obs import (
    audit,
    flight,
    meter,
    trace,
    watchtower,
    xray,
)
from pytorch_distributed_nn_tpu.runtime import chaos
from pytorch_distributed_nn_tpu.serve import autoscale, decoding
from pytorch_distributed_nn_tpu.serve.kv_pool import KVPool
from pytorch_distributed_nn_tpu.serve.prefix_cache import PrefixCache
from pytorch_distributed_nn_tpu.serve.scheduler import (
    Request,
    Scheduler,
    branch_seq_ids,
)

# TTFT spans queueing (ms..s under load); per-token latency is ms-scale
_TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                 2.5, 5.0, 10.0, 30.0)
_TOKEN_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                  0.25, 0.5, 1.0)


def _apply_prefill_at(model, params, cache, tokens, lengths, starts,
                      **extra):
    """Ragged prefill with a per-row cache-write offset: row i's KV
    lands in cache rows [starts[i], starts[i] + lengths[i]) and its
    queries attend absolute positions [0, starts[i] + t] — which is
    what prefix-cache suffix prefill needs: the restored rows
    [0, starts[i]) are already in ``cache`` and the suffix computes
    exactly the floats a full from-zero prefill would have. Returns
    ((B, V) logits at each row's LAST real suffix position, cache).
    ``extra`` forwards per-request LoRA (lora_bank + adapter_ids) so
    TransformerLM-family models never see unknown kwargs."""
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, tokens,
        train=False, decode=True, mutable=["cache"],
        cache_positions=starts.astype(jnp.int32), **extra,
    )
    last = (lengths.astype(jnp.int32) - 1)[:, None, None]
    next_logits = jnp.take_along_axis(logits, last, axis=1)[:, 0, :]
    return next_logits, mutated["cache"]


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_prefill(model, params, cache, tokens, lengths, starts):
    """Batch-of-one (suffix) prefill + greedy first token: (1,) int32
    token, filled (1, P_pad, ...) row cache. ``starts`` (1,) int32 is
    the number of rows already restored from the prefix cache (0 for a
    miss). The argmax runs on device so the only host transfer is the
    token itself."""
    next_logits, cache = _apply_prefill_at(model, params, cache,
                                           tokens, lengths, starts)
    return jnp.argmax(next_logits, axis=-1).astype(jnp.int32), cache


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_prefill_lora(model, params, cache, tokens, lengths, starts,
                        bank, ids):
    """LoRA twin of :func:`_serve_prefill`: same math plus per-row
    adapter deltas. A separate jit (not a None-bank branch) keeps the
    base path's trace free of the bank pytree."""
    next_logits, cache = _apply_prefill_at(
        model, params, cache, tokens, lengths, starts,
        lora_bank=bank, adapter_ids=ids)
    return jnp.argmax(next_logits, axis=-1).astype(jnp.int32), cache


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_step(model, params, cache, last_tok, lengths, active):
    """One decode round over all slots: feed every row its last token
    at its own cache depth, take greedy argmax. Inactive rows still
    flow through the batched apply (a dynamic batch size would
    recompile); their tokens/depths are frozen by the ``active`` mask
    and their cache writes land in retired rows that the next
    occupant's prefill overwrites (and masks until it grows there)."""
    logits, cache = _apply_decode_ragged(model, params, cache, last_tok,
                                         lengths)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, last_tok)
    lengths = jnp.where(active, lengths + 1, lengths)
    return nxt, lengths, cache


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_step_lora(model, params, cache, last_tok, lengths, active,
                     bank, ids):
    """LoRA twin of :func:`_serve_step`: each row applies its own
    adapter's deltas (ids is the per-slot adapter mirror), so one
    batched decode serves every tenant's fine-tune at once."""
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, last_tok[:, None],
        train=False, decode=True, last_only=True, mutable=["cache"],
        cache_positions=lengths.astype(jnp.int32),
        lora_bank=bank, adapter_ids=ids,
    )
    nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, last_tok)
    lengths = jnp.where(active, lengths + 1, lengths)
    return nxt, lengths, mutated["cache"]


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_prefill_logits(model, params, cache, tokens, lengths, starts):
    """Sampled-path prefill twin of :func:`_serve_prefill`: returns the
    (1, V) next-token logits instead of their argmax, so the host can
    fan ONE prompt's logits into n branch first-tokens (and their
    logprobs) without a second forward. The greedy path never routes
    here — its jit (and bytes) are untouched."""
    return _apply_prefill_at(model, params, cache, tokens, lengths,
                             starts)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_prefill_logits_lora(model, params, cache, tokens, lengths,
                               starts, bank, ids):
    """LoRA twin of :func:`_serve_prefill_logits`."""
    return _apply_prefill_at(model, params, cache, tokens, lengths,
                             starts, lora_bank=bank, adapter_ids=ids)


@functools.partial(jax.jit, static_argnums=(1,))
def _sample_first(next_logits, n, temp, top_k, top_p, seed, step0):
    """First token for each of a request's ``n`` branches from one
    prefill's (1, V) logits: branch k draws with key
    ``fold_in(fold_in(key(seed), k), step0)`` — the same derivation
    the decode-step jit uses, so a branch's whole stream is one
    unbroken (seed, branch, step) sequence. Returns ((n,) int32
    tokens, (n,) float32 logprobs under the model distribution).
    ``n`` is static: one program per distinct branch count, not per
    spec."""
    row = next_logits[0]
    logits = jnp.broadcast_to(row, (n, row.shape[-1]))
    branches = jnp.arange(n, dtype=jnp.int32)
    seeds = jnp.full((n,), seed, jnp.int32)
    steps = jnp.full((n,), step0, jnp.int32)
    temps = jnp.full((n,), temp, jnp.float32)
    top_ks = jnp.full((n,), top_k, jnp.int32)
    top_ps = jnp.full((n,), top_p, jnp.float32)
    keys = decoding.row_keys(seeds, branches, steps)
    toks = decoding.sample_rows(logits, temps, top_ks, top_ps,
                                keys).astype(jnp.int32)
    return toks, decoding.token_logprobs(logits, toks)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_step_sample(model, params, cache, last_tok, lengths, active,
                       temps, top_ks, top_ps, seeds, branches, steps,
                       logprob):
    """Sampled twin of :func:`_serve_step`: the SAME ragged forward
    (greedy rows in a mixed batch still see bit-identical logits and
    take the per-row greedy ``where`` branch), then per-row seeded
    sampling with traced temperature/top_k/top_p. Per-row RNG steps
    and cumulative logprobs advance INSIDE the jit, so the hot loop
    stays transfer-free and best-of-n ranking needs no per-round
    fetch."""
    logits, cache = _apply_decode_ragged(model, params, cache, last_tok,
                                         lengths)
    keys = decoding.row_keys(seeds, branches, steps)
    drawn = decoding.sample_rows(logits, temps, top_ks, top_ps,
                                 keys).astype(jnp.int32)
    logprob = jnp.where(active,
                        logprob + decoding.token_logprobs(logits, drawn),
                        logprob)
    nxt = jnp.where(active, drawn, last_tok)
    lengths = jnp.where(active, lengths + 1, lengths)
    steps = jnp.where(active, steps + 1, steps)
    return nxt, lengths, steps, logprob, cache


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _serve_step_sample_lora(model, params, cache, last_tok, lengths,
                            active, bank, ids, temps, top_ks, top_ps,
                            seeds, branches, steps, logprob):
    """LoRA twin of :func:`_serve_step_sample`."""
    raw, mutated = model.apply(
        {"params": params, "cache": cache}, last_tok[:, None],
        train=False, decode=True, last_only=True, mutable=["cache"],
        cache_positions=lengths.astype(jnp.int32),
        lora_bank=bank, adapter_ids=ids,
    )
    logits = raw[:, -1, :]
    keys = decoding.row_keys(seeds, branches, steps)
    drawn = decoding.sample_rows(logits, temps, top_ks, top_ps,
                                 keys).astype(jnp.int32)
    logprob = jnp.where(active,
                        logprob + decoding.token_logprobs(logits, drawn),
                        logprob)
    nxt = jnp.where(active, drawn, last_tok)
    lengths = jnp.where(active, lengths + 1, lengths)
    steps = jnp.where(active, steps + 1, steps)
    return nxt, lengths, steps, logprob, mutated["cache"]


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
def _save_blocks(cache, store, block_size, slot, table, n):
    """Copy the first ``n`` full blocks of batch row ``slot`` into the
    physical blocks ``table[:n]`` of the block store (retire-side
    donation). ``table`` is shape-padded to the per-sequence block
    ceiling so slot/table/n are all traced — ONE program and ONE
    dispatch per retire, however many blocks the sequence spans (the
    per-block version made the cache-ON bench dispatch-bound)."""
    def sv(c, s):
        if c.ndim < 2:
            return s
        def body(j, acc):
            blk = jax.lax.dynamic_slice(
                c, (slot, j * block_size) + (0,) * (c.ndim - 2),
                (1, block_size) + c.shape[2:])
            return jax.lax.dynamic_update_slice(
                acc, blk.astype(acc.dtype),
                (table[j], 0) + (0,) * (acc.ndim - 2))
        return jax.lax.fori_loop(0, n, body, s)
    return jax.tree.map(sv, cache, store)


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(0,))
def _restore_blocks(row_cache, store, block_size, table, n):
    """Copy physical blocks ``table[:n]`` of the store into rows
    [0, n * block_size) of a batch-of-one prefill cache
    (admission-side prefix restore; one dispatch per admission). The
    caller guarantees n * block_size <= the row cache's padded length
    (PrefixCache ``max_rows`` caps matches; out-of-range
    dynamic_update_slice starts would silently CLAMP and corrupt
    neighbor rows)."""
    def rs(r, s):
        if r.ndim < 2:
            return r
        def body(j, acc):
            blk = jax.lax.dynamic_slice(
                s, (table[j], 0) + (0,) * (s.ndim - 2),
                (1, block_size) + s.shape[2:])
            return jax.lax.dynamic_update_slice(
                acc, blk.astype(acc.dtype),
                (0, j * block_size) + (0,) * (acc.ndim - 2))
        return jax.lax.fori_loop(0, n, body, r)
    return jax.tree.map(rs, row_cache, store)


@functools.partial(jax.jit, donate_argnums=(0,))
def _insert_row(batch_cache, row_cache, slot):
    """Copy a prefilled batch-of-one cache into batch row ``slot``.
    Scalar leaves (the shared cache_index / pos_index counters) are
    untouched — per-row mode never reads them."""
    def ins(b, r):
        if b.ndim == 0:
            return b
        return jax.lax.dynamic_update_slice(
            b, r.astype(b.dtype), (slot,) + (0,) * (b.ndim - 1))
    return jax.tree.map(ins, batch_cache, row_cache)


# init_cache retraces model.init (pure Python, ~100ms even for tiny
# models) on every call; per-admission that would dominate TTFT. The
# shape template depends only on (model, batch, max_len), so memoize it
# and mint fresh zeros per prefill (the previous buffer is donated to
# the prefill jit, so it cannot be reused). The value pins the model so
# a dead id() can never alias a different live model.
_CACHE_TMPL: dict = {}


def _fresh_cache(model, batch: int, max_len: int):
    key = (id(model), batch, max_len)
    hit = _CACHE_TMPL.get(key)
    if hit is None:
        tmpl = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            init_cache(model, batch, max_len))
        _CACHE_TMPL[key] = hit = (model, tmpl)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), hit[1])


def _bucket_len(n: int, floor: int = 16) -> int:
    """Round a prompt length up to a power of two (>= ``floor``): the
    prefill/insert jit cache then holds O(log max_seq_len) programs
    instead of one per distinct prompt length."""
    b = floor
    while b < n:
        b *= 2
    return b


class _Slot:
    """Host-side mirror of one batch row (= one decode branch)."""

    __slots__ = ("req", "emitted", "tokens", "depth", "cached",
                 "seq_id", "branch", "step0", "streamed")

    def __init__(self, req: Request, first_token: int, depth: int,
                 cached: int = 0, seq_id: str = "", branch: int = 0):
        self.req = req
        self.tokens = [int(first_token)]
        self.emitted = 1
        self.depth = depth  # cache rows filled (prompt + emitted - 1)
        self.cached = cached  # prompt tokens restored from prefix cache
        # Prism: which pool sequence this row extends (== request_id
        # for branch 0 / unbranched requests), the branch's RNG lane,
        # and the sampling step this leg started at
        self.seq_id = seq_id or req.request_id
        self.branch = branch
        self.step0 = req.decode_step0
        self.streamed = 0  # tokens already pushed to req.stream


class ServingEngine:
    """Continuous-batching engine over one model + params."""

    def __init__(self, model, params, *, max_slots: int = 4,
                 max_seq_len: int = 256, block_size: int = 16,
                 max_queue: int = 64, max_prefills_per_round: int = 2,
                 eos_token: Optional[int] = None, metrics=None,
                 tag: str = "", prefix_cache: bool = True,
                 lora_bank=None, tenant_quotas=None,
                 stream_chunk_tokens: int = 1) -> None:
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.model = model
        self.params = params
        # owner label (fleet replica name): rides every serve_request
        # record so per-replica occupancy survives into the JSONL
        self.tag = tag
        self.max_slots = max_slots
        self.max_seq_len = int(max_seq_len)
        self.eos_token = eos_token
        self.metrics = metrics  # MetricsLogger or None
        # Causeway: give an armed tracer the JSONL sink (no-op when
        # TPUNN_TRACE is unset — zero writes, lint contract)
        trace.attach_metrics(metrics)
        # Abacus: same contract for an armed meter (TPUNN_METER)
        meter.attach_metrics(metrics)
        # Lighthouse: same contract for an armed audit (TPUNN_AUDIT)
        audit.attach_metrics(metrics)
        # fleet replica index (stamped by the fleet supervisor): the
        # chaos flip@replica=K drill keys on it; standalone engines
        # keep 0
        self.replica_index = 0
        # analytic FLOPs per token (utils/flops.py XLA count at batch
        # 1, seq 1): computed lazily on first metered billing, never
        # when the meter is unarmed; 0 = no cost model reachable
        self._flops_per_token: Optional[int] = None
        # per-request LoRA: stacked (n, L, ...) factor bank
        # (nn/lora.py); requests pick an adapter at submit and each
        # batch row applies its own deltas in the shared forward
        self.lora_bank = lora_bank
        pool = KVPool(
            num_blocks=max_slots * (-(-self.max_seq_len // block_size)),
            block_size=block_size,
        )
        self._cache = _fresh_cache(model, max_slots, self.max_seq_len)
        if prefix_cache:
            self.prefix_cache: Optional[PrefixCache] = PrefixCache(
                pool, max_rows=self.max_seq_len, tag=tag)
            # device block store: retired sequences donate their KV
            # blocks here; admissions with a radix match restore from
            # here. Scalar leaves are fresh zeros (NEVER aliased into
            # self._cache — the decode jit donates the cache every
            # round, and an aliased leaf would be invalidated with it).
            self._store = jax.tree.map(
                lambda x: (jnp.zeros_like(x) if x.ndim < 2 else
                           jnp.zeros((pool.num_blocks, block_size)
                                     + x.shape[2:], x.dtype)),
                self._cache)
            # fixed save/restore table width: one compiled program
            # serves every sequence, whatever its block count
            self._blocks_per_seq = -(-self.max_seq_len // block_size)
        else:
            self.prefix_cache = None
            self._store = None
        self.scheduler = Scheduler(
            pool, max_queue=max_queue, max_seq_len=self.max_seq_len,
            max_prefills_per_round=max_prefills_per_round,
            tenant_quotas=tenant_quotas,
            prefix_cache=self.prefix_cache,
        )
        self.scheduler.metrics = metrics
        self._slots: list[Optional[_Slot]] = [None] * max_slots
        self._h_last = np.zeros((max_slots,), np.int32)
        self._h_depth = np.zeros((max_slots,), np.int32)
        self._h_active = np.zeros((max_slots,), bool)
        self._h_adapter = np.zeros((max_slots,), np.int32)
        self._d_last = jnp.asarray(self._h_last)
        self._d_depth = jnp.asarray(self._h_depth)
        self._d_active = jnp.asarray(self._h_active)
        self._d_adapter = jnp.asarray(self._h_adapter)
        # Prism per-row sampling mirrors (serve/decoding.py): synced on
        # admission/retirement like the four above; the decode-sample
        # jit consumes them as traced arrays so any greedy/sampled row
        # mix runs one compiled program. Steps + cumulative logprobs
        # advance ON DEVICE inside the jit.
        self._h_temp = np.zeros((max_slots,), np.float32)
        self._h_topk = np.zeros((max_slots,), np.int32)
        self._h_topp = np.zeros((max_slots,), np.float32)
        self._h_seed = np.zeros((max_slots,), np.int32)
        self._h_branch = np.zeros((max_slots,), np.int32)
        self._h_step = np.zeros((max_slots,), np.int32)
        self._d_temp = jnp.asarray(self._h_temp)
        self._d_topk = jnp.asarray(self._h_topk)
        self._d_topp = jnp.asarray(self._h_topp)
        self._d_seed = jnp.asarray(self._h_seed)
        self._d_branch = jnp.asarray(self._h_branch)
        self._d_step = jnp.asarray(self._h_step)
        self._d_logprob = jnp.zeros((max_slots,), jnp.float32)
        # prefill-sampled first-token logprobs, applied to _d_logprob
        # at the next sync (slot index -> value)
        self._pending_logprob: dict[int, float] = {}
        self._n_sampled = 0  # active slots needing the sampled jit
        # best-of-n bookkeeping: request_id -> {branch: (tokens, logprob)}
        self._branch_done: dict[str, dict[int, tuple]] = {}
        # incremental streaming: tokens per chunk (1 = every token is
        # a chunk). Chunking never changes the retired fingerprint —
        # the Lighthouse fold runs over the full token list at retire.
        self.stream_chunk_tokens = max(int(stream_chunk_tokens), 1)
        # bench/report feed: per-round wall seconds + finished requests
        self.round_seconds: list[float] = []
        self.completed: list[dict] = []
        self._occ_sum = 0  # sum of per-round active-slot counts
        reg = obs.get_registry()
        self._h_ttft = reg.histogram(
            "serve_ttft_seconds", "submit -> first token",
            buckets=_TTFT_BUCKETS)
        # per-tenant twin of serve_ttft_seconds — the base histogram
        # stays UNLABELED (its series is the global SLO feed; labeling
        # it would break every existing snapshot() caller)
        self._h_ttft_tenant = reg.histogram(
            "serve_tenant_ttft_seconds",
            "submit -> first token, per tenant",
            labels=("tenant",), buckets=_TTFT_BUCKETS)
        self._h_tok = reg.histogram(
            "serve_token_latency_seconds", "decode round wall time "
            "(= per-token latency of every active stream)",
            buckets=_TOKEN_BUCKETS)
        self._g_occ = reg.gauge(
            "serve_batch_occupancy", "active decode slots")
        self._c_tokens = reg.counter(
            "serve_tokens_total", "tokens emitted by the engine")
        self._c_stream_chunks = reg.counter(
            "serve_stream_chunks_total",
            "token chunks pushed to streaming clients")

    # -- client surface ----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kw) -> Request:
        adapter = int(kw.get("adapter", 0))
        if self.lora_bank is not None:
            n = num_adapters(self.lora_bank)
            if not 0 <= adapter < n:
                raise ValueError(
                    f"adapter {adapter} out of range for a LoRA bank "
                    f"of {n} adapters")
        elif adapter != 0:
            raise ValueError(
                f"adapter {adapter} requested but the engine has no "
                f"LoRA bank (pass lora_bank= to ServingEngine)")
        spec = kw.get("decode")
        if spec is not None \
                and getattr(spec, "branches", 1) > self.max_slots:
            raise ValueError(
                f"best_of={getattr(spec, 'branches', 1)} branches can "
                f"never fit a {self.max_slots}-slot engine")
        return self.scheduler.submit(prompt, max_new_tokens, **kw)

    @property
    def active_slots(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def has_work(self) -> bool:
        return self.active_slots > 0 or self.scheduler.queue_depth > 0

    # -- engine loop pieces (one driving thread) ---------------------------

    def step(self) -> bool:
        """One scheduler round: admit + prefill into free slots, one
        batched decode round, retire finished rows. Returns False when
        there was nothing to do (caller may sleep/park)."""
        sched = self.scheduler
        sched.round += 1
        # chaos tenant_flood: synthetic burst traffic lands through the
        # REAL submit path (quota checks, DRR queues, reject counters)
        for tenant, owed in chaos.on_tenant_flood():
            for _ in range(owed):
                self.submit(np.asarray([3, 5, 7], np.int32), 2,
                            tenant=tenant)
        changed = self._admit()
        if self.active_slots == 0:
            self._g_occ.set(0)
            if changed:
                self._sync_slots()
            return changed
        host_tok, dt = self._decode_round()
        self.round_seconds.append(dt)
        self._h_tok.observe(dt)
        occ = self.active_slots
        self._g_occ.set(occ)
        self._c_tokens.inc(occ)
        self._occ_sum += occ
        flight.record("serve", "decode_round", step=sched.round,
                      note=f"occ={occ}/{self.max_slots}")
        # watchtower feed (token-latency SLO + queue/KV pressure):
        # here, NOT in _decode_round — its hot-loop lint bans extras
        watchtower.on_serve_round(
            sched.round, dt, queue_depth=sched.queue_depth,
            queue_max=sched.max_queue,
            kv_free=sched.pool.free_blocks,
            kv_total=sched.pool.num_blocks)
        # helm feed (instantaneous queue/KV between control ticks);
        # inert one-comparison no-op unless TPUNN_AUTOSCALE armed it
        autoscale.on_serve_round(
            sched.round, dt, queue_depth=sched.queue_depth,
            queue_max=sched.max_queue,
            kv_free=sched.pool.free_blocks,
            kv_total=sched.pool.num_blocks)
        # xray capture clock (serving-side): rounds advance an active
        # capture window / interval trigger, same placement rule
        xray.on_serve_round(sched.round)
        # Abacus decode billing: one token per active slot this round,
        # split by tenant — here, NOT in _decode_round (hot-loop lint).
        # enabled() gate so the slot scan + FLOPs lookup never run on
        # an unarmed process (the armed-vs-unset A/B contract)
        if meter.enabled():
            # Lighthouse shadow/probe legs are audit duplicates, not
            # customer traffic — their decode rounds are never billed
            meter.on_decode_round(
                [s.req.tenant for s in self._slots if s is not None
                 and s.req.tenant != audit.SHADOW_TENANT],
                self.flops_per_token())
        retired = self._collect(host_tok)
        if retired:
            self._sync_slots()
        return True

    def run_until_idle(self) -> None:
        """Drive rounds until queue and batch are both empty."""
        while self.has_work:
            self.step()

    def drain(self) -> int:
        """Graceful shutdown: reject everything queued, finish every
        in-flight sequence, leave the batch empty. Returns the number
        of requests that were still queued (now rejected)."""
        rejected = self.scheduler.drain()
        while self.active_slots > 0:
            self.step()
        flight.record("serve", "drained",
                      note=f"rejected_queued={rejected}")
        return rejected

    # -- internals ---------------------------------------------------------

    def _admit(self) -> bool:
        """Pull scheduler admissions into free slots and prefill them."""
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return False
        admitted = self.scheduler.next_admissions(len(free))
        if not admitted:
            return False
        for req in admitted:
            # a branched request claims one row per branch (the
            # scheduler already counted them against free_slots)
            slots = [free.pop(0) for _ in range(req.branches)]
            self._prefill_into(slots, req)
        # a budget-1 (or instant-eos) request retires in the same pass
        self._retire_finished()
        self._sync_slots()
        return True

    def _prefill_into(self, slots: list, req: Request) -> None:
        """Prefill ONE request into ``len(slots)`` batch rows. The
        prompt forward runs once; branched requests fan the resulting
        row cache into every branch row (``_insert_row`` donates only
        the batch cache, so one prefilled row inserts n times) and
        draw each branch's first token from the same prompt logits
        under its own RNG lane."""
        L = len(req.prompt)
        spec = req.decode
        sampled = spec is not None and spec.sampled
        match = req.prefix_match
        m = match.tokens if match is not None else 0
        bs = self.scheduler.pool.block_size
        suffix = np.asarray(req.prompt[m:], np.int32)
        T = len(suffix)  # >= 1: PrefixCache caps matches at L - 1
        t_pad = min(_bucket_len(T), self.max_seq_len - m)
        # row-cache length must hold BOTH the restored blocks and the
        # suffix writes: a dynamic_update_slice whose start exceeds the
        # buffer silently clamps (corrupting neighbor rows), so pad is
        # sized to max(restored top, m + suffix pad), never less
        restore_top = len(match.restore_blocks) * bs \
            if match is not None else 0
        pad = min(_bucket_len(max(m + t_pad, restore_top)),
                  self.max_seq_len)
        tokens = np.zeros((1, t_pad), np.int32)
        tokens[0, :T] = suffix  # left-ALIGNED (pad tail is masked)
        row_cache = _fresh_cache(self.model, 1, pad)
        if m > 0:
            nb = len(match.restore_blocks)
            table = np.zeros((self._blocks_per_seq,), np.int32)
            table[:nb] = match.restore_blocks
            t_restore = time.monotonic()
            row_cache = _restore_blocks(
                row_cache, self._store, bs, table, np.int32(nb))
            trace.on_segment(req.trace, "restore", t_restore,
                             time.monotonic(), blocks=nb, cached=m)
        logps: Optional[list] = None
        with obs.span("serve/prefill", request=req.request_id,
                      prompt_len=L, cached=m):
            if not sampled:
                # inert-defaults contract: this arm is the EXACT
                # pre-Prism call (test_quality pins its shape), so
                # greedy requests stay byte-identical
                if self.lora_bank is None:
                    tok0, row_cache = _serve_prefill(
                        self.model, self.params, row_cache,
                        jnp.asarray(tokens), jnp.asarray([T], jnp.int32),
                        jnp.asarray([m], jnp.int32))
                else:
                    tok0, row_cache = _serve_prefill_lora(
                        self.model, self.params, row_cache,
                        jnp.asarray(tokens), jnp.asarray([T], jnp.int32),
                        jnp.asarray([m], jnp.int32), self.lora_bank,
                        jnp.asarray([req.adapter], jnp.int32))
                firsts = [int(np.asarray(tok0)[0])]
            else:
                if self.lora_bank is None:
                    next_logits, row_cache = _serve_prefill_logits(
                        self.model, self.params, row_cache,
                        jnp.asarray(tokens), jnp.asarray([T], jnp.int32),
                        jnp.asarray([m], jnp.int32))
                else:
                    next_logits, row_cache = _serve_prefill_logits_lora(
                        self.model, self.params, row_cache,
                        jnp.asarray(tokens), jnp.asarray([T], jnp.int32),
                        jnp.asarray([m], jnp.int32), self.lora_bank,
                        jnp.asarray([req.adapter], jnp.int32))
                toks, lps = _sample_first(
                    next_logits, len(slots),
                    np.float32(spec.temperature), np.int32(spec.top_k),
                    np.float32(spec.top_p), np.int32(spec.seed),
                    np.int32(req.decode_step0))
                firsts = [int(t) for t in np.asarray(toks)]
                logps = [float(x) for x in np.asarray(lps)]
        if match is not None:
            # restored rows are copied out; the COW tail pin can drop
            self.prefix_cache.finish_restore(match)
            req.prefix_match = None
        now = time.monotonic()
        req.t_first_token = now
        # TTFT is charged from the logical request's ORIGINAL arrival
        # (t_origin: set by the fleet on resubmitted legs), and only
        # when THIS leg delivers the first token — a disagg decode leg
        # or a post-first-token failover re-admission arrives with
        # t_first_origin already set and must not observe again (the
        # capacity sim's accounting, now pinned for the live fleet too)
        if req.t_first_origin == 0.0:
            ttft = now - (req.t_origin or req.t_submit)
            self._h_ttft.observe(ttft)
            self._h_ttft_tenant.observe(ttft, tenant=req.tenant)
        sids = branch_seq_ids(req)
        for k, slot in enumerate(slots):
            self._cache = _insert_row(self._cache, row_cache, slot)
            s = _Slot(req, firsts[k], depth=L, cached=m,
                      seq_id=sids[k], branch=k)
            self._slots[slot] = s
            self._h_last[slot] = firsts[k]
            self._h_depth[slot] = L
            self._h_active[slot] = True
            self._h_adapter[slot] = req.adapter
            # reset the sampling mirrors: slots are reused, and a
            # greedy row landing on a retired sampled row must read
            # temperature 0 (the jit's per-row greedy branch)
            self._h_temp[slot] = spec.temperature if sampled else 0.0
            self._h_topk[slot] = spec.top_k if sampled else 0
            self._h_topp[slot] = spec.top_p if sampled else 0.0
            self._h_seed[slot] = spec.seed if sampled else 0
            self._h_branch[slot] = k
            self._pending_logprob[slot] = logps[k] if sampled else 0.0
            self._c_tokens.inc()  # the prefill-produced first token
            flight.record("serve", "admit", step=self.scheduler.round,
                          note=f"{sids[k]} slot={slot} L={L} "
                               f"cached={m}")
            if k == 0:
                # first chunk = the client-visible TTFT event (no-op
                # for non-streaming requests)
                self._emit_chunk(s)
        # Abacus prefill billing: the suffix actually computed, plus
        # the cached-prefix FLOPs the restore SKIPPED as a credit
        # (audit shadow/probe legs are never billed)
        if meter.enabled() and req.tenant != audit.SHADOW_TENANT:
            meter.on_prefill(req.request_id, req.tenant,
                             new_tokens=T, cached_tokens=m,
                             flops_per_token=self.flops_per_token())

    def _decode_round(self):
        """THE hot loop body (see module docstring for the lint
        contract: no host->device transfers, no jnp/jax array
        construction — device state stays resident; one (slots,)
        device->host fetch)."""
        t0 = time.monotonic()
        # chaos slow@/crash@/preempt@ key on the decode round the way
        # they key on the training step; inside the timed window so an
        # injected slow round shows up in the latency histograms
        # exactly like a real one
        chaos.on_step(self.scheduler.round)
        if self._n_sampled == 0:
            # inert-defaults contract: an all-greedy batch runs the
            # EXACT pre-Prism jits (test_quality pins the call shape),
            # so default requests stay byte-identical
            if self.lora_bank is None:
                nxt, depth, self._cache = _serve_step(
                    self.model, self.params, self._cache, self._d_last,
                    self._d_depth, self._d_active)
            else:
                nxt, depth, self._cache = _serve_step_lora(
                    self.model, self.params, self._cache, self._d_last,
                    self._d_depth, self._d_active, self.lora_bank,
                    self._d_adapter)
        elif self.lora_bank is None:
            nxt, depth, self._d_step, self._d_logprob, self._cache = \
                _serve_step_sample(
                    self.model, self.params, self._cache, self._d_last,
                    self._d_depth, self._d_active, self._d_temp,
                    self._d_topk, self._d_topp, self._d_seed,
                    self._d_branch, self._d_step, self._d_logprob)
        else:
            nxt, depth, self._d_step, self._d_logprob, self._cache = \
                _serve_step_sample_lora(
                    self.model, self.params, self._cache, self._d_last,
                    self._d_depth, self._d_active, self.lora_bank,
                    self._d_adapter, self._d_temp, self._d_topk,
                    self._d_topp, self._d_seed, self._d_branch,
                    self._d_step, self._d_logprob)
        self._d_last, self._d_depth = nxt, depth
        host_tok = np.asarray(nxt)
        return host_tok, time.monotonic() - t0

    def _collect(self, host_tok: np.ndarray) -> int:
        """Fold one round's tokens into the host slot mirrors and
        retire rows that hit eos or budget. Returns retired count."""
        # chaos flip@replica=K: perturb ONE fetched token (first active
        # slot) this round — a silent corruption: the wrong id flows
        # into the slot mirror, the JSONL record, and the fingerprint
        # chain exactly as flaky HBM would ship it. Host-side, outside
        # _decode_round (its hot-loop lint bans extras).
        flip = chaos.on_flip_token(self.replica_index,
                                   self.scheduler.round)
        flipped = False
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            tok = int(host_tok[i])
            if flip:
                flip = False
                flipped = True
                tok = tok - 1 if tok > 0 else tok + 1
            s.tokens.append(tok)
            s.emitted += 1
            s.depth += 1
            self._h_last[i] = tok
            self._h_depth[i] = s.depth
            self.scheduler.pool.extend(s.seq_id, s.depth)
            if s.req.stream is not None and \
                    len(s.tokens) - s.streamed >= self.stream_chunk_tokens:
                self._emit_chunk(s)
        retired = self._retire_finished()
        if flipped:
            # push the corrupted last-token mirror to device (mirrors
            # are all current here) so the flip PROPAGATES: subsequent
            # tokens condition on the wrong id, exactly like real rot
            self._sync_slots()
        return retired

    def _done(self, s: _Slot) -> bool:
        if s.emitted >= s.req.max_new_tokens:
            return True
        return self.eos_token is not None and \
            s.tokens[-1] == self.eos_token

    def _retire_finished(self) -> int:
        retired = 0
        for i, s in enumerate(self._slots):
            if s is None or not self._done(s):
                continue
            self._slots[i] = None
            self._h_active[i] = False
            retired += 1
            req = s.req
            if req.branches > 1:
                self._retire_branch(i, s)
                continue
            if self.prefix_cache is not None:
                # donate BEFORE retire: release() indexes the physical
                # blocks into the radix, so their bytes must already be
                # in the store when another admission can match them
                self._donate_blocks(i, s)
            # final flush BEFORE retire: the closing chunk must be in
            # the stream when done.set() wakes the client
            self._emit_chunk(s, final=True)
            self.scheduler.retire(req, np.asarray(s.tokens, np.int32))
            flight.record("serve", "retire", step=self.scheduler.round,
                          note=f"{req.request_id} tokens={s.emitted}")
            self._finish_record(req, s)
        return retired

    def _retire_branch(self, slot: int, s: _Slot) -> None:
        """Retire ONE branch of a best-of-n request: bank its tokens +
        device-accumulated logprob, free its KV tail (each branch
        retires at its OWN eos/budget — a short branch's blocks return
        to the pool while its siblings decode on). The request itself
        retires when its last branch lands: rank by cumulative
        logprob, hand the client the top ``n``."""
        req = s.req
        # outside the hot loop (retirement path), so the fetch is
        # legal. A budget-1 branch retires in the same _admit pass
        # that prefilled it — before _sync_slots merged its first
        # token's logprob to device — so the pending value wins.
        lp = self._pending_logprob.pop(slot, None)
        if lp is None:
            lp = float(np.asarray(self._d_logprob)[slot])
        self.scheduler.release_branch(req, s.seq_id)
        done = self._branch_done.setdefault(req.request_id, {})
        done[s.branch] = (list(s.tokens), lp)
        flight.record("serve", "retire_branch",
                      step=self.scheduler.round,
                      note=f"{s.seq_id} tokens={s.emitted} "
                           f"logprob={lp:.4f}")
        if len(done) < req.branches:
            return
        del self._branch_done[req.request_id]
        # highest cumulative logprob wins; branch index breaks ties
        # deterministically
        order = sorted(done.items(), key=lambda kv: (-kv[1][1], kv[0]))
        n_best = [dict(branch=k, tokens=list(t), logprob=lp)
                  for k, (t, lp) in order][:req.decode.n]
        win_tokens, win_lp = done[order[0][0]]
        # the winning branch's view rides the JSONL record: reuse the
        # last slot mirror as the record carrier
        s.tokens = win_tokens
        s.emitted = len(win_tokens)
        self.scheduler.finish_branches(
            req, np.asarray(win_tokens, np.int32), n_best, win_lp)
        flight.record("serve", "retire", step=self.scheduler.round,
                      note=f"{req.request_id} tokens={s.emitted} "
                           f"branches={req.branches}")
        self._finish_record(req, s)

    def _emit_chunk(self, s: _Slot, final: bool = False) -> None:
        """THE streaming funnel: every token chunk a client sees flows
        through this one ``TokenStream._feed`` call site (lint-pinned),
        so chunk accounting (counter, flight, JSONL) can never drift
        from what was actually delivered. No-op for non-streaming
        requests."""
        stream = s.req.stream
        if stream is None:
            return
        chunk = s.tokens[s.streamed:]
        if chunk:
            first = s.streamed == 0
            s.streamed = len(s.tokens)
            stream._feed(chunk)
            self._c_stream_chunks.inc()
            flight.record("serve", "stream_chunk",
                          step=self.scheduler.round,
                          note=f"{s.req.request_id} n={len(chunk)}"
                               f"{' first' if first else ''}"
                               f"{' final' if final else ''}")
            if self.metrics is not None:
                self.metrics.emit(
                    "serve_stream_chunk", request_id=s.req.request_id,
                    tokens=len(chunk), first=first, final=final)
        if final:
            stream.close()

    def _donate_blocks(self, slot: int, s: _Slot) -> None:
        """Copy the retiring slot's full KV blocks into the device
        store. Count matches what ``PrefixCache.release`` will index:
        ``depth // block_size`` full blocks (depth = prompt + emitted
        - 1 = exactly the rows whose tokens the scheduler hands to
        release). Re-saving a block the radix already owns writes
        bit-identical bytes — harmless."""
        pool = self.scheduler.pool
        bs = pool.block_size
        table = pool.block_table(s.req.request_id)
        nb = min(s.depth // bs, len(table))
        if nb == 0:
            return
        padded = np.zeros((self._blocks_per_seq,), np.int32)
        padded[:nb] = table[:nb]
        self._store = _save_blocks(
            self._cache, self._store, bs,
            np.int32(slot), padded, np.int32(nb))

    def export_blocks(self, table):
        """Host-side copy of physical store blocks ``table`` (leading
        axis = position in the streamed chain) — the transfer SOURCE of
        KV block streaming (:mod:`serve.disagg`). Reads the device
        block store the retire path's ``_save_blocks`` maintains; the
        caller pins the blocks in the pool across the export window so
        eviction cannot recycle them before the peer's write lands.
        Non-block leaves (ndim < 2 scalars) ship as empty placeholders
        so the pytree structure round-trips."""
        idx = jnp.asarray(np.asarray(table, np.int32))
        return jax.tree.map(
            lambda s: np.asarray(s[idx]) if s.ndim >= 2
            else np.zeros((), s.dtype), self._store)

    def ingest_blocks(self, tokens, host_blocks, adapter: int = 0) -> int:
        """Transfer SINK of KV block streaming: index ``tokens``'s full
        blocks in this engine's prefix cache (:meth:`PrefixCache.
        ingest` adopts cached-ring blocks from the free list) and
        scatter the streamed ``host_blocks`` rows into the device store
        at the adopted ids. Already-resident blocks dedup by digest and
        are not rewritten. Returns blocks written; 0 when this engine
        has no prefix cache or the pool had no headroom to adopt."""
        if self.prefix_cache is None or self._store is None:
            return 0
        plan = self.prefix_cache.ingest(tokens, adapter)
        if not plan:
            return 0
        src = jnp.asarray(np.asarray([j for j, _ in plan], np.int32))
        dst = jnp.asarray(np.asarray([p for _, p in plan], np.int32))
        self._store = jax.tree.map(
            lambda d, b: d.at[dst].set(jnp.asarray(b)[src])
            if d.ndim >= 2 else d, self._store, host_blocks)
        return len(plan)

    def _finish_record(self, req: Request, s: _Slot) -> None:
        # TTFT from the logical request's original arrival: for a
        # resubmitted leg, t_origin is the FIRST submit and
        # t_first_origin (if set) the first token an earlier leg
        # already delivered — the JSONL must agree with the fleet
        # ticket and the capacity sim, not restart the clock per leg
        origin = req.t_origin or req.t_submit
        t_first = req.t_first_origin or req.t_first_token
        ttft = t_first - origin
        total = req.t_done - req.t_submit
        decode = req.t_done - req.t_first_token
        per_tok = decode / max(s.emitted - 1, 1)
        # per-request waterfall: the request_id's timeline through
        # admission -> queue -> prefill -> decode -> retire, from the
        # scheduler's lifecycle timestamps + round bookkeeping. Rides
        # the serve_request JSONL record, the retroactive trace span's
        # phase children, and any watchtower alert that names this
        # request.
        waterfall = dict(
            queued_s=round(max(req.t_admit - req.t_submit, 0.0), 6),
            prefill_s=round(max(req.t_first_token - req.t_admit, 0.0),
                            6),
            decode_s=round(max(decode, 0.0), 6),
            round_submitted=req.round_submitted,
            round_admitted=req.round_admitted,
            round_done=req.round_done,
        )
        rec = dict(
            request_id=req.request_id, prompt_len=len(req.prompt),
            new_tokens=s.emitted, ttft_s=ttft, total_s=total,
            per_token_s=per_tok,
            rounds_waited=req.round_admitted - req.round_submitted,
            kv_util=self.scheduler.pool.utilization(),
            waterfall=waterfall,
            tenant=req.tenant, adapter=req.adapter,
            cached_tokens=s.cached,
        )
        if self.tag:
            rec["replica"] = self.tag
        # Prism keys: absent for default requests (key-absent wire
        # discipline — a greedy, non-streaming run's JSONL is
        # byte-identical to a pre-Prism build)
        if req.decode is not None:
            rec["decode"] = req.decode.to_wire()
        if req.n_best is not None:
            rec["branches"] = req.branches
            rec["logprob"] = round(req.logprob, 6)
        if req.stream is not None:
            rec["stream_chunks"] = req.stream.chunks
        if req.trace is not None:
            # the record names its trace (watchtower pages attach it;
            # key absent when untraced, so replayed streams from an
            # unarmed run stay byte-identical)
            rec["trace"] = req.trace.trace_id
        # Lighthouse fingerprint: THE one engine call site that folds a
        # request's emitted tokens onto its chain seed (lint-pinned).
        # None unarmed — the fp key stays absent and the record stream
        # is byte-identical to a pre-audit run.
        fp = audit.on_retire(req.request_id, s.tokens,
                             seed=req.fp_seed, replica=self.tag)
        if fp is not None:
            rec["fp"] = fp
        self.completed.append(rec)
        if self.metrics is not None:
            self.metrics.emit("serve_request", **rec)
        watchtower.on_serve_request(rec)
        # Abacus lifecycle charges (queue/decode wall time, tokens,
        # the per-request JSONL record, the cost-anomaly feed). Audit
        # shadow/probe legs are duplicates, never billed.
        if meter.enabled() and req.tenant != audit.SHADOW_TENANT:
            meter.on_request_done(rec, self.flops_per_token())
        # Causeway segments, retroactive from the scheduler's
        # lifecycle timestamps — the decode hot loop stays untouched
        # (its lint bans extras); resubmit legs ride the ctx the fleet
        # minted/linked
        trace.on_segment(req.trace, "queued", req.t_submit,
                         req.t_admit, request_id=req.request_id,
                         replica=self.tag)
        trace.on_segment(req.trace, "prefill", req.t_admit,
                         req.t_first_token, request_id=req.request_id,
                         replica=self.tag, cached=s.cached,
                         prompt_len=len(req.prompt))
        seg_kw = dict(request_id=req.request_id, replica=self.tag,
                      tokens=s.emitted)
        if fp is not None:
            # the decode span carries the leg fingerprint so a trace
            # waterfall can show WHERE a chain diverged across legs
            seg_kw["fp"] = fp
        trace.on_segment(req.trace, "decode", req.t_first_token,
                         req.t_done, **seg_kw)
        tracer = obs.current_recorder()
        if tracer is not None:
            # retroactive per-request span: duration is only known now
            end_us = tracer._now_us()
            t0_us = end_us - total * 1e6
            tracer.add_event(f"serve/{req.request_id}",
                             t0_us, total * 1e6,
                             cat="serve", args=dict(
                                 prompt_len=len(req.prompt),
                                 new_tokens=s.emitted,
                                 ttft_ms=ttft * 1e3))
            off_us = 0.0
            for phase in ("queued", "prefill", "decode"):
                dur_us = waterfall[f"{phase}_s"] * 1e6
                if dur_us > 0:
                    tracer.add_event(
                        f"serve/{req.request_id}/{phase}",
                        t0_us + off_us, dur_us, cat="serve")
                off_us += dur_us

    def _sync_slots(self) -> None:
        """Push the host slot mirrors to device (admission/retirement
        path only — never per round)."""
        self._d_last = jnp.asarray(self._h_last)
        self._d_depth = jnp.asarray(self._h_depth)
        self._d_active = jnp.asarray(self._h_active)
        self._d_adapter = jnp.asarray(self._h_adapter)
        # Prism mirrors: recompute which rows need the sampled jit and
        # each row's RNG step (step0 + emitted — recomputable host-side
        # by design, so a flip-drill mid-round resync cannot skew the
        # device counter)
        self._n_sampled = 0
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            self._h_step[i] = s.step0 + s.emitted
            if s.req.decode is not None and s.req.decode.sampled:
                self._n_sampled += 1
        if self._n_sampled or self._pending_logprob:
            # logprobs accumulate ON DEVICE: pull, overlay the prefill
            # first-token values, push back (retirement path only)
            h_logprob = np.asarray(self._d_logprob).copy()
            for slot, v in self._pending_logprob.items():
                h_logprob[slot] = v
            self._pending_logprob.clear()
            self._d_logprob = jnp.asarray(h_logprob)
        self._d_temp = jnp.asarray(self._h_temp)
        self._d_topk = jnp.asarray(self._h_topk)
        self._d_topp = jnp.asarray(self._h_topp)
        self._d_seed = jnp.asarray(self._h_seed)
        self._d_branch = jnp.asarray(self._h_branch)
        self._d_step = jnp.asarray(self._h_step)

    def flops_per_token(self) -> int:
        """Analytic forward FLOPs of ONE token through this model
        (:func:`utils.flops.fwd_flops` at batch 1, seq 1) — the unit
        every Abacus billing multiplies. Integer (exact per-tenant
        sums), computed once per engine, 0 when no backend with a cost
        model is reachable (billing then meters tokens/residency/wire
        only). Only metered paths call this, so an unarmed process
        never pays the lowering."""
        if self._flops_per_token is None:
            from pytorch_distributed_nn_tpu.utils.flops import (
                CostModelUnavailable,
                fwd_flops,
            )

            try:
                self._flops_per_token = int(round(
                    fwd_flops(self.model, (1, 1), jnp.int32)))
            except (CostModelUnavailable, RuntimeError):
                self._flops_per_token = 0
        return self._flops_per_token

    def summary(self) -> dict:
        """Engine-lifetime aggregates (bench + serve_summary JSONL)."""
        # flush per-tenant meter_ledger JSONL records (inert no-op
        # unless TPUNN_METER armed): a finished run's stream carries
        # the final ledgers for obs_cost/obs_report
        meter.on_serve_summary()
        rounds = len(self.round_seconds)
        occ = self._occ_sum / max(rounds * self.max_slots, 1)
        out = dict(
            rounds=rounds,
            requests_done=len(self.completed),
            tokens_out=int(sum(r["new_tokens"] for r in self.completed)),
            occupancy=occ,
            kv_util=self.scheduler.pool.utilization(),
            queue_depth=self.scheduler.queue_depth,
        )
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.stats())
        return out
