"""Fleet store layer: one key-value surface, three transports.

The serving fleet's control plane — heartbeats, request dispatch,
progress, the coordinator's journals — speaks one tiny store protocol
(the slice of c10d TCPStore the stack already standardized on for
rendezvous):

``set(key, bytes)`` / ``get(key, timeout_ms=...)`` (blocking wait) /
``add(key, delta) -> int`` (atomic counter) / ``check(key)`` /
``delete(key)`` / ``close()``.

Three implementations satisfy it:

- :class:`runtime.native.StoreClient` — the real C++ store
  (native/store.cpp), one TCP connection per client. Production and
  the process-backed fleet (:mod:`serve.procfleet`) use this.
- :class:`MemStore` — the in-process stand-in the thread-backed
  :class:`serve.fleet.Fleet` runs on. Full surface parity with the
  real client is CONTRACTUAL (tests/test_store_parity.py drives both
  through identical sequences), including the
  :func:`runtime.chaos.on_store_op` passthrough — ``store_flaky`` /
  ``store_partition`` chaos hits the stub exactly like the wire.
- :class:`PrefixStore` — the c10d ``PrefixStore`` idiom: a namespacing
  wrapper over either of the above, so one physical store hosts many
  logical ones (``fleet0/hb/0/3``, ``fleet0/journal/7``) and the REAL
  ``HeartbeatReporter`` / ``FailureDetector`` run unmodified against a
  namespaced view. The namespace is fixed per deployment — replica and
  coordinator incarnation bumps happen *inside* it, so recovery never
  has to guess a key prefix.

:class:`StoreJournal` layers the append-only journal the coordinator's
crash story rests on: entries at ``<name>/<seq>`` with ``<seq>``
allocated by the store's atomic counter, values canonical
``sort_keys`` JSON (or pre-serialized lines, for byte-continuity with
``serve.autoscale.Decision.as_json``). Append-only by construction —
recovery replays it; nothing ever rewrites it.

Stdlib-only on purpose (no jax, no numpy): worker subprocesses import
this before deciding whether to touch a backend at all.
"""

from __future__ import annotations

import json
import threading
import time

from pytorch_distributed_nn_tpu.runtime import chaos


class MemStore:
    """In-process store with FULL :class:`runtime.native.StoreClient`
    surface parity — blocking ``get`` with timeout, atomic ``add``,
    ``delete`` — and the same chaos injection point on every op, so
    the thread-backed fleet and the store-parity suite exercise the
    exact protocol the wire speaks."""

    def __init__(self) -> None:
        self._d: dict[str, bytes] = {}
        self._counters: dict[str, int] = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: bytes) -> None:
        chaos.on_store_op("set", key)  # store_flaky injection point
        with self._cond:
            self._d[key] = bytes(value)
            self._cond.notify_all()

    def get(self, key: str, *, timeout_ms: int = -1,
            max_bytes: int = 1 << 20) -> bytes:
        """Blocking wait for ``key`` (timeout_ms < 0 waits forever) —
        the real client's wait semantics, not a dict lookup."""
        chaos.on_store_op("get", key)  # store_flaky injection point
        deadline = (None if timeout_ms < 0
                    else time.monotonic() + timeout_ms / 1000.0)
        with self._cond:
            while key not in self._d:
                if deadline is None:
                    self._cond.wait()
                else:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._cond.wait(left):
                        if key in self._d:
                            break
                        raise TimeoutError(
                            f"store get({key!r}) timed out")
            return self._d[key]

    def add(self, key: str, delta: int = 1) -> int:
        chaos.on_store_op("add", key)  # store_flaky injection point
        with self._cond:
            # counter keys mirror the native store: numeric state kept
            # apart from blobs, readable back through get() as ASCII
            value = self._counters.get(key, 0) + int(delta)
            self._counters[key] = value
            self._d[key] = str(value).encode()
            self._cond.notify_all()
            return value

    def check(self, key: str) -> bool:
        chaos.on_store_op("check", key)  # store_flaky injection point
        with self._cond:
            return key in self._d

    def delete(self, key: str) -> None:
        chaos.on_store_op("delete", key)  # store_flaky injection point
        with self._cond:
            self._d.pop(key, None)
            self._counters.pop(key, None)

    def close(self) -> None:
        pass


class PrefixStore:
    """Key-namespacing view over any store (the c10d ``PrefixStore``
    idiom): every key gets ``<prefix>/`` prepended on the way down.
    Store users (heartbeats, journals) stay namespace-blind.

    ``close()`` is a no-op unless this wrapper ``owns`` the underlying
    client: the common shape is many logical views over ONE shared
    connection (the coordinator), and a reporter stopping must not
    yank the socket out from under its siblings.
    """

    def __init__(self, store, prefix: str, *, owns: bool = False) -> None:
        self._store = store
        self.prefix = prefix.rstrip("/")
        self._owns = owns

    def _k(self, key: str) -> str:
        return f"{self.prefix}/{key}"

    def set(self, key: str, value: bytes) -> None:
        self._store.set(self._k(key), value)

    def get(self, key: str, *, timeout_ms: int = -1,
            max_bytes: int = 1 << 20) -> bytes:
        return self._store.get(self._k(key), timeout_ms=timeout_ms,
                               max_bytes=max_bytes)

    def add(self, key: str, delta: int = 1) -> int:
        return self._store.add(self._k(key), delta)

    def check(self, key: str) -> bool:
        return self._store.check(self._k(key))

    def delete(self, key: str) -> None:
        self._store.delete(self._k(key))

    def close(self) -> None:
        if self._owns:
            self._store.close()


def make_store(endpoint: str = "mem"):
    """Store factory behind one endpoint string: ``"mem"`` → a fresh
    :class:`MemStore`; ``"host:port"`` → a
    :class:`runtime.native.StoreClient` connection. The fleet CLI and
    worker entrypoint both take exactly this string."""
    endpoint = (endpoint or "mem").strip()
    if endpoint == "mem":
        return MemStore()
    host, _, port = endpoint.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(
            f"store endpoint must be 'mem' or 'host:port', "
            f"got {endpoint!r}")
    from pytorch_distributed_nn_tpu.runtime import native

    return native.StoreClient(host, int(port))


class StoreJournal:
    """Append-only journal through the store: ``<name>/<seq>`` entries,
    ``<seq>`` from the store's atomic counter at ``<name>/next`` —
    writers on any host append without coordination, and a recovering
    coordinator replays the whole stream in order.

    A writer that dies between the counter bump and the entry write
    leaves a hole; :meth:`read_lines` skips it (bounded wait) and
    reports it, so recovery is never wedged by exactly the crash it
    exists to survive."""

    def __init__(self, store, name: str) -> None:
        self._store = store
        self.name = name
        self.holes = 0

    def append(self, rec: dict) -> int:
        """Canonical-JSON append (sort_keys — the byte-determinism
        contract every journal in this codebase follows)."""
        return self.append_line(json.dumps(rec, sort_keys=True))

    def append_line(self, line: str) -> int:
        """Pre-serialized append — :class:`serve.autoscale.Decision`
        journals its own ``as_json()`` bytes so the persisted stream
        is byte-identical to the in-memory one."""
        seq = self._store.add(f"{self.name}/next", 1) - 1
        self._store.set(f"{self.name}/{seq}", line.encode())
        return seq

    def __len__(self) -> int:
        return self._store.add(f"{self.name}/next", 0)

    def read_lines(self, *, entry_timeout_ms: int = 2000) -> list[str]:
        """Every journal line, in append order. A missing entry under
        an advanced counter (writer died mid-append) is skipped after
        ``entry_timeout_ms`` and counted in :attr:`holes`."""
        n = len(self)
        out: list[str] = []
        self.holes = 0
        for seq in range(n):
            try:
                out.append(self._store.get(
                    f"{self.name}/{seq}",
                    timeout_ms=entry_timeout_ms).decode())
            except (TimeoutError, KeyError):
                self.holes += 1
        return out

    def read_all(self, *, entry_timeout_ms: int = 2000) -> list[dict]:
        return [json.loads(line) for line in
                self.read_lines(entry_timeout_ms=entry_timeout_ms)]
