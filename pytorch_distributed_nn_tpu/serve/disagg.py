"""Disaggregated prefill/decode fleet with KV block streaming.

Serving has two phases with opposite resource shapes: **prefill** is one
compute-bound GEMM pass over the whole prompt (arithmetic intensity of a
training step, holds KV for milliseconds), **decode** is a long
bandwidth-bound drip (one token per round, holds its KV blocks for the
whole emission). A unified replica runs both, so a long prefill stalls
every decode sharing its batch — the head-of-line blocking behind TTFT
p99 cliffs under mixed traffic. Disaggregation (DistServe/Splitwise)
gives each phase its own replica pool sized independently, at the cost
of moving the prompt's KV cache between pools.

:class:`DisaggFleet` is that split over the existing fleet machinery —
``Fleet(model, params, prefill=P, decode=D)`` constructs one (the base
class dispatches on the kwargs), so every call site that sizes a fleet
today opts in with two keywords:

- **prefill leg**: the request is admitted on a prefill-pool replica
  with a budget of exactly 1 token. The engine's continuous-batching
  admission runs the full prompt prefill, emits the first token, and
  retires the sequence *in the same pass* — donating its prompt blocks
  to the replica's prefix cache, which is precisely the state the
  decode leg needs;
- **handoff**: when the prefill leg finalizes, the supervisor rewrites
  the ticket — ``prompt' = prompt + [t1]``, budget ``max_new - 1`` —
  and places the decode leg through the two-stage router
  (:meth:`serve.router.Router.place` with ``stage=``: prefill scored by
  queue depth, decode by KV-headroom-after-reservation plus prefix
  affinity). Greedy decoding makes the stitch exact: the decode leg's
  suffix prefill replays the same logits the unified engine would have
  seen, so stitched output is bit-identical to a unified fleet's;
- **KV block streaming**: before the decode leg is submitted, the fleet
  pulls the prompt's resident prefix chain from the peer that owns it —
  export (:meth:`serve.engine.ServingEngine.export_blocks`), one
  point-to-point hop through the :func:`ops.collectives.kv_transfer`
  choke point (wire bytes land in goodput accounting and the flight
  ring like every other collective), ingest into the destination's
  radix + store (:meth:`serve.engine.ServingEngine.ingest_blocks`). The
  decode admission then prefix-matches the streamed blocks and restores
  instead of re-prefilling. The same path is the prefix-cache miss
  repair: a decode replica placed by headroom rather than affinity
  pulls the matched blocks from whichever peer holds them;
- **failure**: streaming is best-effort and correctness-free. A
  ``kill_transfer@`` chaos fault (:mod:`runtime.chaos`) raises
  :class:`runtime.chaos.TransferKillError` with the payload half on the
  wire; the fleet declares the *source* dead (its stranded requests
  re-admit through the normal failover path) and the decode leg simply
  runs cold — it re-prefills on the survivor, output still
  bit-identical. Warmth is an optimization; the ticket journal is the
  only durable state.

Scaling: :meth:`Fleet.scale_to` on a disaggregated fleet targets the
**decode** pool (``_scalable``) — decode is the KV/bandwidth-bound
class whose pressure the Helm autoscaler actually measures; the prefill
pool is sized at construction. The process-backed fleet
(:mod:`serve.procfleet`) runs the same two-pool topology across real
process boundaries: host-side KV pytrees travel through the store on
the versioned, checksummed :mod:`serve.kv_wire` format, and its Helm
edition scales BOTH pools independently
(:meth:`serve.procfleet.ProcessFleet.scale_to` with ``pool=``).

Observability: ``serve_kv_transfer_bytes`` / ``serve_kv_transfer_seconds``
/ ``serve_kv_transfer_total{outcome}`` and per-class
``serve_fleet_replicas{role}`` gauges, plus ``handoff`` / ``kv_transfer``
flight-ring events. Lint-enforced (tests/test_quality.py): the only
serve-package callers of :func:`ops.collectives.kv_transfer` are
:meth:`DisaggFleet._stream_blocks` (thread fleet) and
:func:`serve.kv_wire.push` (process fleet), so every streamed KV byte
is on the books.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from pytorch_distributed_nn_tpu.obs import audit, flight, trace
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.ops import collectives
from pytorch_distributed_nn_tpu.runtime import chaos
from pytorch_distributed_nn_tpu.serve.fleet import (
    Fleet,
    FleetTicket,
    ReplicaHandle,
)
from pytorch_distributed_nn_tpu.serve.router import DEAD, READY
from pytorch_distributed_nn_tpu.serve.scheduler import DONE, REJECTED

# transfer latency buckets: an in-process hop is sub-millisecond; a real
# ICI/DCN block stream for a 100k-token prompt is tens of milliseconds
_TRANSFER_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0,
)


class DisaggFleet(Fleet):
    """Prefill pool + decode pool behind the one admission point."""

    def __init__(self, model, params, *, prefill: int = 1,
                 decode: int = 1, **kw) -> None:
        if "replicas" in kw:
            raise TypeError(
                "DisaggFleet sizes its pools with prefill=/decode=; "
                "replicas= is the unified Fleet's knob")
        prefill, decode = int(prefill), int(decode)
        if prefill < 1 or decode < 1:
            raise ValueError(
                f"need at least one replica per pool, got "
                f"prefill={prefill} decode={decode}")
        # pool sizes and instruments exist before super().__init__ —
        # the base constructor calls our _new_handle/_set_state
        # overrides while building the replica list
        self.n_prefill = prefill
        self.n_decode = decode
        # transfer log for introspection/bench (one dict per attempt)
        self.transfers: list[dict] = []
        reg = get_registry()
        self._c_transfer_bytes = reg.counter(
            "serve_kv_transfer_bytes",
            "KV block bytes streamed between replicas")
        self._c_transfer_total = reg.counter(
            "serve_kv_transfer_total",
            "KV block-streaming attempts", labels=("outcome",))
        self._h_transfer_s = reg.histogram(
            "serve_kv_transfer_seconds",
            "KV block-streaming transfer latency",
            buckets=_TRANSFER_BUCKETS)
        self._g_replicas = reg.gauge(
            "serve_fleet_replicas",
            "ready replicas per pool class", labels=("role",))
        super().__init__(model, params, replicas=prefill + decode, **kw)
        # the scalable pool is decode; prefill is fixed at construction
        self._target_replicas = decode
        self._publish_roles()

    # -- pool shape --------------------------------------------------------

    def _new_handle(self, index: int) -> ReplicaHandle:
        h = super()._new_handle(index)
        # indexes are never reused and only scale_to (decode pool) adds
        # handles, so the first n_prefill indexes are the prefill pool
        # for the fleet's whole life
        h.role = "prefill" if index < self.n_prefill else "decode"
        return h

    def _set_state(self, h: ReplicaHandle, state: str,
                   reason: str = "") -> None:
        super()._set_state(h, state, reason)
        self._publish_roles()

    def _scalable(self) -> list[ReplicaHandle]:
        return [h for h in self._replicas if h.role == "decode"]

    def _publish_roles(self) -> None:
        counts = {"prefill": 0, "decode": 0}
        for h in getattr(self, "_replicas", ()):
            if h.state == READY:
                counts[h.role] = counts.get(h.role, 0) + 1
        for role, n in counts.items():
            self._g_replicas.set(n, role=role)

    # -- two-stage placement -----------------------------------------------

    def _place(self, ticket: FleetTicket, prompt: np.ndarray,
               max_new: int, *, resubmit: bool):
        """Stage-aware placement (caller holds the fleet lock). A fresh
        ticket starts its prefill leg with a budget of exactly 1 token;
        a decode-stage ticket (post-handoff, or a decode-leg failover
        re-admission) places by KV headroom + affinity and pulls warmth
        from the owning peer first."""
        branches = (ticket.decode.branches
                    if ticket.decode is not None else 1)
        if not ticket.stage:
            # Prism best-of-n skips the split: a branched request has
            # no single "first token" to hand off (each branch forks
            # its own stream at step 0), so it runs whole on a decode
            # replica. Sampled n=1 requests split normally — the
            # decode leg resumes RNG lane (seed, 0) at step
            # len(prefix), stitching the exact single-leg stream.
            ticket.stage = "prefill" if branches == 1 else "decode"
        if ticket.stage == "prefill":
            leg_budget = 1
            h = self.router.place(self._replicas, len(prompt) + 1,
                                  prompt=prompt, stage="prefill")
        else:
            leg_budget = max_new
            h = self.router.place(self._replicas,
                                  len(prompt) + max_new,
                                  prompt=prompt, stage="decode",
                                  branches=branches)
        if h is None:
            self._finalize_rejected(ticket, "no_replica")
            return None
        if ticket.stage == "decode":
            # best-effort: a failed/absent stream just means a cold
            # suffix prefill on h — never a correctness event
            self._warm_peer(h, prompt, trace_ctx=ticket.trace,
                            tenant=ticket.tenant)
        req = h.engine.submit(
            prompt, leg_budget, deadline_s=ticket.deadline_s,
            request_id=ticket.request_id, resubmit=resubmit,
            tenant=ticket.tenant,
            # Prism: each leg continues the SAME (seed, branch, step)
            # lanes — the decode leg's step0 is exactly the tokens the
            # prefill leg (and any dead lives) already covered
            decode=ticket.decode, decode_step0=len(ticket.prefix),
            trace_ctx=ticket.trace, t_origin=ticket.t_submit,
            t_first_origin=ticket.t_first_token,
            # Lighthouse: the decode leg resumes the prefill leg's
            # fingerprint chain (seed = chain over the stitched prefix)
            fp_seed=audit.seed_of(ticket.prefix)
            if audit.enabled() else "")
        ticket._attempt = (h.index, req)
        if req.done.is_set() and req.state == REJECTED:
            self._finalize_rejected(ticket, req.reject_reason)
            return None
        return h.index

    # -- the prefill -> decode handoff -------------------------------------

    def _finalize_tickets(self) -> None:
        # intercept finished prefill legs before the base finalizer
        # would stitch them as complete requests
        for ticket in list(self._journal.values()):
            if ticket.done.is_set() or ticket._attempt is None \
                    or ticket.stage != "prefill":
                continue
            idx, req = ticket._attempt
            if req.done.is_set() and req.state == DONE:
                self._handoff(ticket, idx, req)
        super()._finalize_tickets()

    def _handoff(self, ticket: FleetTicket, idx: int, req) -> None:
        """Rewrite a finished prefill leg into its decode leg: the
        emitted first token joins the stitched prefix, the remaining
        budget becomes the decode submission. TTFT is the prefill
        leg's first-token time — handoff latency lands in TBT, not
        TTFT. A budget-1 request (or an instant EOS) is already
        complete and finalizes without a decode leg."""
        emitted = ([int(t) for t in req.tokens]
                   if req.tokens is not None else [])
        if ticket.t_first_token == 0.0:
            ticket.t_first_token = req.t_first_token
        hit_eos = (self.eos_token is not None and emitted
                   and emitted[-1] == int(self.eos_token))
        if hit_eos or len(ticket.prefix) + len(emitted) \
                >= ticket.max_new_tokens:
            # _finalize_done stitches prefix + this attempt's tokens
            self._finalize_done(ticket, idx)
            return
        ticket.prefix.extend(emitted)
        ticket.stage = "decode"
        # Causeway: the decode leg is a resubmission of the same trace
        # — leg+1, parent = the prefill leg's root span
        nxt = trace.on_resubmit(ticket.trace)
        if nxt is not None:
            ticket.trace = nxt
        remaining = ticket.max_new_tokens - len(ticket.prefix)
        new_prompt = np.concatenate(
            [ticket.prompt, np.asarray(ticket.prefix, np.int32)])
        flight.record("fleet", "handoff",
                      note=f"{ticket.request_id} r{idx}-> "
                           f"prefix={len(ticket.prefix)} "
                           f"remaining={remaining}")
        if self.metrics is not None:
            self.metrics.emit("fleet_handoff",
                              request_id=ticket.request_id,
                              from_replica=idx,
                              prefix_tokens=len(ticket.prefix),
                              remaining=remaining)
        self._place(ticket, new_prompt, remaining, resubmit=True)

    # -- KV block streaming ------------------------------------------------

    def _warm_peer(self, dst: ReplicaHandle, prompt,
                   adapter: int = 0, trace_ctx=None,
                   tenant: str = "") -> int:
        """Pull the longest resident prefix chain for ``prompt`` from
        the peer that owns it into ``dst``'s cache, if any peer beats
        what ``dst`` already holds. Returns blocks ingested (0: nobody
        warmer, or the stream failed — the caller proceeds cold)."""
        if dst.engine is None or dst.engine.prefix_cache is None:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        have = dst.engine.prefix_cache.peek(prompt, adapter)
        best = best_match = None
        for h in self._replicas:
            if h is dst or h.state == DEAD or h.engine is None \
                    or h.engine.prefix_cache is None:
                continue
            m = h.engine.prefix_cache.resident_chain(prompt, adapter)
            if m.tokens > have and (best_match is None
                                    or m.tokens > best_match.tokens):
                best, best_match = h, m
        if best is None:
            return 0
        return self._stream_blocks(best, dst, best_match, prompt,
                                   adapter, trace_ctx=trace_ctx,
                                   tenant=tenant)

    def _stream_blocks(self, src: ReplicaHandle, dst: ReplicaHandle,
                       match, prompt, adapter: int = 0, *,
                       trace_ctx=None, tenant: str = "") -> int:
        """THE transfer path (lint-enforced, tests/test_quality.py):
        pin the chain on the source, export its block rows, ship them
        through :func:`ops.collectives.kv_transfer` (wire bytes →
        goodput + flight ring; a ``kill_transfer@`` chaos fault raises
        here), ingest into the destination's radix + store. Returns
        blocks ingested."""
        pool = src.engine.scheduler.pool
        blocks = list(match.blocks)
        for b in blocks:
            pool.pin(b)
        t0 = time.monotonic()
        outcome, ingested, payload = "skipped", 0, 0
        try:
            # the chain could have been evicted between match and pin;
            # re-match under the pins and keep the surviving prefix
            m2 = src.engine.prefix_cache.resident_chain(prompt, adapter)
            k = 0
            while (k < min(len(blocks), len(m2.blocks))
                   and m2.blocks[k] == blocks[k]):
                k += 1
            blocks = blocks[:k]
            if not blocks:
                return 0
            host = src.engine.export_blocks(blocks)
            payload = int(sum(
                x.nbytes for x in jax.tree.leaves(host)
                if getattr(x, "ndim", 0) >= 2))
            outcome = "failed"  # until the wire round-trips
            collectives.kv_transfer(
                host, src=src.name, dst=dst.name,
                src_index=src.index, dst_index=dst.index,
                trace=trace_ctx, tenant=tenant)
            bs = pool.block_size
            ingested = dst.engine.ingest_blocks(
                prompt[:len(blocks) * bs], host, adapter)
            outcome = "ok"
            trace.on_segment(trace_ctx, "transfer", t0, time.monotonic(),
                             src=src.name, dst=dst.name,
                             blocks=len(blocks), bytes=payload,
                             outcome="ok")
            return ingested
        except chaos.TransferKillError:
            # the source "died" with the payload half on the wire:
            # declare it dead (its stranded requests re-admit through
            # the normal failover) and let the caller's decode leg run
            # cold — re-prefill on the survivor, output unchanged
            t_kill = time.monotonic()
            trace.on_segment(trace_ctx, "transfer", t0, t_kill,
                             src=src.name, dst=dst.name,
                             blocks=len(blocks), bytes=payload,
                             outcome="failed")
            self._fail_replica(src, kind="crash",
                               reason="crash:kill_transfer")
            trace.on_segment(trace_ctx, "failover", t_kill,
                             time.monotonic(), from_replica=src.name,
                             reason="kill_transfer")
            return 0
        finally:
            for b in match.blocks:
                pool.unpin(b)
            dt = time.monotonic() - t0
            self._c_transfer_total.inc(outcome=outcome)
            if outcome != "skipped":
                self._c_transfer_bytes.inc(payload)
                self._h_transfer_s.observe(dt)
            self.transfers.append(dict(
                src=src.name, dst=dst.name, blocks=len(blocks),
                ingested=ingested, bytes=payload, outcome=outcome,
                seconds=round(dt, 6)))
            flight.record("fleet", "kv_transfer",
                          note=f"{src.name}->{dst.name} "
                               f"blocks={len(blocks)} "
                               f"ingested={ingested} {outcome}")
            if self.metrics is not None:
                self.metrics.emit(
                    "kv_transfer", src=src.index, dst=dst.index,
                    blocks=len(blocks), ingested=ingested,
                    bytes=payload, outcome=outcome)

    # -- introspection -----------------------------------------------------

    def summary(self) -> dict:
        s = super().summary()
        n_ok = sum(1 for t in self.transfers if t["outcome"] == "ok")
        s["disagg"] = dict(
            prefill=self.n_prefill, decode=self.n_decode,
            transfers=len(self.transfers), transfers_ok=n_ok,
            transfer_bytes=sum(t["bytes"] for t in self.transfers))
        return s
