"""Replica worker subprocess — the process-backed fleet's data plane.

One process = one replica = one crash domain for real. The coordinator
(:mod:`serve.procfleet`) spawns this module with ``python -m``, hands it
a store endpoint + namespace + replica index, and from then on every
word between them travels through the store:

- ``req/<idx>/<k>``   — the k-th request dispatched to this replica
  (``k`` allocated by the coordinator's atomic counter; the worker
  consumes strictly in order, so a dispatch is never lost or doubled);
- ``prog/<rid>``      — tokens emitted so far for a running request,
  republished every decode round; this is what a coordinator (original
  or recovered) stitches from when this replica dies mid-stream;
- ``done/<rid>``      — the final token list; written exactly once per
  request life;
- ``gauge/<idx>``     — queue depth / KV headroom, the remote mirror of
  the scheduler+pool gauges :meth:`serve.router.Router._score` reads;
- ``ctl/<idx>``       — coordinator control: ``drain`` (finish what you
  hold, exit ``GRACEFUL_EXIT_CODE``) or ``stop`` (fleet shutdown);
- ``hb/0/<idx>``      — the REAL :class:`runtime.failure.HeartbeatReporter`
  beating through the same store (progress-watchdog mode, so a wedged
  decode loop reads as a hang even while the beat thread lives);
- ``enroll/<idx>``    — the worker's birth certificate (pid, host,
  role), written once at startup. A locally-spawned worker's record is
  redundant (the coordinator holds the ``Popen``); a worker spawned on
  another host through a :class:`serve.procfleet.TemplateProvisioner`
  has NO process object on the coordinator — this record is how the
  coordinator learns its pid/host at all (``_check_enrollment``);
- ``kvwire/<rid>/*``  — the versioned, checksummed KV handoff wire
  (:mod:`serve.kv_wire`): a ``--role prefill`` worker pushes the
  request's KV tree here after publishing ``done`` (done FIRST — a
  death mid-push is exactly a crash after completion, the coordinator
  hands off and the decode leg runs cold); a ``--role decode`` worker
  pulls it at admit and ingests warm, or re-prefills cold when the
  wire is absent/torn past its bounded deadline. Never wedges.

Roles (``--role prefill|decode|unified``) do not change how this
process serves — the coordinator's stage-aware router is what routes
legs to pools — but a prefill worker pushes the wire on completion and
a decode worker pulls it at admission, and the role rides the enroll
record and the coordinator's ``serve_fleet_replicas{role}`` gauge.

Exit codes are the elastic-agent contract: ``0`` on ``stop``,
``failure.GRACEFUL_EXIT_CODE`` (83) on drain/SIGTERM,
``chaos.CRASH_EXIT_CODE`` (43) on an injected or real crash — the
coordinator's per-replica :class:`launch.RestartPolicy` classifies them
exactly like the training agent does.

Backends: ``stub`` decodes with :func:`serve.stub.stub_next_token`
(deterministic, model-free — restart drills and tier-1); ``tiny``
builds the same deterministic tiny model ``bench.py --serve-tiny``
uses and drives a real :class:`serve.engine.ServingEngine`;
``preset`` builds a REAL model from a named :data:`config.PRESETS`
entry (``--preset``, validated with an error naming every available
preset) with optional Orbax params at ``--ckpt``, behind the same
engine loop.

Store failures (``store_partition`` / ``store_flaky`` chaos, a real
blip) degrade to counted retries (``store_errors_total{op}``) — the
worker keeps decoding through a partition and republishes state when
the store comes back; only the detector's staleness math may declare
it dead.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import sys
import time

import numpy as np

from pytorch_distributed_nn_tpu.obs import audit, meter, trace
from pytorch_distributed_nn_tpu.runtime import chaos, failure
from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)
from pytorch_distributed_nn_tpu.serve import kv_wire
from pytorch_distributed_nn_tpu.serve.store import (
    PrefixStore,
    StoreJournal,
    make_store,
)
from pytorch_distributed_nn_tpu.serve.stub import stub_next_token

# entrypoint contract: honor JAX_PLATFORMS before first backend use —
# a fleet of tiny-backend workers must not pile onto the one real chip
apply_platform_overrides()

log = logging.getLogger(__name__)


class _StubBackend:
    """Model-free decode: one deterministic stub token per active
    request per round. ``token_ms`` paces the round so drills see a
    realistic service rate (queues actually build under flash crowds)."""

    def __init__(self, *, max_slots: int, token_ms: float) -> None:
        self.max_slots = int(max_slots)
        self.token_ms = float(token_ms)
        self._active: list[dict] = []

    @property
    def slots_free(self) -> int:
        return self.max_slots - len(self._active)

    @property
    def has_work(self) -> bool:
        return bool(self._active)

    def admit(self, rec: dict) -> None:
        self._active.append({"rec": rec, "tokens": []})

    def step(self) -> tuple[list, list]:
        """One decode round → ``(progress, completed)`` where progress
        is ``[(rec, tokens_so_far)]`` and completed
        ``[(rec, tokens, status)]``."""
        if not self._active:
            return [], []
        if self.token_ms:
            time.sleep(self.token_ms / 1000.0)
        progress, completed, still = [], [], []
        for ent in self._active:
            rec, toks = ent["rec"], ent["tokens"]
            toks.append(stub_next_token(list(rec["prompt"]) + toks))
            if len(toks) >= int(rec["max_new_tokens"]):
                completed.append((rec, toks, "done"))
            else:
                progress.append((rec, toks))
                still.append(ent)
        self._active = still
        return progress, completed

    def gauges(self) -> dict:
        # slot-granular "KV": free slots over total, the same headroom
        # shape the router scores on real pools
        return {"free_blocks": self.slots_free,
                "num_blocks": self.max_slots, "block_size": 1}

    def export_kv(self, rec: dict, toks: list) -> dict:
        """The stub's 'KV state' is just the token stream —
        :func:`stub_next_token` is a pure function of the prefix, so
        warm and cold decode legs are bit-identical by construction.
        The tree still rides the real wire (chunking, checksums, chaos
        tears) so every drill exercises the full transfer path. Shaped
        ``(1, N)`` — ``kv_transfer`` bills ndim>=2 leaves (the paged
        block convention), so even the stub's bytes are on the books."""
        return {"tokens": np.asarray(
            list(rec["prompt"]) + list(toks), np.int32).reshape(1, -1)}

    def ingest_kv(self, rec: dict, tree: dict) -> int:
        return 0  # nothing to warm; the pull outcome is the point


class _EngineBackend:
    """A real :class:`serve.engine.ServingEngine` over the
    deterministic tiny model (``bench.py``'s ``--serve-tiny`` shape):
    same config, same seed-0 params in every process, so greedy decode
    is bit-identical across replicas and coordinator lives."""

    def __init__(self, *, max_slots: int, max_seq_len: int,
                 block_size: int, max_queue: int, tag: str,
                 model=None, params=None) -> None:
        from pytorch_distributed_nn_tpu.serve.engine import ServingEngine

        self._np = np
        if model is None:
            model, params = build_tiny_model()
        self.engine = ServingEngine(
            model, params, max_slots=max_slots, max_seq_len=max_seq_len,
            block_size=block_size, max_queue=max_queue, tag=tag)
        self._reqs: list[tuple[dict, object]] = []

    @property
    def slots_free(self) -> int:
        return max(self.engine.max_slots - len(self._reqs), 0)

    @property
    def has_work(self) -> bool:
        return bool(self._reqs) or self.engine.has_work

    def admit(self, rec: dict) -> None:
        kw = {}
        if rec.get("decode"):
            # Prism: rebuild the spec from its wire dict (loud on
            # unknown keys — a version-skewed coordinator fails the
            # dispatch, never silently mis-samples)
            from pytorch_distributed_nn_tpu.serve.decoding import (
                DecodeSpec,
            )
            kw["decode"] = DecodeSpec.from_wire(rec["decode"])
            kw["decode_step0"] = int(rec.get("step0", 0))
        req = self.engine.submit(
            self._np.asarray(rec["prompt"], self._np.int32),
            int(rec["max_new_tokens"]),
            request_id=rec["request_id"],
            resubmit=bool(rec.get("life", 0)),
            tenant=rec.get("tenant", "default"), **kw)
        self._reqs.append((rec, req))

    def step(self) -> tuple[list, list]:
        if self.engine.has_work:
            self.engine.step()
        progress, completed, still = [], [], []
        for rec, req in self._reqs:
            if req.done.is_set():
                toks = ([int(t) for t in req.tokens]
                        if req.tokens is not None else [])
                status = "done" if req.state == "done" else "rejected"
                completed.append((rec, toks, status))
                continue
            toks = []
            for slot in self.engine._slots:
                if slot is not None and slot.req is req:
                    toks = [int(t) for t in slot.tokens]
                    break
            progress.append((rec, toks))
            still.append((rec, req))
        self._reqs = still
        return progress, completed

    def gauges(self) -> dict:
        pool = self.engine.scheduler.pool
        return {"free_blocks": pool.free_blocks,
                "num_blocks": pool.num_blocks,
                "block_size": pool.block_size}

    def export_kv(self, rec: dict, toks: list) -> dict:
        """Host-side KV tree for the wire: the request's resident
        prefix chain exported from this engine's block store
        (:meth:`serve.engine.ServingEngine.export_blocks` — the same
        source the threaded DisaggFleet streams from). Single-threaded
        serve loop: nothing can evict between the chain match and the
        export, so no pin window is needed here."""
        tokens = np.asarray(list(rec["prompt"]) + list(toks), np.int32)
        tree: dict = {"tokens": tokens}
        pc = self.engine.prefix_cache
        if pc is None:
            return tree
        adapter = int(rec.get("adapter", 0))
        m = pc.resident_chain(tokens, adapter)
        blocks = list(m.blocks)
        if blocks:
            tree["kv"] = self.engine.export_blocks(blocks)
            tree["nblk"] = np.asarray(len(blocks), np.int32)
        return tree

    def ingest_kv(self, rec: dict, tree: dict) -> int:
        """Warm this engine from a pulled wire tree: adopt prefix-cache
        blocks for the shipped tokens and scatter the streamed rows in
        (:meth:`serve.engine.ServingEngine.ingest_blocks`). Returns
        blocks written; 0 means the decode leg prefills cold anyway —
        warmth is an optimization, never a correctness input."""
        if "kv" not in tree or self.engine.prefix_cache is None:
            return 0
        tokens = np.asarray(tree["tokens"], np.int32)
        bs = self.engine.scheduler.pool.block_size
        n = int(np.asarray(tree["nblk"]).reshape(-1)[0])
        return int(self.engine.ingest_blocks(
            tokens[:n * bs], tree["kv"], int(rec.get("adapter", 0))))


def build_tiny_model():
    """The deterministic tiny decoder every process-backed replica
    serves: the exact ``bench.py --serve-tiny`` shape with seed-0
    init — identical params in every process by construction, so the
    process fleet's greedy streams are bit-comparable to the threaded
    fleet's."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_nn_tpu.config import get_config
    from pytorch_distributed_nn_tpu.models import get_model

    cfg = get_config("llama3_8b_zero")
    cfg.model.extra = dict(num_layers=4, d_model=256, num_heads=8,
                           num_kv_heads=4, mlp_dim=1024,
                           vocab_size=1024)
    cfg.model.compute_dtype = "float32"
    cfg.model.remat = False
    model = get_model(cfg.model)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    return model, params


def build_preset_model(preset: str, ckpt: str = ""):
    """``--backend preset``: a REAL model from the named
    :data:`config.PRESETS` entry, seed-0 params or an Orbax
    params-tree checkpoint at ``ckpt`` (a ``StandardSave`` of the
    params pytree — the serving analogue of the trainer's ``arrays``
    item). Config validation is loud and names every available preset,
    so a typo in a deploy script fails the worker at spawn with the
    fix in the message, not with a silent stub."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_nn_tpu.config import PRESETS, get_config
    from pytorch_distributed_nn_tpu.models import get_model

    if not preset:
        raise SystemExit(
            "fleet-worker: --backend preset needs --preset NAME; "
            f"available presets: {', '.join(sorted(PRESETS))}")
    if preset not in PRESETS:
        raise SystemExit(
            f"fleet-worker: unknown --preset {preset!r}; available "
            f"presets: {', '.join(sorted(PRESETS))}")
    cfg = get_config(preset)
    model = get_model(cfg.model)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    if ckpt:
        from pathlib import Path

        import orbax.checkpoint as ocp

        path = Path(ckpt).absolute()
        if not path.exists():
            raise SystemExit(
                f"fleet-worker: --ckpt {ckpt!r} does not exist")
        params = ocp.StandardCheckpointer().restore(path, target=params)
    return model, params


def _publish(ps, key: str, rec: dict, *, op: str) -> bool:
    """Counted-retry store write: a partition degrades the publish to
    a ``store_errors_total{op}`` bump, never a dead worker."""
    try:
        ps.set(key, json.dumps(rec, sort_keys=True).encode())
        return True
    except (OSError, TimeoutError):
        failure.count_store_error(op)
        return False


def _publish_done(ps, rec: dict, tokens: list, status: str,
                  *, retries: int = 100) -> None:
    """The one write that must not be silently dropped: retry through
    a partition window. If the store stays gone the coordinator's
    staleness math re-admits the request elsewhere and greedy decode
    regenerates the identical stream — correctness never rests on this
    write landing, only latency does."""
    payload = {"life": int(rec.get("life", 0)), "status": status,
               "tokens": [int(t) for t in tokens]}
    if "trace" in rec:  # Causeway echo — absent when unarmed
        payload["trace"] = rec["trace"]
    key = f"done/{rec['request_id']}"
    for _ in range(retries):
        if _publish(ps, key, payload, op="worker_done"):
            return
        time.sleep(0.05)
    log.warning("giving up publishing %s after %d retries", key, retries)


def _push_wire(ps, idx: int, rec: dict, toks: list, backend) -> None:
    """Prefill leg completed: stream its KV tree to the store wire.

    Called strictly AFTER :func:`_publish_done` — the done record is
    the correctness commit; the wire is warmth. A death anywhere in
    here (``kill_transfer@`` chaos fires inside ``kv_transfer``, a real
    SIGKILL) is therefore exactly a crash after completion: the
    coordinator's handoff proceeds from the done payload and the
    decode leg pulls a dead wire — cold re-prefill, identical tokens."""
    tree = backend.export_kv(rec, toks)
    ctx = None
    if "trace" in rec:  # Causeway: the transfer bills to this leg
        ctx = trace.TraceContext.from_wire(rec["trace"])
    kv_wire.push(ps, rec["request_id"], tree,
                 src=f"r{idx}", dst="decode",
                 src_index=idx, dst_index=-1,
                 trace=ctx, tenant=rec.get("tenant", ""))


def _pull_wire(ps, idx: int, rec: dict, backend, journal) -> None:
    """Decode leg admitted: pull the prefill leg's KV tree and warm
    this backend, or fall through cold. The warm/cold disposition is
    journaled (counted write — a partitioned journal never blocks the
    admission) so drills and ``obs_doctor`` can see which path ran."""
    tree = kv_wire.pull(ps, rec["request_id"])
    outcome = "warm" if tree is not None else "cold"
    blocks = backend.ingest_kv(rec, tree) if tree is not None else 0
    failure.store_call(
        lambda: journal.append({
            "event": "kv_pull", "request_id": rec["request_id"],
            "replica": idx, "outcome": outcome, "blocks": blocks}),
        op="worker_journal", deadline_s=1.0, fallback=None)


def _serve_loop(args, ps, idx: int, reporter, backend) -> int:
    journal = StoreJournal(ps, "journal")
    queue: list[dict] = []
    next_k = args.start_k
    draining = False
    rounds = 0
    idle_s = max(args.poll_ms, 0.5) / 1000.0
    while True:
        rounds += 1
        # chaos kill/hang drill — may raise ReplicaKillError (caught in
        # main → exit CRASH_EXIT_CODE) or block (heartbeat goes stale)
        chaos.on_replica_round(idx, rounds)
        reporter.notify_progress()
        if failure.preempt_requested():
            draining = True  # SIGTERM → finish what we hold, exit 83
        try:
            if ps.check(f"ctl/{idx}"):
                cmd = ps.get(f"ctl/{idx}", timeout_ms=1000).decode()
                if cmd == "stop":
                    return 0
                if cmd == "drain":
                    draining = True
        except (OSError, TimeoutError):
            failure.count_store_error("worker_ctl")
        try:
            while ps.check(f"req/{idx}/{next_k}"):
                queue.append(json.loads(ps.get(
                    f"req/{idx}/{next_k}", timeout_ms=1000).decode()))
                next_k += 1
        except (OSError, TimeoutError):
            failure.count_store_error("worker_pull")
        while queue and backend.slots_free > 0:
            rec0 = queue.pop(0)
            # Causeway: stamp the admit time for this leg's decode
            # span before the backend owns the record
            trace.on_worker_admit(rec0, host=idx)
            if rec0.get("stage") == "decode":
                # warm from the handoff wire, or prefill cold — the
                # pull is bounded (deadline + counted re-pulls), so
                # a dead/torn wire can never wedge the admission
                _pull_wire(ps, idx, rec0, backend, journal)
            backend.admit(rec0)
        progress, completed = backend.step()
        for rec, toks in progress:
            if toks:
                payload = {"life": int(rec.get("life", 0)),
                           "tokens": [int(t) for t in toks]}
                if "trace" in rec:  # Causeway echo
                    payload["trace"] = rec["trace"]
                _publish(ps, f"prog/{rec['request_id']}", payload,
                         op="worker_prog")
        for rec, toks, status in completed:
            trace.on_worker_done(rec, toks, status, host=idx)
            # Lighthouse: the leg fingerprint (seeded by the chain the
            # coordinator dispatched as rec["fp"]) is published BEFORE
            # done — the coordinator's verify at finalize never races
            # the evidence; key/write absent entirely when unarmed
            if status == "done":
                fp_payload = audit.on_worker_done(rec, toks, host=idx)
                if fp_payload is not None:
                    _publish(ps, f"fp/{rec['request_id']}", fp_payload,
                             op="worker_fp")
            # done FIRST, then the wire: the coordinator's handoff
            # rests on the done record alone — see _push_wire
            _publish_done(ps, rec, toks, status)
            if rec.get("stage") == "prefill" and status == "done":
                _push_wire(ps, idx, rec, toks, backend)
        trace.maybe_publish(ps, rank=idx)
        meter.maybe_publish(ps, rank=idx)
        audit.maybe_publish(ps, rank=idx)
        _publish(ps, f"gauge/{idx}", dict(
            queue_depth=len(queue), max_queue=args.max_queue,
            pid=os.getpid(), round=rounds, draining=draining,
            **backend.gauges()), op="worker_gauge")
        if draining and not backend.has_work and not queue:
            return failure.GRACEFUL_EXIT_CODE
        if not backend.has_work:
            time.sleep(idle_s)


def _parse(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="process-fleet replica worker (serve/procfleet.py "
                    "spawns this; not a user-facing CLI)")
    p.add_argument("--store", required=True,
                   help="store endpoint, host:port")
    p.add_argument("--namespace", default="fleet")
    p.add_argument("--replica-index", type=int, required=True)
    p.add_argument("--backend", choices=("stub", "tiny", "preset"),
                   default="stub")
    p.add_argument("--role", choices=("unified", "prefill", "decode"),
                   default="unified",
                   help="this replica's disaggregation pool — routing "
                        "is the coordinator's job; the role drives the "
                        "KV wire push (prefill) / pull (decode) and "
                        "rides the enroll record")
    p.add_argument("--preset", default="",
                   help="config.PRESETS name for --backend preset "
                        "(validated; the error names every preset)")
    p.add_argument("--ckpt", default="",
                   help="optional Orbax params checkpoint for "
                        "--backend preset")
    p.add_argument("--max-slots", type=int, default=4)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--max-seq-len", type=int, default=256)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--token-ms", type=float, default=2.0,
                   help="stub decode pacing per round")
    p.add_argument("--hb-interval", type=float, default=0.1)
    p.add_argument("--progress-window", type=float, default=None)
    p.add_argument("--poll-ms", type=float, default=2.0)
    p.add_argument("--start-k", type=int, default=0,
                   help="first dispatch seq to consume — a restarted "
                        "index resumes the stream where the store "
                        "counter left it, skipping requests the dead "
                        "life already owned (the coordinator re-admits "
                        "those under a new life)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = _parse(argv)
    logging.basicConfig(
        level=logging.INFO,
        format=f"[fleet-worker r{args.replica_index}] %(message)s")
    chaos.maybe_init()
    failure.install_preemption_handler(force=True)
    client = make_store(args.store)
    ps = PrefixStore(client, args.namespace) if args.namespace else client
    idx = int(args.replica_index)
    # arm tracing from TPUNN_TRACE (inherited via worker_env) — each
    # worker process owns its own span ring, published at trace/<idx>
    trace.maybe_init(rank=idx)
    # arm metering from TPUNN_METER (inherited via worker_env) — each
    # worker process bills its own engine, published at meter/<idx>
    meter.maybe_init(rank=idx)
    # arm auditing from TPUNN_AUDIT (inherited, or re-exported by a
    # programmatically-armed coordinator) — leg fingerprints publish
    # at fp/<rid>, the summary at audit/<idx>
    audit.maybe_init(rank=idx)
    reporter = failure.HeartbeatReporter(
        ps, rank=idx, incarnation=0,
        interval_s=args.hb_interval,
        progress_window_s=args.progress_window)
    if args.backend == "stub":
        backend = _StubBackend(max_slots=args.max_slots,
                               token_ms=args.token_ms)
    else:
        model = params = None
        if args.backend == "preset":
            model, params = build_preset_model(args.preset, args.ckpt)
        backend = _EngineBackend(
            max_slots=args.max_slots, max_seq_len=args.max_seq_len,
            block_size=args.block_size, max_queue=args.max_queue,
            tag=f"r{idx}", model=model, params=params)
        # chaos flip@replica=K keys on this (silent-corruption drill)
        backend.engine.replica_index = idx
    # enrollment handshake: tell the coordinator who actually
    # materialized behind this index — for a cross-host spawn
    # (TemplateProvisioner) this record is the ONLY way it learns
    # the pid/host; for a local spawn it is a harmless echo
    _publish(ps, f"enroll/{idx}", dict(
        pid=os.getpid(), host=socket.gethostname(), role=args.role),
        op="worker_enroll")
    code = chaos.CRASH_EXIT_CODE
    try:
        code = _serve_loop(args, ps, idx, reporter, backend)
    except chaos.ReplicaKillError:
        log.warning("replica %d: injected kill", idx)
        code = chaos.CRASH_EXIT_CODE
    except Exception:
        log.exception("replica %d crashed", idx)
        code = chaos.CRASH_EXIT_CODE
    finally:
        reporter.stop()
        try:
            client.close()
        except OSError:
            pass
    return code


if __name__ == "__main__":
    sys.exit(main())
