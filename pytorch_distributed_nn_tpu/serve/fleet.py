"""Replica fleet: supervisor, failover, and rolling weight reload.

One :class:`serve.engine.ServingEngine` is one crash domain — when it
dies, every in-flight stream dies with it. This module turns N engines
into a fleet a request can survive:

- **replicas** — each replica is an engine plus a driver thread (the
  worker), a :class:`runtime.failure.HeartbeatReporter` beating into an
  in-process store, and its own :class:`launch.RestartPolicy` (the
  PR-3 restart governor, reused verbatim: budget window, exponential
  backoff + seeded jitter, free restarts for graceful preemption);
- **admission** — :meth:`Fleet.submit` admits a request exactly once
  fleet-wide: the :class:`serve.router.Router` scores READY replicas by
  KV-block headroom and queue depth (one counted choke point), and the
  chosen replica's own scheduler applies the real backpressure;
- **failover** — every admission is journaled (prompt, budget,
  placement). Replica death is detected two ways: a worker exception
  (chaos ``kill_replica`` raises :class:`runtime.chaos.ReplicaKillError`
  in the driver loop) surfaces on the next :meth:`poll`, and a wedged
  worker (chaos ``hang_replica`` sleeps in the driver loop) stops
  notifying its heartbeat's progress watchdog, so the REAL
  :class:`runtime.failure.FailureDetector` flags the replica stale.
  Either way the fleet marks the replica DEAD (counted state change),
  dumps the flight ring, pages the watchtower (``replica_down``), and
  re-admits each stranded request on a survivor with prompt +
  tokens-emitted-so-far as the new prompt — greedy decode is a pure
  function of the sequence prefix, so the stitched stream is
  bit-identical to an uninterrupted run (golden-tested);
- **rolling reload** — :meth:`Fleet.reload` rolls replicas one at a
  time through the graceful-drain contract: the router stops placing on
  the replica, the worker finishes everything it holds and exits with
  ``failure.GRACEFUL_EXIT_CODE`` (83), the restart policy charges
  nothing (``reason="preempt"``), and a fresh engine rejoins with the
  new params. No request is ever rejected by a reload — draining here
  means "stop feeding", never ``scheduler.drain()``'s queued-reject.

Design contract (lint-enforced by tests/test_quality.py, mirroring the
scheduler's ``_transition``): every replica state change goes through
:meth:`Fleet._set_state`, which bumps
``serve_replica_state_total{state}`` and lands a ``fleet`` event in the
flight ring — replica lifecycle can never drift off the books.

Thread model: client threads call :meth:`submit`; each replica's worker
thread drives only its own engine; one supervisor thread (started by
:meth:`start`) calls :meth:`poll` — exits, staleness, delayed restarts,
ticket finalization — under the fleet lock. Workers never take the
fleet lock, so a wedged replica cannot wedge supervision.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import Optional

import numpy as np

from pytorch_distributed_nn_tpu.launch import RestartPolicy
from pytorch_distributed_nn_tpu.obs import (
    audit,
    flight,
    meter,
    trace,
    watchtower,
)
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.runtime import chaos, failure
from pytorch_distributed_nn_tpu.serve.engine import ServingEngine
from pytorch_distributed_nn_tpu.serve.router import (
    DEAD,
    DRAINING,
    QUARANTINED,
    READY,
    RELOADING,
    STARTING,
    Router,
)
from pytorch_distributed_nn_tpu.serve.scheduler import DONE, REJECTED

from pytorch_distributed_nn_tpu.serve.store import MemStore, PrefixStore

log = logging.getLogger(__name__)

_ids = itertools.count()

# Back-compat alias: the in-process store grew full StoreClient surface
# parity and moved to serve/store.py (tests/test_store_parity.py pins
# it to the real transport op-for-op).
_MemStore = MemStore


class FleetTicket:
    """The client's handle on one fleet-admitted request. Survives
    failover: the underlying per-replica ``Request`` may be replaced,
    ``done``/``tokens`` here are the logical request's."""

    def __init__(self, request_id: str, prompt: np.ndarray,
                 max_new_tokens: int,
                 deadline_s: Optional[float],
                 tenant: str = "default") -> None:
        self.request_id = request_id
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        # Abacus (obs/meter.py): the billing identity every leg of this
        # logical request carries — a disagg prefill leg and its decode
        # leg, or a failover re-admission, all bill the same tenant
        self.tenant = str(tenant)
        self.t_submit = time.monotonic()
        self.t_first_token = 0.0
        self.t_done = 0.0
        # tokens emitted by dead replicas, re-fed as prompt suffix on
        # re-admission; final tokens = prefix + surviving life's output
        self.prefix: list[int] = []
        self.failovers: list[dict] = []
        self.status = "pending"  # pending | done | rejected | failed
        self.reject_reason = ""
        self.tokens: Optional[np.ndarray] = None
        self.done = threading.Event()
        self._attempt: Optional[tuple[int, object]] = None
        # disaggregated fleets (serve/disagg.py): which leg the current
        # attempt runs — "" (unified), "prefill", or "decode"
        self.stage = ""
        # Causeway (obs/trace.py): the logical request's TraceContext,
        # re-linked (leg+1, parent=previous root span) on every
        # resubmission; None when unarmed or unsampled
        self.trace = None
        # Prism (serve/decoding.py): the request's DecodeSpec (None =
        # greedy, byte-identity path) — every leg (failover, shadow,
        # referee, disagg decode) carries the same spec so seeded
        # sampling reproduces deterministically across legs
        self.decode = None
        self.n_best = None  # ranked [{branch, tokens, logprob}] (best-of-n)

    @property
    def ok(self) -> bool:
        return self.status == "done"

    @property
    def ttft_s(self) -> float:
        return (self.t_first_token - self.t_submit
                if self.t_first_token else -1.0)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        """Block for the tokens; None on timeout or a non-DONE end."""
        if not self.done.wait(timeout):
            return None
        return self.tokens if self.ok else None


class _ReplicaWorker:
    """One replica's driver thread: pump the engine, heartbeat
    progress, honor stop/preempt, and run the chaos replica drill."""

    def __init__(self, index: int, engine: ServingEngine,
                 reporter: failure.HeartbeatReporter,
                 idle_wait_s: float) -> None:
        self.index = index
        self.engine = engine
        self.reporter = reporter
        self.idle_wait_s = idle_wait_s
        self.started_at = time.monotonic()
        self.exit_reason: Optional[str] = None  # ok | preempt | crash
        self.exit_code: Optional[int] = None
        self.error: Optional[BaseException] = None
        # set on the first progress beat from inside the loop: the
        # join gate's proof the driver thread is actually pumping (the
        # reporter's constructor beat is synchronous in the spawning
        # thread and proves nothing about this one)
        self.progressed = threading.Event()
        self._stop = threading.Event()
        self._preempt = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-r{index}", daemon=True)

    def start(self) -> None:
        self.started_at = time.monotonic()
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def request_stop(self) -> None:
        """Hard stop (death declared / fleet shutdown): the loop exits
        before its next engine touch — a thread waking from an injected
        hang must never step an engine its successor replaced."""
        self._stop.set()

    def request_preempt(self) -> None:
        """Graceful-drain notice (rolling reload): finish everything
        the engine holds, then exit ``GRACEFUL_EXIT_CODE`` — the
        thread-world analog of the PR-3 SIGTERM/exit-83 contract."""
        self._preempt.set()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def _run(self) -> None:
        code, reason = 0, "ok"
        try:
            while not self._stop.is_set():
                # chaos kill/hang drill, outside the engine's lint-
                # guarded hot loop; may raise (kill) or block (hang)
                chaos.on_replica_round(self.index,
                                       self.engine.scheduler.round + 1)
                if self._stop.is_set():
                    break  # declared dead while hung: hands off
                self.reporter.notify_progress()
                if not self.progressed.is_set():
                    self.progressed.set()
                if self._preempt.is_set() and not self.engine.has_work:
                    code, reason = failure.GRACEFUL_EXIT_CODE, "preempt"
                    break
                if self.engine.has_work:
                    self.engine.step()
                else:
                    time.sleep(self.idle_wait_s)
        except BaseException as e:  # noqa: BLE001 — any death is a crash
            code, reason = chaos.CRASH_EXIT_CODE, "crash"
            self.error = e
            log.warning("fleet replica %d crashed: %r", self.index, e)
        self.exit_code, self.exit_reason = code, reason


@dataclasses.dataclass
class ReplicaHandle:
    """The fleet's book entry for one replica slot. ``state`` is
    written ONLY by :meth:`Fleet._set_state` (lint-enforced)."""

    index: int
    name: str
    policy: RestartPolicy
    engine: Optional[ServingEngine] = None
    worker: Optional[_ReplicaWorker] = None
    reporter: Optional[failure.HeartbeatReporter] = None
    state: str = ""
    incarnations: int = 0
    restart_at: Optional[float] = None
    stop_reason: str = ""
    # join gate (live fleets): a replica entering mid-traffic stays
    # STARTING — invisible to the router — until its warmup jits are
    # compiled AND its worker has beaten progress from inside the loop
    warm_done: bool = True
    # scale-down: draining toward removal; reaped by poll() once empty
    retiring: bool = False
    # pool class (serve/disagg.py): "unified" | "prefill" | "decode";
    # the router's stage= filter keys on this
    role: str = "unified"


class Fleet:
    """N serving replicas behind one admission point."""

    def __new__(cls, *args, **kwargs):
        # ``Fleet(prefill=P, decode=D)`` is the disaggregated
        # constructor: swap in the subclass (serve/disagg.py) so every
        # call site that builds a Fleet today opts into split pools
        # with two kwargs instead of a new import.
        if cls is Fleet and ("prefill" in kwargs
                             or "decode" in kwargs):
            from pytorch_distributed_nn_tpu.serve.disagg import (
                DisaggFleet,
            )
            return super().__new__(DisaggFleet)
        return super().__new__(cls)

    def __init__(self, model, params, *, replicas: int = 2,
                 max_slots: int = 4, max_seq_len: int = 256,
                 block_size: int = 16, max_queue: int = 64,
                 max_prefills_per_round: int = 2,
                 eos_token: Optional[int] = None, metrics=None,
                 max_restarts: int = 3,
                 restart_window_s: Optional[float] = None,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 heartbeat_interval_s: float = 0.1,
                 heartbeat_timeout_s: float = 10.0,
                 progress_window_s: Optional[float] = None,
                 idle_wait_s: float = 0.002,
                 poll_interval_s: float = 0.01,
                 store=None, namespace: str = "") -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.model = model
        self.params = params
        self.metrics = metrics
        self.eos_token = eos_token
        self._engine_kw = dict(
            max_slots=max_slots, max_seq_len=max_seq_len,
            block_size=block_size, max_queue=max_queue,
            max_prefills_per_round=max_prefills_per_round)
        self._hb_interval = heartbeat_interval_s
        self._hb_timeout = heartbeat_timeout_s
        self._policy_kw = dict(
            max_restarts=max_restarts, window_s=restart_window_s,
            backoff_base_s=backoff_base_s, backoff_max_s=backoff_max_s)
        self._progress_window = (progress_window_s
                                 if progress_window_s is not None
                                 else max(heartbeat_timeout_s / 2,
                                          2 * heartbeat_interval_s))
        self._idle_wait = idle_wait_s
        self._poll_interval = poll_interval_s
        self.router = Router()
        # Heartbeat transport: in-process by default; pass ``store=``
        # (e.g. a runtime.native.StoreClient) to beat through the real
        # wire instead — the protocol is identical either way (the
        # store-parity suite guarantees it). ``namespace`` scopes every
        # key under ``<namespace>/`` so one physical store can host
        # many fleets (and the process-backed fleet's coordinator
        # state) without collisions.
        base_store = store if store is not None else MemStore()
        self._store = (PrefixStore(base_store, namespace)
                       if namespace else base_store)
        self._detector = failure.FailureDetector(
            self._store, ranks=list(range(replicas)), incarnation=0,
            timeout_s=heartbeat_timeout_s)
        self._lock = threading.RLock()
        self._journal: dict[str, FleetTicket] = {}
        self.completed: list[dict] = []
        self.failovers = 0
        # Lighthouse (obs/audit.py) shadow-replay bookkeeping: pending
        # comparisons keyed by the primary's request id. Empty forever
        # on an unarmed process (shadow_sampled is always False).
        self._shadows: dict[str, dict] = {}
        self._referees: dict[str, tuple[int, object]] = {}
        self._probes: list[tuple[int, object]] = []
        self._probe_n = 0
        self._last_probe_t = time.monotonic()
        reg = get_registry()
        self._c_replica_state = reg.counter(
            "serve_replica_state_total", "replica state transitions",
            labels=("state",))
        self._replicas: list[ReplicaHandle] = []
        for i in range(replicas):
            h = self._new_handle(i)
            self._replicas.append(h)
            self._set_state(h, STARTING, reason="init")
            self._spawn(h, params)
            self._set_state(h, READY, reason="up")
        self._target_replicas = replicas
        self._next_index = replicas
        self._started = False
        self._sup_stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None

    # -- the single replica-state choke point ------------------------------

    def _set_state(self, h: ReplicaHandle, state: str,
                   reason: str = "") -> None:
        """EVERY replica state change funnels through here (lint-
        enforced): the ``serve_replica_state_total{state}`` counter and
        the flight ring can't drift from the fleet's actual shape."""
        h.state = state
        self._c_replica_state.inc(state=state)
        flight.record("fleet", f"state:{state}",
                      note=f"{h.name} {reason}".strip())
        if self.metrics is not None:
            self.metrics.emit("fleet_state", replica=h.index,
                              state=state, reason=reason)

    # -- replica lifecycle -------------------------------------------------

    def _new_handle(self, index: int) -> ReplicaHandle:
        return ReplicaHandle(
            index=index, name=f"r{index}",
            policy=RestartPolicy(seed=index, **self._policy_kw))

    def _rebuild_detector(self) -> None:
        """Point the failure detector at the current membership.
        Replica indexes are never reused (``_next_index`` is monotonic)
        so a retired slot's stale heartbeat keys can't alias a newer
        replica's."""
        self._detector = failure.FailureDetector(
            self._store, ranks=[h.index for h in self._replicas],
            incarnation=0, timeout_s=self._hb_timeout)

    def _spawn(self, h: ReplicaHandle, params) -> None:
        """Fresh engine + heartbeat + worker for one replica slot (first
        start, post-crash restart, or post-reload rejoin)."""
        h.engine = ServingEngine(
            self.model, params, eos_token=self.eos_token,
            metrics=self.metrics, tag=h.name, **self._engine_kw)
        # chaos flip@replica=K keys on the fleet index (obs/audit.py
        # silent-corruption drill); standalone engines keep 0
        h.engine.replica_index = h.index
        h.reporter = failure.HeartbeatReporter(
            self._store, rank=h.index, incarnation=0,
            interval_s=self._hb_interval,
            progress_window_s=self._progress_window)
        h.worker = _ReplicaWorker(h.index, h.engine, h.reporter,
                                  self._idle_wait)
        h.incarnations += 1
        h.restart_at = None
        if getattr(self, "_started", False):
            h.worker.start()

    def _admit_joining(self, h: ReplicaHandle, *,
                       reason: str) -> None:
        """Bring a freshly spawned replica into the routable set. On a
        stopped fleet that is immediate (``run_until_idle`` drives the
        engine directly; there is no cold compile to misread as a
        hang). On a live fleet the replica stays STARTING — the router
        never places on it — until the join gate opens: its warmup jits
        compiled (a background warm thread; the jit cache is keyed on
        the model, so an already-warm fleet passes in microseconds) AND
        its worker has beaten progress from inside the driver loop.
        :meth:`_promote_joining` flips it READY on a later poll."""
        if not self._started:
            h.warm_done = True
            self._set_state(h, READY, reason=reason)
            return
        h.warm_done = False
        engine = h.engine

        def _warm() -> None:
            try:
                self.warmup(engine=engine)
            except Exception:
                # open the gate anyway: a genuinely broken replica
                # surfaces through the normal crash/staleness paths
                log.exception("fleet: warmup for %s failed", h.name)
            h.warm_done = True

        threading.Thread(target=_warm, name=f"fleet-warm-{h.name}",
                         daemon=True).start()

    def _promote_joining(self) -> None:
        """Open the join gate: STARTING replicas whose warmup finished
        and whose worker proved liveness become READY (routable)."""
        for h in self._replicas:
            if (h.state == STARTING and h.warm_done
                    and not h.retiring and h.worker is not None
                    and h.worker.progressed.is_set()):
                self._set_state(h, READY, reason="join:warm+beat")

    def warmup(self, prompt_lens=(8,), *, engine=None) -> None:
        """Compile the serve jits (prefill per prompt bucket, row
        insert, the batched decode step) before any worker thread
        runs them. Without this, the first decode on a cold process
        stalls a worker for the whole XLA compile — long enough to
        starve its progress watchdog and read as a hang to the failure
        detector (a false replica_down on a healthy fleet). One
        throwaway forward per bucket; the jit cache is keyed on the
        model so every replica shares the result."""
        from pytorch_distributed_nn_tpu.serve.engine import (
            _bucket_len,
            _fresh_cache,
            _insert_row,
            _serve_prefill,
            _serve_step,
        )
        import jax.numpy as jnp
        eng = engine if engine is not None else self._replicas[0].engine
        max_slots = eng.max_slots
        cache = _fresh_cache(self.model, max_slots, eng.max_seq_len)
        for plen in prompt_lens:
            pad = min(_bucket_len(int(plen)), eng.max_seq_len)
            row = _fresh_cache(self.model, 1, pad)
            _, row = _serve_prefill(
                self.model, self.params, row,
                jnp.zeros((1, pad), jnp.int32),
                jnp.asarray([int(plen)], jnp.int32),
                jnp.zeros((1,), jnp.int32))
            cache = _insert_row(cache, row, 0)
        nxt, _, _ = _serve_step(
            self.model, self.params, cache,
            jnp.zeros((max_slots,), jnp.int32),
            jnp.zeros((max_slots,), jnp.int32),
            jnp.zeros((max_slots,), bool))
        np.asarray(nxt)  # block until compiled + executed

    def start(self, *, warmup_prompt_lens=(8,)) -> "Fleet":
        """Start every replica's worker plus the supervisor thread.
        Compiles the serve jits first (see :meth:`warmup`) so a cold
        process cannot misread compilation as a hung replica; pass
        ``warmup_prompt_lens=()`` to skip."""
        if self._started:
            return self
        if warmup_prompt_lens:
            self.warmup(warmup_prompt_lens)
        self._started = True
        for h in self._replicas:
            if h.worker is not None and not h.worker.alive \
                    and h.worker.exit_reason is None:
                h.worker.start()
        self._sup_thread = threading.Thread(
            target=self._supervise, name="fleet-supervisor", daemon=True)
        self._sup_thread.start()
        return self

    def _supervise(self) -> None:
        while not self._sup_stop.wait(self._poll_interval):
            try:
                self.poll()
            except Exception:  # supervision must outlive any one fault
                log.exception("fleet poll failed")

    def stop(self) -> None:
        """Shut the fleet down: stop workers, finish in-flight work
        synchronously, reject whatever is still queued (``draining``),
        release heartbeats, finalize every ticket."""
        if self._sup_thread is not None:
            self._sup_stop.set()
            self._sup_thread.join(timeout=5.0)
            self._sup_thread = None
        for h in self._replicas:
            if h.worker is not None and h.worker.alive:
                h.worker.request_stop()
                h.worker.join(timeout=5.0)
            if h.state not in (DEAD,):
                self._set_state(h, DRAINING, reason="stop")
                if h.engine is not None and not (
                        h.worker is not None and h.worker.alive):
                    h.engine.drain()
            if h.reporter is not None:
                h.reporter.stop()
        self._started = False
        self.poll()

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface ----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               tenant: str = "default",
               decode=None) -> FleetTicket:
        """Admit once, place once (router-scored), journal for
        failover. Always returns a ticket; a rejected one is already
        terminal. ``decode`` (a :class:`serve.decoding.DecodeSpec`)
        rides the ticket so every leg — failover, shadow, referee,
        disagg decode — reproduces the same seeded stream."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ticket = FleetTicket(
            request_id or f"freq-{next(_ids)}", prompt,
            max_new_tokens, deadline_s, tenant=tenant)
        ticket.decode = decode
        # Causeway mint point: the context outlives every per-replica
        # Request this ticket will spawn
        ticket.trace = trace.on_submit(ticket.request_id)
        with self._lock:
            self._journal[ticket.request_id] = ticket
            placed = self._place(ticket, prompt, int(max_new_tokens),
                                 resubmit=False)
            # Lighthouse shadow replay: a deterministic request-id-hash
            # sample runs AGAIN on a second replica; the fingerprint
            # compare happens in _audit_poll once both legs finish.
            # Inert one-call no-op unless TPUNN_AUDIT armed it.
            if placed is not None \
                    and audit.shadow_sampled(ticket.request_id):
                self._submit_shadow(ticket, prompt,
                                    int(max_new_tokens),
                                    primary=placed)
        return ticket

    def generate(self, prompt, max_new_tokens: int,
                 timeout: Optional[float] = None):
        """Blocking convenience: submit + wait, tokens or None."""
        ticket = self.submit(prompt, max_new_tokens)
        if not self._started:
            self.run_until_idle()
        return ticket.result(timeout)

    def run_until_idle(self) -> None:
        """Synchronous drive (fleet not started): round-robin every
        live engine until all queues and batches are empty, finalizing
        tickets as they finish. Deterministic — tests use this."""
        while True:
            busy = False
            for h in self._replicas:
                if h.state in (READY, DRAINING, RELOADING) \
                        and h.engine is not None and h.engine.has_work:
                    h.engine.step()
                    busy = True
            self.poll()
            if busy:
                continue
            # poll() itself can create work after an idle sweep — a
            # failover re-admission or a disagg prefill->decode handoff
            # lands new queue entries — so only an idle sweep FOLLOWED
            # by an idle poll terminates
            if any(h.state in (READY, DRAINING, RELOADING)
                   and h.engine is not None and h.engine.has_work
                   for h in self._replicas):
                continue
            return

    # -- placement ---------------------------------------------------------

    def _place(self, ticket: FleetTicket, prompt: np.ndarray,
               max_new: int, *, resubmit: bool) -> Optional[int]:
        """One admission attempt through the router (caller holds the
        fleet lock). Terminalizes the ticket when no replica is ready
        or the chosen replica rejects. Returns the replica index that
        accepted the request, None otherwise."""
        branches = (ticket.decode.branches
                    if ticket.decode is not None else 1)
        h = self.router.place(self._replicas,
                              len(prompt) + max_new, prompt=prompt,
                              branches=branches)
        if h is None:
            self._finalize_rejected(ticket, "no_replica")
            return None
        req = h.engine.submit(
            prompt, max_new, deadline_s=ticket.deadline_s,
            request_id=ticket.request_id, resubmit=resubmit,
            tenant=ticket.tenant,
            # Prism: the leg samples the SAME (seed, branch, step)
            # lanes, resumed at the step the prefix already covers
            decode=ticket.decode, decode_step0=len(ticket.prefix),
            trace_ctx=ticket.trace, t_origin=ticket.t_submit,
            t_first_origin=ticket.t_first_token,
            # Lighthouse: the leg resumes the chain over the tokens
            # earlier lives already emitted ("" unarmed — key-absent)
            fp_seed=audit.seed_of(ticket.prefix)
            if audit.enabled() else "")
        ticket._attempt = (h.index, req)
        if req.done.is_set() and req.state == REJECTED:
            self._finalize_rejected(ticket, req.reject_reason)
            return None
        return h.index

    # -- supervision -------------------------------------------------------

    def poll(self) -> None:
        """One supervision pass: crashed workers, stale heartbeats,
        due restarts, finished tickets. Thread-safe; the supervisor
        thread calls it continuously once :meth:`start` has run."""
        with self._lock:
            self._check_exits()
            self._check_stale()
            self._restart_due()
            self._promote_joining()
            self._reap_retiring()
            self._finalize_tickets()
            # Lighthouse: golden probes at idle cadence + pending
            # shadow/referee fingerprint comparisons. Both are inert
            # one-call no-ops unless TPUNN_AUDIT armed the process.
            self._maybe_probe()
            self._audit_poll()

    def _check_exits(self) -> None:
        for h in self._replicas:
            if (h.state != DEAD and h.worker is not None
                    and h.worker.exit_reason == "crash"):
                err = h.worker.error
                self._fail_replica(
                    h, kind="crash",
                    reason=f"crash:{type(err).__name__}"
                    if err is not None else "crash")

    def _check_stale(self) -> None:
        alive = {h.index for h in self._replicas
                 if h.state != DEAD and h.worker is not None
                 and h.worker.alive and h.worker.exit_reason is None}
        if not alive:
            return
        by_index = {h.index: h for h in self._replicas}
        for idx in self._detector.stale_ranks(alive=alive):
            self._fail_replica(by_index[idx], kind="hang",
                               reason="hang:heartbeat_stale")

    def _fail_replica(self, h: ReplicaHandle, *, kind: str,
                      reason: str) -> None:
        """The failover core: declare the replica dead (counted), dump
        the ring, page the watchtower, re-admit every stranded request
        on a survivor, and schedule the restart the policy allows."""
        stranded = self._stranded_of(h)
        ids = [t.request_id for t, _ in stranded]
        self._set_state(h, DEAD, reason=reason)
        if h.worker is not None:
            h.worker.request_stop()
        if h.reporter is not None:
            h.reporter.stop()
        flight.record("fleet", "replica_down",
                      note=f"{h.name} reason={reason} "
                           f"stranded={','.join(ids)}")
        # a dead replica is a post-mortem: the ring must reach disk now
        flight.dump_now(f"replica_down:{h.name}", force=True)
        watchtower.on_replica_down(h.index, reason, ids)
        if self.metrics is not None:
            self.metrics.emit("fleet_replica_down", replica=h.index,
                              reason=reason, stranded=ids)
        log.warning("fleet: replica %s down (%s), re-admitting %d "
                    "stranded request(s)", h.name, reason, len(ids))
        # Lighthouse legs on the dead replica can never finish — drop
        # their pending comparisons (shadows are never journaled, so
        # the failover machinery above does not touch them)
        self._shadows = {rid: p for rid, p in self._shadows.items()
                         if p["sidx"] != h.index}
        self._referees = {rid: r for rid, r in self._referees.items()
                          if r[0] != h.index}
        self._probes = [(i, r) for i, r in self._probes
                        if i != h.index]
        t_detect = time.monotonic()
        for ticket, emitted in stranded:
            self._readmit(ticket, emitted, from_replica=h.index,
                          t_detect=t_detect, reason=reason)
        worker = h.worker
        duration = (time.monotonic() - worker.started_at
                    if worker is not None else 0.0)
        code = (worker.exit_code if worker is not None
                and worker.exit_code is not None
                else chaos.CRASH_EXIT_CODE)
        decision = h.policy.on_exit(
            reason=kind, code=code, duration_s=duration,
            beat_seen=True)
        if decision.action == "restart":
            h.restart_at = time.monotonic() + decision.delay_s
        else:
            h.restart_at = None
            h.stop_reason = decision.why
            log.warning("fleet: replica %s stays down: %s", h.name,
                        decision.why)

    def _stranded_of(self, h: ReplicaHandle) -> list[tuple]:
        """(ticket, tokens-emitted-so-far) for every journaled request
        whose current life sits on this replica and isn't terminal —
        running requests recover their slot's emitted tokens, queued
        ones restart from the bare prompt."""
        out = []
        for ticket in self._journal.values():
            if ticket.done.is_set() or ticket._attempt is None:
                continue
            idx, req = ticket._attempt
            if idx != h.index or req.done.is_set():
                continue  # terminal lives finalize normally
            emitted: list[int] = []
            branched = (ticket.decode is not None
                        and ticket.decode.branches > 1)
            if h.engine is not None and not branched:
                # best-of-n requests restart from the bare prompt: one
                # branch's tail is not "the" stream, and deterministic
                # seeding re-derives every branch identically anyway
                for slot in h.engine._slots:
                    if slot is not None and slot.req is req:
                        emitted = [int(t) for t in slot.tokens]
                        break
            if emitted and ticket.t_first_token == 0.0:
                ticket.t_first_token = req.t_first_token
            out.append((ticket, emitted))
        return out

    def _readmit(self, ticket: FleetTicket, emitted: list[int], *,
                 from_replica: int, t_detect: float,
                 reason: str) -> None:
        """Re-admit one stranded request on a survivor: prompt +
        emitted-so-far becomes the new prompt (greedy re-decode is
        output-invariant), the remaining budget the new max_new."""
        ticket.prefix.extend(emitted)
        remaining = ticket.max_new_tokens - len(ticket.prefix)
        if remaining <= 0:  # stream was already complete; just stitch
            self._finalize_done(ticket, from_replica)
            return
        new_prompt = ticket.prompt
        if ticket.prefix:
            new_prompt = np.concatenate(
                [ticket.prompt,
                 np.asarray(ticket.prefix, np.int32)])
        self.failovers += 1
        # Causeway: the re-admitted leg gets a linked child context
        # (same trace_id, leg+1, parent = the dead leg's root span)
        nxt = trace.on_resubmit(ticket.trace)
        if nxt is not None:
            ticket.trace = nxt
        placed = self._place(ticket, new_prompt, remaining,
                             resubmit=True)
        readmit_s = time.monotonic() - t_detect
        trace.on_segment(ticket.trace, "failover", t_detect,
                         time.monotonic(),
                         request_id=ticket.request_id,
                         from_replica=from_replica, reason=reason)
        to_replica = placed if placed is not None else -1
        fo = dict(from_replica=from_replica, to_replica=to_replica,
                  reason=reason, readmit_s=round(readmit_s, 6),
                  prefix_tokens=len(ticket.prefix))
        ticket.failovers.append(fo)
        flight.record("fleet", "readmit",
                      note=f"{ticket.request_id} r{from_replica}->"
                           f"r{to_replica} prefix={len(ticket.prefix)}")
        if self.metrics is not None:
            self.metrics.emit("fleet_failover",
                              request_id=ticket.request_id, **fo)

    # -- Lighthouse output-integrity auditing (obs/audit.py) ---------------

    def _submit_shadow(self, ticket: FleetTicket, prompt: np.ndarray,
                       max_new: int, *, primary: int) -> None:
        """Duplicate one sampled request onto a second READY replica
        (caller holds the fleet lock). The shadow leg rides the
        reserved audit tenant — never billed, never TTFT-observed
        (``t_first_origin`` pre-set) — and is not journaled: it can
        never fail over, only finish or die with its replica."""
        h = self.router.place_shadow(
            self._replicas, len(prompt) + max_new,
            exclude=primary, prompt=prompt)
        if h is None:
            return  # single-replica fleet: nothing to compare against
        try:
            req = h.engine.submit(
                prompt, max_new,
                request_id=ticket.request_id + "#shadow",
                tenant=audit.SHADOW_TENANT,
                # Prism: the shadow leg samples the same seeded lanes,
                # so sampled streams are comparable fingerprints too
                decode=ticket.decode,
                t_first_origin=ticket.t_submit)
        except ValueError:
            return
        if req.done.is_set() and req.state == REJECTED:
            return
        self._shadows[ticket.request_id] = dict(
            ticket=ticket, sreq=req, sidx=h.index)

    def _maybe_probe(self) -> None:
        """Push the canned golden probe through every READY replica at
        ``probe_every_s`` cadence, only when the fleet is idle — the
        probe audits capacity that real traffic (and the shadow
        sample) is not reaching; it must never displace a customer."""
        every = audit.probe_interval()
        if not every:
            return
        now = time.monotonic()
        if now - self._last_probe_t < every:
            return
        if any(h.state == READY and h.engine is not None
               and h.engine.has_work for h in self._replicas):
            return  # not idle; try again next poll
        self._last_probe_t = now
        self._probe_n += 1
        for h in self._replicas:
            if h.state != READY or h.engine is None:
                continue
            try:
                req = h.engine.submit(
                    np.asarray(audit.PROBE_PROMPT, np.int32),
                    audit.PROBE_BUDGET,
                    request_id=f"auditprobe-{self._probe_n}-r{h.index}",
                    tenant=audit.SHADOW_TENANT,
                    t_first_origin=now)
            except ValueError:
                continue
            self._probes.append((h.index, req))

    def _audit_poll(self) -> None:
        """Settle pending audit comparisons (caller holds the fleet
        lock): finished probes against the golden, finished shadow
        pairs against each other — a mismatch launches a third
        *referee* leg and the majority names the suspect."""
        if not audit.enabled():
            return
        for idx, req in list(self._probes):
            if not req.done.is_set():
                continue
            try:
                self._probes.remove((idx, req))
            except ValueError:
                continue  # purged by a quarantine earlier this sweep
            if req.state != DONE or req.tokens is None:
                continue  # shed probe: no integrity evidence either way
            fp = audit.chain("", req.tokens)
            if not audit.on_probe_result("p0", f"r{idx}", fp):
                self._confirm_divergence(
                    "probe", request_id=req.request_id,
                    pair=(f"r{idx}",), suspect_idx=idx,
                    note="golden mismatch")
        for rid, pend in list(self._shadows.items()):
            if rid not in self._shadows:
                continue  # purged by a quarantine earlier this sweep
            ticket, sreq = pend["ticket"], pend["sreq"]
            sidx = pend["sidx"]
            if not (sreq.done.is_set() and ticket.done.is_set()):
                continue
            if ticket.status != "done" or sreq.state != DONE \
                    or sreq.tokens is None or ticket.tokens is None:
                self._shadows.pop(rid, None)  # a shed leg proves nothing
                self._referees.pop(rid, None)
                continue
            pidx = (ticket._attempt[0] if ticket._attempt is not None
                    else -1)
            pfp = audit.chain("", ticket.tokens)
            sfp = audit.chain("", sreq.tokens)
            if pfp == sfp:
                self._shadows.pop(rid, None)
                continue
            ref = self._referees.get(rid)
            if ref is None:
                # two-way disagreement: a third leg on a replica
                # outside the pair breaks the tie by majority
                h = self.router.place_shadow(
                    self._replicas,
                    len(ticket.prompt) + ticket.max_new_tokens,
                    exclude=(pidx, sidx), prompt=ticket.prompt)
                rreq = None
                if h is not None:
                    try:
                        rreq = h.engine.submit(
                            ticket.prompt, ticket.max_new_tokens,
                            request_id=rid + "#referee",
                            tenant=audit.SHADOW_TENANT,
                            decode=ticket.decode,
                            t_first_origin=time.monotonic())
                    except ValueError:
                        rreq = None
                if rreq is None:
                    # no third replica: blame the primary
                    # (conservative — the customer-facing leg is the
                    # one whose output we cannot vouch for)
                    self._settle_shadow(rid, ticket, sreq,
                                        pidx=pidx, sidx=sidx,
                                        suspect_idx=pidx)
                    continue
                self._referees[rid] = (h.index, rreq)
                continue
            _ridx, rreq = ref
            if not rreq.done.is_set():
                continue
            rfp = (audit.chain("", rreq.tokens)
                   if rreq.state == DONE and rreq.tokens is not None
                   else "")
            # majority: the leg the referee agrees with is honest;
            # three-way disagreement blames the primary (conservative)
            suspect_idx = sidx if rfp == pfp else pidx
            self._settle_shadow(rid, ticket, sreq, pidx=pidx,
                                sidx=sidx, suspect_idx=suspect_idx)

    def _settle_shadow(self, rid: str, ticket: FleetTicket, sreq, *,
                       pidx: int, sidx: int,
                       suspect_idx: int) -> None:
        """A confirmed shadow divergence: page + quarantine, and when
        the PRIMARY leg is the suspect, repair the client-facing
        tokens with the majority (shadow) output — the customer gets
        the honest stream even though the diverging replica already
        'finished' the request."""
        self._shadows.pop(rid, None)
        self._referees.pop(rid, None)
        repaired = False
        if suspect_idx == pidx and sreq.tokens is not None:
            ticket.tokens = np.asarray(sreq.tokens, np.int32)
            repaired = True
        self._confirm_divergence(
            "shadow", request_id=rid,
            pair=(f"r{pidx}", f"r{sidx}"), suspect_idx=suspect_idx,
            note="repaired" if repaired else "")

    def _confirm_divergence(self, kind: str, *, request_id: str,
                            pair, suspect_idx: int,
                            note: str = "") -> None:
        """Record + page one confirmed divergence, then quarantine the
        suspect (policy-gated). The watchtower page auto-dumps the
        flight ring and triggers an Xray capture — evidence first,
        isolation second."""
        audit.on_divergence(kind, request_id=request_id, pair=pair,
                            suspect=f"r{suspect_idx}", note=note)
        watchtower.on_output_divergence(
            kind, request_id=request_id, pair=pair,
            suspect=f"r{suspect_idx}")
        if not audit.quarantine_enabled():
            return
        h = next((x for x in self._replicas
                  if x.index == suspect_idx), None)
        if h is not None:
            self._quarantine_replica(
                h, reason=f"{kind}_divergence:{request_id}")

    def _quarantine_replica(self, h: ReplicaHandle, *,
                            reason: str) -> None:
        """Isolate a confirmed-diverging replica: QUARANTINED through
        the counted choke point (router excludes it exactly like
        DEAD), worker stopped, in-flight requests re-admitted on
        survivors through the existing failover machinery — and NO
        restart, ever: the process passes every liveness check, which
        is exactly why it must not serve."""
        if h.state in (DEAD, QUARANTINED):
            return
        stranded = self._stranded_of(h)
        ids = [t.request_id for t, _ in stranded]
        self._set_state(h, QUARANTINED, reason=reason)
        if h.worker is not None:
            h.worker.request_stop()
        if h.reporter is not None:
            h.reporter.stop()
        h.restart_at = None
        h.stop_reason = f"quarantined:{reason}"
        audit.on_quarantine(h.name, reason)
        flight.record("fleet", "quarantine",
                      note=f"{h.name} reason={reason} "
                           f"stranded={','.join(ids)}")
        flight.dump_now(f"quarantine:{h.name}", force=True)
        if self.metrics is not None:
            self.metrics.emit("fleet_quarantine", replica=h.index,
                              reason=reason, stranded=ids)
        log.warning("fleet: replica %s QUARANTINED (%s), re-admitting "
                    "%d in-flight request(s)", h.name, reason,
                    len(ids))
        # audit legs queued on the quarantined replica will never
        # finish (the worker is stopped): drop their comparisons
        self._shadows = {rid: p for rid, p in self._shadows.items()
                         if p["sidx"] != h.index}
        self._referees = {rid: r for rid, r in self._referees.items()
                          if r[0] != h.index}
        self._probes = [(i, r) for i, r in self._probes
                        if i != h.index]
        t_detect = time.monotonic()
        for ticket, emitted in stranded:
            self._readmit(ticket, emitted, from_replica=h.index,
                          t_detect=t_detect,
                          reason=f"quarantine:{reason}")

    def _restart_due(self) -> None:
        now = time.monotonic()
        for h in self._replicas:
            if (h.state == DEAD and not h.retiring
                    and h.restart_at is not None
                    and now >= h.restart_at):
                self._set_state(h, STARTING,
                                reason=f"restart #{h.incarnations}")
                self._spawn(h, self.params)
                self._admit_joining(h, reason="up")

    def _finalize_tickets(self) -> None:
        for ticket in list(self._journal.values()):
            if ticket.done.is_set() or ticket._attempt is None:
                continue
            idx, req = ticket._attempt
            if not req.done.is_set():
                continue
            if req.state == DONE:
                if ticket.t_first_token == 0.0:
                    ticket.t_first_token = req.t_first_token
                self._finalize_done(ticket, idx)
            else:
                self._finalize_rejected(
                    ticket, req.reject_reason or req.state,
                    failed=(req.state == "failed"))

    def _finalize_done(self, ticket: FleetTicket,
                       replica: int) -> None:
        tail = []
        if ticket._attempt is not None:
            _, req = ticket._attempt
            if req.tokens is not None:
                tail = [int(t) for t in req.tokens]
            # Prism best-of-n: the ranked alternates ride the ticket
            # (None for unbranched requests — attribute stays inert)
            ticket.n_best = getattr(req, "n_best", None)
        ticket.tokens = np.asarray(ticket.prefix + tail, np.int32)
        ticket.t_done = time.monotonic()
        ticket.status = "done"
        rec = dict(
            request_id=ticket.request_id,
            prompt_len=len(ticket.prompt),
            new_tokens=len(ticket.tokens),
            ttft_s=round(ticket.ttft_s, 6),
            total_s=round(ticket.t_done - ticket.t_submit, 6),
            replica=f"r{replica}", failovers=ticket.failovers)
        self.completed.append(rec)
        del self._journal[ticket.request_id]
        ticket.done.set()

    def _finalize_rejected(self, ticket: FleetTicket, reason: str,
                           failed: bool = False) -> None:
        ticket.reject_reason = reason
        ticket.t_done = time.monotonic()
        ticket.status = "failed" if failed else "rejected"
        self._journal.pop(ticket.request_id, None)
        ticket.done.set()

    # -- rolling reload ----------------------------------------------------

    def reload(self, params) -> dict:
        """Live weight reload, one replica at a time: exclude from
        placement (RELOADING), graceful-drain the worker (it finishes
        everything it holds, exits ``GRACEFUL_EXIT_CODE``), restart
        with the new params (policy charges nothing: ``preempt``),
        rejoin READY. Under steady load the remaining replicas absorb
        placement the whole time and nothing is ever rejected with
        ``draining`` — this path never calls ``scheduler.drain()``.

        Returns ``{replicas_rolled, skipped_dead}``."""
        rolled, skipped = 0, 0
        self.params = params
        for h in list(self._replicas):
            if h.state == DEAD:
                skipped += 1  # a later restart picks up self.params
                continue
            with self._lock:
                self._set_state(h, RELOADING, reason="reload")
            worker = h.worker
            if worker is not None and worker.alive:
                worker.request_preempt()
                worker.join(timeout=120.0)
                if worker.alive:
                    raise RuntimeError(
                        f"fleet reload: replica {h.name} did not "
                        f"drain in time")
            else:
                # synchronous fleet: drain by stepping in place
                while h.engine is not None and h.engine.has_work:
                    h.engine.step()
                self.poll()
            with self._lock:
                duration = (time.monotonic() - worker.started_at
                            if worker is not None else 0.0)
                h.policy.on_exit(
                    reason="preempt", code=failure.GRACEFUL_EXIT_CODE,
                    duration_s=duration, beat_seen=True)
                if h.reporter is not None:
                    h.reporter.stop()
                self._spawn(h, params)
                self._set_state(h, READY, reason="reloaded")
                rolled += 1
            flight.record("fleet", "reload", note=f"{h.name} rejoined")
        if self.metrics is not None:
            self.metrics.emit("fleet_reload", replicas=rolled,
                              skipped_dead=skipped)
        return dict(replicas_rolled=rolled, skipped_dead=skipped)

    # -- elastic scaling ---------------------------------------------------

    def scale_to(self, n: int, *, reason: str = "") -> dict:
        """Resize the replica set to ``n`` slots — the Helm
        autoscaler's actuator (:mod:`serve.autoscale`), equally usable
        by hand.

        Scale **up** appends fresh slots (monotonic indexes, never
        reused) and admits each through the join gate: on a live fleet
        a joiner stays STARTING — unroutable — until its warmup jits
        compile and its worker beats progress, so a cold compile can
        never read as a hang or swallow a routed request. Scale
        **down** retires the highest-index non-retiring slots through
        the reload-style graceful drain: DRAINING (the router stops
        placing immediately), the worker finishes everything the
        engine holds and exits ``GRACEFUL_EXIT_CODE``, and a later
        :meth:`poll` reaps the empty slot — this path never calls
        ``scheduler.drain()``, so scaling down rejects nothing, ever.

        Retiring slots no longer count toward the fleet's size intent,
        so ``scale_to(2)`` on a 4-replica fleet followed by
        ``scale_to(3)`` before the drains finish adds one fresh slot
        rather than resurrecting a draining one (a drain in flight is
        not cancellable without racing its worker's exit).

        Returns ``{target, added, retiring}``."""
        n = int(n)
        if n < 1:
            raise ValueError(f"scale_to: n must be >= 1, got {n}")
        with self._lock:
            current = [h for h in self._scalable() if not h.retiring]
            delta = n - len(current)
            added, retiring = 0, 0
            if delta > 0:
                for _ in range(delta):
                    h = self._new_handle(self._next_index)
                    self._next_index += 1
                    self._replicas.append(h)
                    self._set_state(h, STARTING, reason="scale_up")
                    self._spawn(h, self.params)
                    self._admit_joining(h, reason="scale_up")
                    added += 1
                self._rebuild_detector()
            elif delta < 0:
                doomed = sorted(current, key=lambda r: -r.index)
                for h in doomed[:-delta]:
                    h.retiring = True
                    h.restart_at = None  # a dead slot stays down
                    if h.state != DEAD:
                        self._set_state(h, DRAINING,
                                        reason="scale_down")
                    if h.worker is not None and h.worker.alive:
                        h.worker.request_preempt()
                    retiring += 1
            self._target_replicas = n
            flight.record(
                "fleet", "scale_to",
                note=f"target={n} added={added} retiring={retiring}"
                     + (f" {reason}" if reason else ""))
            if self.metrics is not None:
                self.metrics.emit("fleet_scale", target=n, added=added,
                                  retiring=retiring, reason=reason)
            # idle retirees on a synchronous fleet reap right here
            self._reap_retiring()
        return dict(target=n, added=added, retiring=retiring)

    def _scalable(self) -> list[ReplicaHandle]:
        """The handles ``scale_to``'s size intent counts against. The
        unified fleet scales every slot; the disaggregated fleet
        (:mod:`serve.disagg`) narrows this to the decode pool — decode
        is the KV/bandwidth-bound class Helm's burn-rate evidence
        actually measures."""
        return self._replicas

    def _reap_retiring(self) -> None:
        """Release retired slots whose drain completed: worker exited
        (gracefully — or, on a synchronous fleet, the engine emptied
        under ``run_until_idle``), policy credited as a preemption,
        heartbeat released, handle dropped from the books. Membership
        changed ⇒ the failure detector is rebuilt."""
        done = []
        for h in self._replicas:
            if not h.retiring:
                continue
            if h.state != DEAD:
                if h.worker is not None and h.worker.alive:
                    continue  # still draining
                if h.engine is not None and h.engine.has_work:
                    continue  # synchronous fleet: still being stepped
            done.append(h)
        if not done:
            return
        for h in done:
            if h.worker is not None and h.state != DEAD:
                h.policy.on_exit(
                    reason="preempt", code=failure.GRACEFUL_EXIT_CODE,
                    duration_s=time.monotonic() - h.worker.started_at,
                    beat_seen=True)
            if h.reporter is not None:
                h.reporter.stop()
            self._replicas.remove(h)
            flight.record("fleet", "retired", note=h.name)
        self._rebuild_detector()

    # -- introspection -----------------------------------------------------

    @property
    def replicas(self) -> list[ReplicaHandle]:
        return list(self._replicas)

    @property
    def live_replicas(self) -> int:
        return sum(1 for h in self._replicas if h.state == READY)

    @property
    def target_replicas(self) -> int:
        """The size intent (last ``scale_to`` target, or the
        constructed size) — what the fleet is converging toward."""
        return self._target_replicas

    def summary(self) -> dict:
        """Fleet-lifetime aggregates (bench + fleet_summary JSONL)."""
        per_replica = []
        for h in self._replicas:
            eng = h.engine.summary() if h.engine is not None else {}
            per_replica.append(dict(
                replica=h.name, state=h.state, role=h.role,
                incarnations=h.incarnations,
                budget_restarts=h.policy.budget_restarts,
                preempt_restarts=h.policy.preempt_restarts,
                stop_reason=h.stop_reason, **eng))
        out = dict(
            replicas=len(self._replicas),
            live=self.live_replicas,
            requests_done=len(self.completed),
            in_flight=len(self._journal),
            failovers=self.failovers,
            tokens_out=int(sum(r["new_tokens"]
                               for r in self.completed)),
            per_replica=per_replica,
        )
        if meter.enabled():
            # Abacus rollup: all in-process engines share one module
            # meter, so the singleton's ledgers already cover the fleet
            out["meter"] = meter.summary()
        if audit.enabled():
            out["audit"] = audit.summary()
        return out
