"""Thread-based loopback inference server + synthetic load clients.

The front-end of the serving stack for a single-process deployment (and
for every test): one daemon thread drives :class:`serve.engine
.ServingEngine` rounds, client threads submit through the scheduler's
thread-safe admission path and block on each request's ``done`` event.
No sockets on purpose — the transport is not what this subsystem is
about, and a loopback front-end is what CI can exercise
deterministically under ``JAX_PLATFORMS=cpu``.

Shutdown reuses the PR-3 preemption machinery
(:mod:`runtime.failure`): ``install_sigterm_drain`` arms the SIGTERM
handler (flag-only, flight-ring snapshot), the serve loop polls
``preempt_requested()`` once per round, and on notice it **drains** —
queued requests are rejected (clients unblock with reason
``draining``), in-flight sequences finish their budgets, the loop
exits. ``scripts/serve.py`` then exits ``GRACEFUL_EXIT_CODE`` so an
agent classifies the shutdown exactly like a trainer preemption.

Synthetic clients, both canonical load shapes:

- :func:`open_loop_client` — requests arrive on their own schedule
  (a fixed metronome, or seeded exponential gaps — a true Poisson
  process) regardless of completions: the model of external traffic,
  the one that can actually overload the server (bench.py --serve);
  richer shapes (diurnal, flash crowds, tenant mixes) live in
  :mod:`serve.traffic`;
- :func:`closed_loop_client` — N users, each submits, waits, repeats:
  arrival rate self-throttles to service rate (latency-measurement
  shape, cannot overload).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from pytorch_distributed_nn_tpu.obs import flight, watchtower
from pytorch_distributed_nn_tpu.runtime import failure
from pytorch_distributed_nn_tpu.serve.engine import ServingEngine
from pytorch_distributed_nn_tpu.serve.scheduler import Request


class InferenceServer:
    """Single-threaded engine driver with a thread-safe submit path."""

    def __init__(self, engine: ServingEngine, *,
                 idle_wait_s: float = 0.002) -> None:
        self.engine = engine
        self.idle_wait_s = idle_wait_s
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.preempted = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-loop", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        flight.record("serve", "server_start")
        while not self._stop.is_set():
            if failure.preempt_requested():
                self.preempted = True
                break
            if self.engine.has_work:
                self.engine.step()
            else:
                # park until a submit wakes us (bounded so stop/SIGTERM
                # polls stay live even with no traffic)
                self._wake.wait(self.idle_wait_s)
                self._wake.clear()
        self.engine.drain()
        self._drained.set()
        flight.record("serve", "server_stop",
                      note="preempt" if self.preempted else "stop")

    def stop(self, timeout: float = 60.0) -> None:
        """Graceful stop: drain and join the loop thread."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("serve loop did not drain in time")

    def join_drained(self, timeout: float = 60.0) -> bool:
        """Block until the loop has drained (SIGTERM path)."""
        return self._drained.wait(timeout)

    # -- client surface ----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kw) -> Request:
        req = self.engine.submit(prompt, max_new_tokens, **kw)
        # queue-pressure feed from the CLIENT thread: the watchtower
        # still sees a filling queue even when the engine loop itself
        # is wedged and no more rounds (and round hooks) ever run
        watchtower.on_serve_submit(req.request_id,
                                   self.engine.scheduler.queue_depth,
                                   self.engine.scheduler.max_queue)
        self._wake.set()
        return req

    def generate(self, prompt, max_new_tokens: int,
                 timeout: float = 120.0, **kw) -> Request:
        """Blocking convenience: submit + wait for the terminal state."""
        req = self.submit(prompt, max_new_tokens, **kw)
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {req.request_id} did not "
                               f"finish in {timeout}s")
        return req

    def stream(self, prompt, max_new_tokens: int, **kw):
        """Submit with incremental streaming and return the request's
        :class:`serve.decoding.TokenStream`. The first chunk arriving
        is the client-visible TTFT event; iteration ends when the
        engine retires (or rejects/fails) the request — terminal
        transitions close the stream, so a rejected request yields an
        empty terminated stream, never a hang. The Request rides on
        ``stream.request`` for state/record inspection."""
        req = self.submit(prompt, max_new_tokens, stream=True, **kw)
        req.stream.request = req
        return req.stream


def install_sigterm_drain() -> bool:
    """Arm SIGTERM-as-drain-notice (main thread only). The serve loop
    polls :func:`runtime.failure.preempt_requested` per round and
    drains on notice; the CLI exits ``GRACEFUL_EXIT_CODE``."""
    return failure.install_preemption_handler(force=True)


# ---------------------------------------------------------------------------
# Synthetic load clients
# ---------------------------------------------------------------------------


def ragged_prompt_sampler(vocab_size: int, *, min_len: int = 4,
                          max_len: int = 48, seed: int = 0
                          ) -> Callable[[], np.ndarray]:
    """Deterministic ragged-length prompt stream (the workload shape
    continuous batching wins on: short and long prompts interleaved)."""
    rng = np.random.default_rng(seed)

    def sample() -> np.ndarray:
        n = int(rng.integers(min_len, max_len + 1))
        return rng.integers(0, vocab_size, size=(n,)).astype(np.int32)

    return sample


def arrival_offsets(num_requests: int, rate_hz: float, *,
                    arrival: str = "fixed",
                    seed: int = 0) -> list[float]:
    """The open-loop submit schedule as offsets from t0 — split out so
    a determinism test can assert the schedule itself (same seed →
    identical offsets) without racing wall clocks. ``fixed``: a
    metronome at ``1/rate_hz``. ``poisson``: seeded exponential
    inter-arrival gaps (a true Poisson process of the same mean rate —
    the burstiness real traffic has and the metronome hides)."""
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    if arrival not in ("fixed", "poisson"):
        raise ValueError(f"arrival must be 'fixed' or 'poisson', "
                         f"got {arrival!r}")
    if arrival == "fixed":
        return [i / rate_hz for i in range(num_requests)]
    rng = random.Random(seed)
    offsets, t = [], 0.0
    for _ in range(num_requests):
        offsets.append(t)
        t += rng.expovariate(rate_hz)
    return offsets


def open_loop_client(server: InferenceServer, *, num_requests: int,
                     rate_hz: float, max_new_tokens: int,
                     prompt_sampler: Callable[[], np.ndarray],
                     deadline_s: Optional[float] = None,
                     arrival: str = "fixed",
                     seed: int = 0) -> list[Request]:
    """Submit ``num_requests`` on an open loop (arrivals do not wait
    for completions). ``arrival="fixed"`` keeps the historical
    metronome clock; ``arrival="poisson"`` draws seeded exponential
    inter-arrival gaps via :func:`arrival_offsets`, so the schedule is
    Poisson in fact — not just "Poisson-ish" — and reproducible per
    seed. Returns every Request — including rejected ones; the caller
    inspects states. Blocks until all terminal."""
    offsets = arrival_offsets(num_requests, rate_hz,
                              arrival=arrival, seed=seed)
    reqs: list[Request] = []
    t0 = time.monotonic()
    for off in offsets:
        wait = t0 + off - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        dl = (time.monotonic() + deadline_s
              ) if deadline_s is not None else None
        reqs.append(server.submit(prompt_sampler(), max_new_tokens,
                                  deadline_s=dl))
    for r in reqs:
        r.done.wait()
    return reqs


def closed_loop_client(server: InferenceServer, *, num_users: int,
                       requests_per_user: int, max_new_tokens: int,
                       prompt_sampler: Callable[[], np.ndarray]
                       ) -> list[Request]:
    """``num_users`` synthetic users, each submit->wait->repeat. The
    closed loop self-throttles to service rate — latency numbers from
    it are uncontended-by-construction (use the open loop to probe
    overload)."""
    out_lock = threading.Lock()
    reqs: list[Request] = []

    def user() -> None:
        for _ in range(requests_per_user):
            with out_lock:
                prompt = prompt_sampler()
            r = server.submit(prompt, max_new_tokens)
            with out_lock:
                reqs.append(r)
            r.done.wait()

    threads = [threading.Thread(target=user, daemon=True)
               for _ in range(num_users)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return reqs


def wait_all(reqs: Sequence[Request], timeout: float = 300.0) -> None:
    deadline = time.monotonic() + timeout
    for r in reqs:
        if not r.done.wait(max(deadline - time.monotonic(), 0.0)):
            raise TimeoutError(f"request {r.request_id} still "
                               f"{r.state} at timeout")
