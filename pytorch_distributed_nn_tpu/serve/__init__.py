"""Continuous-batching serving subsystem (ISSUE 5 + fleet, ISSUE 8).

Layering (each module's docstring carries its own contract):

- :mod:`serve.kv_pool` — paged KV-cache accounting: block allocator +
  per-sequence block tables, reservation-at-admission;
- :mod:`serve.scheduler` — bounded admission queue, strict-FIFO
  anti-starvation policy, deadlines, chaos load-shedding;
- :mod:`serve.engine` — the batched decode loop: per-row cache
  positions over one dense KV cache, mid-batch retirement, greedy
  decode bit-identical to sequential ``inference.generate``;
- :mod:`serve.server` — thread loopback front-end, SIGTERM drain,
  open/closed-loop synthetic clients;
- :mod:`serve.traffic` — Skyline trace-driven load generator: seeded
  diurnal/flash-crowd/heavy-tailed multi-tenant traffic shapes
  (``TPUNN_TRAFFIC`` chaos-style spec grammar), byte-identical JSONL
  traces, replay into a server or fleet; the capacity judge lives in
  :mod:`obs.capacity`;
- :mod:`serve.prefix_cache` — Mosaic prefix-cache residency (ISSUE
  14): content-addressed radix index over retired KV blocks, COW
  tail reuse, leaf-only LRU eviction, one counted ``_account`` choke
  point; the engine's save/restore side lives in :mod:`serve.engine`;
- :mod:`serve.router` — fleet placement policy: score READY replicas
  by KV headroom minus queue pressure plus prefix-cache affinity
  (``PrefixCache.peek``), one counted choke point;
- :mod:`serve.fleet` — replica supervisor: N engines behind one
  admission point, heartbeat failure detection, chaos-tested failover
  with in-flight re-admission, rolling zero-reject weight reload,
  elastic ``scale_to`` with a warm-before-READY join gate;
- :mod:`serve.disagg` — Estuary (ISSUE 15): disaggregated
  prefill/decode pools (``Fleet(prefill=P, decode=D)``), KV block
  streaming between replicas through the
  :func:`ops.collectives.kv_transfer` choke point, two-stage
  stage-aware placement, chaos-tested mid-transfer failover with
  bit-identical stitched output;
- :mod:`serve.autoscale` — Helm: the SLO burn-rate autoscaler closing
  the watchtower → fleet loop (``TPUNN_AUTOSCALE`` spec grammar,
  explainable ``autoscale_decision`` journal, hysteresis/cooldowns,
  Skyline-forecast scale-down floor);
- :mod:`serve.store` — the fleet's coordination substrate:
  ``MemStore`` (in-process, parity-tested against the native wire
  client), ``PrefixStore`` namespacing, append-only ``StoreJournal``,
  ``make_store`` endpoint factory;
- :mod:`serve.procfleet` — the deployment shape (ISSUE 13): replica
  subprocesses (:mod:`serve.fleet_worker`) supervised over the real
  native store, with a crash-recoverable coordinator (adoption, not
  restart; journal continuity across incarnations); Breakwater (ISSUE
  18) adds role-tagged pools (``ProcessFleet(prefill=P, decode=D)``)
  and cross-host enrollment through a ``ProcessFleetProvisioner``;
- :mod:`serve.kv_wire` — Breakwater's fault-tolerant KV handoff wire
  (ISSUE 18): versioned, checksummed ``kvwire/<req>/<seq>`` chunk
  records streamed through the store, every op on a counted retry
  helper (:func:`runtime.failure.store_call`), torn chunks re-pulled
  then degraded to a cold re-prefill — a request never wedges.

CLI: ``scripts/serve.py``, ``scripts/fleet_deploy.py``; load test:
``bench.py --serve`` / ``bench.py --fleet [--fleet-procs N]`` /
``bench.py --fleet --disagg[-procs]``; docs: ``docs/serving.md``.
"""

from pytorch_distributed_nn_tpu.serve.autoscale import (  # noqa: F401
    ENV_AUTOSCALE,
    AutoscaleConfig,
    Autoscaler,
    Decision,
    FleetAutoscaler,
    SimController,
)
from pytorch_distributed_nn_tpu.serve import autoscale  # noqa: F401
from pytorch_distributed_nn_tpu.serve.decoding import (  # noqa: F401
    DecodeSpec,
    TokenStream,
)
from pytorch_distributed_nn_tpu.serve.disagg import (  # noqa: F401
    DisaggFleet,
)
from pytorch_distributed_nn_tpu.serve.engine import (  # noqa: F401
    ServingEngine,
)
from pytorch_distributed_nn_tpu.serve.fleet import (  # noqa: F401
    Fleet,
    FleetTicket,
    ReplicaHandle,
)
from pytorch_distributed_nn_tpu.serve.kv_pool import KVPool  # noqa: F401
from pytorch_distributed_nn_tpu.nn.lora import (  # noqa: F401
    init_lora_bank,
    merge_lora,
    num_adapters,
)
from pytorch_distributed_nn_tpu.serve.prefix_cache import (  # noqa: F401
    PrefixCache,
    PrefixMatch,
)
from pytorch_distributed_nn_tpu.serve import kv_wire  # noqa: F401
from pytorch_distributed_nn_tpu.serve.procfleet import (  # noqa: F401
    ProcessFleet,
    ProcessFleetProvisioner,
    ProcTicket,
    TemplateProvisioner,
)
from pytorch_distributed_nn_tpu.serve.router import (  # noqa: F401
    DEAD,
    DRAINING,
    READY,
    RELOADING,
    REPLICA_STATES,
    STARTING,
    Router,
)
from pytorch_distributed_nn_tpu.serve.scheduler import (  # noqa: F401
    Request,
    Scheduler,
)
from pytorch_distributed_nn_tpu.serve.store import (  # noqa: F401
    MemStore,
    PrefixStore,
    StoreJournal,
    make_store,
)
from pytorch_distributed_nn_tpu.serve.server import (  # noqa: F401
    InferenceServer,
    arrival_offsets,
    closed_loop_client,
    install_sigterm_drain,
    open_loop_client,
    ragged_prompt_sampler,
)
from pytorch_distributed_nn_tpu.serve.traffic import (  # noqa: F401
    ENV_TRAFFIC,
    TrafficSpec,
    generate_trace,
    load_trace,
    replay_trace,
    trace_to_jsonl,
    write_trace,
)
from pytorch_distributed_nn_tpu.serve import traffic  # noqa: F401
