"""Breakwater KV wire (ISSUE 18): the versioned, checksummed format
KV blocks ride when a prefill->decode handoff crosses a *process*
boundary through the native store.

The in-process disaggregated fleet (:mod:`serve.disagg`) hands host
arrays straight to the decode engine — the arrays ARE the wire. The
process fleet cannot: the prefill worker and the decode worker share
nothing but the coordination store, so the blocks must serialize into
store records that can tear, stall, and vanish mid-transfer. This
module is the ONE place that format exists (lint-enforced by
tests/test_quality.py: no other serve file touches a ``kvwire/*``
key), and it is robust by construction:

- **key layout**: ``kvwire/<request_id>/<seq>`` chunk records plus a
  ``kvwire/<request_id>/meta`` commit point written LAST — a reader
  that sees meta knows every chunk landed at least once; a reader that
  never sees meta within its deadline degrades, it does not wedge;
- **chunk record**: a fixed ``!4sIIII`` header (magic ``KVW1``, wire
  version, seq, CRC32 of the payload slice, slice length) followed by
  the slice — torn writes and version skew are *detected*, loudly;
- **every store op** on the transfer path goes through
  :func:`runtime.failure.store_call` — deadline + exponential backoff
  + seeded jitter, each failed attempt counted in
  ``store_errors_total{op}`` and ``kv_wire_retries_total{op}`` (the
  helper is the sole ``except OSError`` site on this path,
  lint-enforced);
- **torn chunks** (checksum mismatch, bad magic, or an injected
  ``corrupt_wire@`` fault) trigger a bounded re-pull; exhaustion
  degrades to ``None`` — the decode replica re-prefills cold and the
  request finishes bit-identical, never wedged;
- **accounting rides the existing fan-out**: :func:`push` feeds the
  whole tree through :func:`ops.collectives.kv_transfer` once, so wire
  bytes (CommRecorder + flight ring), tenant billing (Abacus), trace
  context (Causeway), and the ``kill_transfer`` chaos hook all see a
  cross-process transfer exactly as they see an in-process one.

With chaos/meter/trace env unset the encoded bytes are byte-identical
run to run (canonical sort_keys JSON meta, deterministic chunking) and
this module writes nothing to the registry or the flight ring on the
happy path — counters move only when a retry or a degradation actually
happens.

Stdlib + numpy only at import time (workers arm this before touching
the backend); :mod:`ops.collectives` — and through it jax — imports
lazily inside :func:`push`.
"""

from __future__ import annotations

import json
import logging
import struct
import zlib

import numpy as np

from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.runtime import chaos
from pytorch_distributed_nn_tpu.runtime.failure import store_call

log = logging.getLogger(__name__)

MAGIC = b"KVW1"
WIRE_VERSION = 1
_HEADER = struct.Struct("!4sIIII")  # magic, version, seq, crc32, length

# one store record per chunk; sized so a few chunks cover a tiny-model
# handoff while real block tables still split (re-pull granularity)
DEFAULT_CHUNK_BYTES = 1 << 18

# a torn chunk re-pulls at most this many times before the pull
# degrades to a cold re-prefill
DEFAULT_MAX_REPULLS = 3


class WireError(RuntimeError):
    """Base for KV wire format violations."""


class WireVersionError(WireError):
    """Chunk or meta written by an incompatible wire version — loud on
    purpose: version skew is an operator error, not a transient."""


class TornChunkError(WireError):
    """Chunk failed its checksum / header validation — the torn-write
    shape :func:`pull` absorbs with a bounded re-pull."""


def chunk_key(request_id: str, seq: int) -> str:
    return f"kvwire/{request_id}/{seq}"


def meta_key(request_id: str) -> str:
    return f"kvwire/{request_id}/meta"


def _count_retry(op: str) -> None:
    get_registry().counter(
        "kv_wire_retries_total",
        "KV wire store ops retried on the transfer path",
        labels=("op",)).inc(op=op)


# ---------------------------------------------------------------------------
# Pytree <-> bytes (spec + one concatenated payload)
# ---------------------------------------------------------------------------


def encode_tree(tree) -> tuple[dict, bytes]:
    """Flatten a host pytree (dict/list/tuple of array-likes, Nones,
    and JSON scalars) into a JSON-able spec plus one concatenated
    payload. Leaves serialize as raw C-order bytes with their exact
    dtype string (endianness included), so the round trip is
    byte-identical."""
    payload: list[bytes] = []

    def enc(node):
        if node is None:
            return {"t": "n"}
        if isinstance(node, dict):
            keys = sorted(node)
            return {"t": "d", "k": keys,
                    "c": [enc(node[k]) for k in keys]}
        if isinstance(node, (list, tuple)):
            return {"t": "l" if isinstance(node, list) else "t",
                    "c": [enc(x) for x in node]}
        if isinstance(node, (bool, int, float, str)):
            return {"t": "v", "v": node}
        arr = np.ascontiguousarray(node)
        spec = {"t": "a", "i": len(payload), "d": arr.dtype.str,
                "s": list(arr.shape)}
        payload.append(arr.tobytes())
        return spec

    return enc(tree), b"".join(payload)


def decode_tree(spec: dict, payload: bytes):
    """Inverse of :func:`encode_tree`."""
    leaves: dict[int, tuple[str, list]] = {}

    def scan(node):
        if node["t"] == "a":
            leaves[node["i"]] = (node["d"], node["s"])
        elif node["t"] in ("d", "l", "t"):
            for c in node["c"]:
                scan(c)

    scan(spec)
    offsets: dict[int, int] = {}
    off = 0
    for i in sorted(leaves):
        dtype, shape = leaves[i]
        nbytes = int(np.dtype(dtype).itemsize * int(np.prod(shape,
                                                            dtype=np.int64)))
        offsets[i] = off
        off += nbytes
    if off != len(payload):
        raise WireError(
            f"payload length {len(payload)} does not match spec "
            f"({off} bytes of leaves)")

    def dec(node):
        t = node["t"]
        if t == "n":
            return None
        if t == "v":
            return node["v"]
        if t == "d":
            return {k: dec(c) for k, c in zip(node["k"], node["c"])}
        if t in ("l", "t"):
            seq = [dec(c) for c in node["c"]]
            return seq if t == "l" else tuple(seq)
        dtype, shape = node["d"], node["s"]
        nbytes = int(np.dtype(dtype).itemsize * int(np.prod(shape,
                                                            dtype=np.int64)))
        start = offsets[node["i"]]
        arr = np.frombuffer(payload[start:start + nbytes],
                            dtype=np.dtype(dtype))
        return arr.reshape(shape).copy()

    return dec(spec)


# ---------------------------------------------------------------------------
# Chunk records
# ---------------------------------------------------------------------------


def encode_chunk(seq: int, data: bytes) -> bytes:
    """One ``kvwire/<req>/<seq>`` store record: header + payload
    slice."""
    return _HEADER.pack(MAGIC, WIRE_VERSION, seq,
                        zlib.crc32(data) & 0xFFFFFFFF, len(data)) + data


def decode_chunk(blob: bytes) -> tuple[int, bytes]:
    """Validate and open one chunk record. Raises
    :class:`TornChunkError` on torn/garbled bytes (retryable) and
    :class:`WireVersionError` on a version-skewed peer (loud)."""
    if len(blob) < _HEADER.size:
        raise TornChunkError(f"chunk truncated at {len(blob)} bytes")
    magic, version, seq, crc, length = _HEADER.unpack_from(blob)
    if magic != MAGIC:
        raise TornChunkError(f"bad chunk magic {magic!r}")
    if version != WIRE_VERSION:
        raise WireVersionError(
            f"KV wire version mismatch: chunk is v{version}, this "
            f"process speaks v{WIRE_VERSION} — upgrade the fleet in "
            f"lockstep")
    data = blob[_HEADER.size:]
    if len(data) != length:
        raise TornChunkError(
            f"chunk {seq} torn: header says {length} bytes, "
            f"got {len(data)}")
    if zlib.crc32(data) & 0xFFFFFFFF != crc:
        raise TornChunkError(f"chunk {seq} failed checksum")
    return seq, data


def split_chunks(payload: bytes,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> list[bytes]:
    """Deterministic chunking: fixed-size slices, one (possibly empty)
    chunk minimum so even an empty tree has a record to commit."""
    if not payload:
        return [b""]
    return [payload[i:i + chunk_bytes]
            for i in range(0, len(payload), chunk_bytes)]


def join_chunks(chunks: dict[int, bytes], n: int) -> bytes:
    """Order-independent reassembly: chunks arrive keyed by seq (pulls
    may interleave and re-pull out of order); missing seq is loud."""
    missing = [i for i in range(n) if i not in chunks]
    if missing:
        raise WireError(f"missing chunks {missing} of {n}")
    return b"".join(chunks[i] for i in range(n))


# ---------------------------------------------------------------------------
# push / pull (the transfer path)
# ---------------------------------------------------------------------------


_ABANDON = object()  # push-internal: a write the deadline gave up on


def push(store, request_id: str, tree, *, src: str = "prefill",
         dst: str = "store", src_index: int = -1, dst_index: int = -1,
         trace=None, tenant: str = "",
         chunk_bytes: int = DEFAULT_CHUNK_BYTES,
         deadline_s: float = 5.0, seed: int = 0):
    """Serialize ``tree`` and commit it to the store under
    ``kvwire/<request_id>/*``. Returns the meta record, or ``None``
    when the store stayed unreachable past the deadline — the wire is
    simply never committed (no meta) and the decode leg runs cold; a
    partition degrades the push, it never kills the worker.

    Ordering is the contract: the tree feeds
    :func:`ops.collectives.kv_transfer` FIRST (wire bytes, tenant
    billing, trace context, and the ``kill_transfer`` chaos hook all
    fire before a byte lands — a killed transfer burned its bytes,
    exactly like a real mid-push death), then every chunk, then meta
    LAST as the commit point. Every store op goes through
    :func:`runtime.failure.store_call`."""
    from pytorch_distributed_nn_tpu.ops import collectives

    spec, payload = encode_tree(tree)
    collectives.kv_transfer(tree, src=src, dst=dst,
                            src_index=src_index, dst_index=dst_index,
                            trace=trace, tenant=tenant)
    chunks = split_chunks(payload, chunk_bytes)
    for seq, data in enumerate(chunks):
        blob = encode_chunk(seq, data)
        out = store_call(
            lambda k=chunk_key(request_id, seq), b=blob: store.set(k, b),
            op="kv_push", deadline_s=deadline_s, seed=seed,
            on_retry=lambda: _count_retry("push"), fallback=_ABANDON)
        if out is _ABANDON:
            flight.record("kvwire", "push_abandoned",
                          note=f"{request_id}: chunk {seq} unreachable "
                               f"past {deadline_s:.1f}s — wire never "
                               f"committed")
            log.warning("kv_wire: %s push abandoned at chunk %d — "
                        "decode leg will run cold", request_id, seq)
            return None
    meta = {"version": WIRE_VERSION, "chunks": len(chunks),
            "bytes": len(payload),
            "crc": zlib.crc32(payload) & 0xFFFFFFFF, "spec": spec}
    out = store_call(
        lambda: store.set(meta_key(request_id),
                          json.dumps(meta, sort_keys=True).encode()),
        op="kv_push_meta", deadline_s=deadline_s, seed=seed,
        on_retry=lambda: _count_retry("push_meta"), fallback=_ABANDON)
    if out is _ABANDON:
        flight.record("kvwire", "push_abandoned",
                      note=f"{request_id}: meta unreachable past "
                           f"{deadline_s:.1f}s — wire never committed")
        log.warning("kv_wire: %s push abandoned at meta — decode leg "
                    "will run cold", request_id)
        return None
    return meta


def pull(store, request_id: str, *, deadline_s: float = 2.0,
         max_repulls: int = DEFAULT_MAX_REPULLS, seed: int = 0):
    """Pull and decode ``kvwire/<request_id>/*``; ``None`` means the
    wire is cold — the caller re-prefills, it never wedges.

    Degradation ladder: meta absent past the (bounded) deadline ->
    ``None``; a torn chunk (checksum, truncation, or an injected
    ``corrupt_wire@``) re-pulls up to ``max_repulls`` times, then
    ``None``; a version-skewed peer raises
    :class:`WireVersionError` loudly (skew is operator error, not a
    transient). Reassembly is order-independent by seq. Every
    degradation lands a ``kvwire`` flight event so the drill's
    disposition is visible post-mortem."""
    raw = store_call(
        lambda: store.get(meta_key(request_id),
                          timeout_ms=int(deadline_s * 250)),
        op="kv_pull_meta", deadline_s=deadline_s, seed=seed,
        on_retry=lambda: _count_retry("pull_meta"), fallback=None)
    if raw is None:
        flight.record("kvwire", "cold_fallback",
                      note=f"{request_id}: meta absent past deadline")
        log.warning("kv_wire: %s meta absent past %.1fs deadline — "
                    "cold re-prefill", request_id, deadline_s)
        return None
    meta = json.loads(raw.decode())
    if meta.get("version") != WIRE_VERSION:
        raise WireVersionError(
            f"KV wire version mismatch: meta is "
            f"v{meta.get('version')}, this process speaks "
            f"v{WIRE_VERSION} — upgrade the fleet in lockstep")
    got: dict[int, bytes] = {}
    for seq in range(int(meta["chunks"])):
        data = None
        for attempt in range(1 + max_repulls):
            blob = store_call(
                lambda k=chunk_key(request_id, seq): store.get(
                    k, timeout_ms=int(deadline_s * 250)),
                op="kv_pull", deadline_s=deadline_s, seed=seed,
                on_retry=lambda: _count_retry("pull"), fallback=None)
            if blob is None:
                continue  # absent/unreachable counts against repulls
            try:
                rseq, data = decode_chunk(blob)
            except TornChunkError as e:
                flight.record("kvwire", "torn_chunk",
                              note=f"{request_id}/{seq}: {e} "
                                   f"(attempt {attempt + 1})")
                data = None
                continue
            if rseq != seq:
                flight.record("kvwire", "torn_chunk",
                              note=f"{request_id}/{seq}: header says "
                                   f"seq {rseq}")
                data = None
                continue
            if chaos.on_wire_chunk(seq):
                # injected tear: identical disposition to a real one
                data = None
                continue
            break
        if data is None:
            flight.record("kvwire", "cold_fallback",
                          note=f"{request_id}: chunk {seq} torn after "
                               f"{1 + max_repulls} pulls")
            log.warning("kv_wire: %s chunk %d unrecoverable after %d "
                        "pulls — cold re-prefill", request_id, seq,
                        1 + max_repulls)
            return None
        got[seq] = data
    payload = join_chunks(got, int(meta["chunks"]))
    if zlib.crc32(payload) & 0xFFFFFFFF != int(meta["crc"]) \
            or len(payload) != int(meta["bytes"]):
        flight.record("kvwire", "cold_fallback",
                      note=f"{request_id}: reassembled payload failed "
                           f"whole-transfer checksum")
        log.warning("kv_wire: %s reassembled payload failed checksum "
                    "— cold re-prefill", request_id)
        return None
    return decode_tree(meta["spec"], payload)


def cleanup(store, request_id: str, *, deadline_s: float = 1.0,
            seed: int = 0) -> None:
    """Best-effort wire GC after a successful ingest: drop the chunk
    records and meta so the store does not accumulate dead blocks. A
    partition here is absorbed (counted) and abandoned — GC must never
    block serving."""
    raw = store_call(
        lambda: store.get(meta_key(request_id), timeout_ms=50),
        op="kv_gc", deadline_s=deadline_s, seed=seed, fallback=None)
    if raw is None:
        return
    try:
        n = int(json.loads(raw.decode()).get("chunks", 0))
    except (ValueError, UnicodeDecodeError):
        n = 0
    for seq in range(n):
        store_call(
            lambda k=chunk_key(request_id, seq): store.delete(k),
            op="kv_gc", deadline_s=deadline_s, seed=seed,
            fallback=None)
    store_call(lambda: store.delete(meta_key(request_id)),
               op="kv_gc", deadline_s=deadline_s, seed=seed,
               fallback=None)
