"""Content-addressed radix prefix cache over the paged KV pool.

Thousands of requests share system prompts and few-shot prefixes; the
Gemma-on-TPU serving analysis (PAPERS.md) attributes most of the
serving gap to batching policy and KV **residency** — this module is
the residency half. The paged :class:`serve.kv_pool.KVPool` was built
so that sharing a block across sequences is one refcount; this module
decides *which* blocks to share.

Design:

- **content addressing** — a block covering token ids ``t`` whose
  parent block hashed to ``d`` is keyed ``sha1(d + t.tobytes())``. The
  chained digest makes the key a function of the entire prefix, so two
  requests agree on a block id iff they agree on every token up to and
  including it. The index is a radix tree flattened to one dict keyed
  by digest (the chain IS the tree path); explicit parent/children
  links exist only to enforce leaf-only eviction;
- **admission matching** — :meth:`PrefixCache.admit` walks the
  request's full blocks through the index; every resident block is
  shared by reference (refcount++ via ``pool.reserve(shared=)``), so
  the engine restores those rows from the device block store and
  prefills only the suffix. A partial-tail match (the request diverges
  mid-block) is **copy-on-write**: the matched block's content is
  restored but the request's table gets a fresh private block, so the
  donor's block is never written past. At most ``len(prompt) - 1``
  tokens match — at least one token always prefills so the request's
  first-token logits exist;
- **eviction** — a finished sequence donates its full blocks to the
  index (:meth:`release`), which parks refcount-0 blocks in the pool's
  cached LRU ring instead of freeing them. Under allocation pressure,
  admission sheds unpinned LRU **leaf** blocks (children would be
  orphaned by an interior eviction: matching requires a contiguous
  chain from block 0). The COW tail is pinned across the
  match->restore window so a same-round admission cannot evict content
  another admission is about to copy;
- **accounting** — every index mutation funnels through
  :meth:`PrefixCache._account` (lint-enforced by tests/test_quality
  .py, mirroring the scheduler's ``_transition``): the
  ``serve_kv_prefix_{hits,misses,evictions}_total`` counters, the
  ``serve_kv_prefix_hit_rate`` gauge, the tokens-saved counter, and a
  ``prefix`` flight event can never drift from the index's actual
  shape.

Thread model: the engine thread matches/admits (inside the
scheduler's admission pass) and donates (at retire); client threads
only :meth:`peek` (router affinity), which takes the lock but mutates
nothing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from typing import Optional

import numpy as np

from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.runtime import chaos
from pytorch_distributed_nn_tpu.serve.kv_pool import KVPool


def _digest(parent: bytes, tokens: np.ndarray) -> bytes:
    return hashlib.sha1(
        parent + np.asarray(tokens, np.int32).tobytes()).digest()


def _root(adapter: int) -> bytes:
    """Chain seed. The KV content of a block depends on the LoRA
    adapter (its v-projection delta is baked into the cached rows), so
    the content address namespaces the whole chain by adapter id — two
    requests share a block iff they agree on every token AND the
    adapter. Never a valid sha1 digest (wrong length), so roots can't
    collide with interior nodes."""
    return b"a%d|" % int(adapter)


@dataclasses.dataclass
class PrefixMatch:
    """One admission's match: ``blocks`` are shared by reference (they
    are the head of the sequence's block table), ``tail`` is the
    pinned copy-on-write source whose content is restored but whose
    block is NOT in the table, ``tokens`` is the prefill offset m."""

    blocks: tuple[int, ...] = ()
    tail: Optional[int] = None
    tokens: int = 0

    @property
    def restore_blocks(self) -> tuple[int, ...]:
        return self.blocks + ((self.tail,) if self.tail is not None
                              else ())


class _Node:
    __slots__ = ("digest", "parent", "tokens", "phys", "children")

    def __init__(self, digest: bytes, parent: bytes,
                 tokens: np.ndarray, phys: int) -> None:
        self.digest = digest
        self.parent = parent
        self.tokens = np.asarray(tokens, np.int32)
        self.phys = int(phys)
        self.children: set[bytes] = set()


class PrefixCache:
    """Radix index of resident KV blocks, content-addressed."""

    def __init__(self, pool: KVPool, *, max_rows: int = 0,
                 tag: str = "") -> None:
        self.pool = pool
        self.block_size = pool.block_size
        # ceiling on rows the engine's per-row cache can restore into
        # (a COW tail whose block would overflow it is not matched)
        self.max_rows = int(max_rows) or pool.num_blocks * pool.block_size
        self.tag = tag
        self._lock = threading.Lock()
        self._nodes: dict[bytes, _Node] = {}
        self._by_phys: dict[int, bytes] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.tokens_saved = 0
        reg = get_registry()
        self._c_hits = reg.counter(
            "serve_kv_prefix_hits_total",
            "admissions that matched a resident prefix")
        self._c_misses = reg.counter(
            "serve_kv_prefix_misses_total",
            "admissions with no resident prefix")
        self._c_evictions = reg.counter(
            "serve_kv_prefix_evictions_total",
            "cached prefix blocks evicted under pressure")
        self._c_saved = reg.counter(
            "serve_kv_prefix_tokens_saved_total",
            "prompt tokens whose prefill was skipped")
        self._g_hit_rate = reg.gauge(
            "serve_kv_prefix_hit_rate",
            "hits / (hits + misses), lifetime")

    # -- the single counted choke point ------------------------------------

    def _account(self, op: str, *, tokens: int = 0,
                 note: str = "") -> None:
        """EVERY prefix-cache state change lands here (lint-enforced):
        the counters, the hit-rate gauge, and the flight ring cannot
        drift from the index's actual mutations."""
        flight.record("prefix", op, note=note or self.tag)
        if op == "hit":
            self.hits += 1
            self.tokens_saved += tokens
            self._c_hits.inc()
            self._c_saved.inc(tokens)
        elif op == "miss":
            self.misses += 1
            self._c_misses.inc()
        elif op == "evict":
            self.evictions += 1
            self._c_evictions.inc()
        total = self.hits + self.misses
        if total:
            self._g_hit_rate.set(self.hits / total)

    # -- matching ----------------------------------------------------------

    def _match_locked(self, prompt: np.ndarray,
                      adapter: int = 0) -> PrefixMatch:
        """Longest resident chain, capped at ``len(prompt) - 1`` tokens
        (>= 1 token must prefill). Read-only."""
        bs = self.block_size
        cap = len(prompt) - 1
        blocks: list[int] = []
        root = _root(adapter)
        parent = root
        j = 0
        while (j + 1) * bs <= cap:
            d = _digest(parent, prompt[j * bs:(j + 1) * bs])
            node = self._nodes.get(d)
            if node is None:
                break
            blocks.append(node.phys)
            parent = d
            j += 1
        # partial tail: the request diverges inside the next block —
        # restore a child block's content copy-on-write when its first
        # t tokens agree (and the extra block still fits the row cache)
        tail, t = None, cap - j * bs
        if 0 < t < bs and (j + 1) * bs <= self.max_rows:
            head = self._nodes.get(parent) if parent != root else None
            kids = (head.children if head is not None
                    else {d for d, n in self._nodes.items()
                          if n.parent == root})
            rest = prompt[j * bs:cap]
            for d in sorted(kids):
                node = self._nodes.get(d)
                if node is not None and np.array_equal(
                        node.tokens[:t], rest):
                    tail = node.phys
                    break
        m = j * bs + (t if tail is not None else 0)
        return PrefixMatch(blocks=tuple(blocks), tail=tail, tokens=m)

    def peek(self, prompt, adapter: int = 0) -> int:
        """Read-only matched-token count (router affinity scoring).
        No counters, no LRU touch, no pins."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 2:
            return 0
        with self._lock:
            return self._match_locked(prompt, adapter).tokens

    def resident_chain(self, prompt, adapter: int = 0) -> PrefixMatch:
        """Read-only full-block resident chain for ``prompt`` — the
        streamable prefix for peer warm-up (:mod:`serve.disagg`).
        Unlike :meth:`admit` there is no COW tail (only whole blocks
        ship between replicas) and nothing is counted or touched; the
        caller pins the returned blocks in the pool across the export
        window so eviction cannot recycle them mid-stream."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 2:
            return PrefixMatch()
        with self._lock:
            m = self._match_locked(prompt, adapter)
        return PrefixMatch(blocks=m.blocks,
                           tokens=len(m.blocks) * self.block_size)

    # -- admission ---------------------------------------------------------

    def admit(self, seq_id: str, prompt, total_tokens: int,
              adapter: int = 0) -> Optional[PrefixMatch]:
        """Match + reserve for one admission. Returns the match (tokens
        may be 0) when the reservation landed, None on backpressure —
        the scheduler treats None exactly like ``pool.reserve`` False.

        The COW tail is pinned here and stays pinned until the engine
        finishes restoring (:meth:`finish_restore`)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            if chaos.on_prefix_evict():
                self._evict_locked(1)
            match = self._match_locked(prompt, adapter)
            if match.tail is not None:
                self.pool.pin(match.tail)
            need = (self.pool.blocks_for(total_tokens)
                    - len(match.blocks))
            short = need - self.pool.free_blocks
            if short > 0:
                self._evict_locked(short)
            if not self.pool.reserve(seq_id, total_tokens,
                                     shared=match.blocks):
                if match.tail is not None:
                    self.pool.unpin(match.tail)
                self._account("defer", note=seq_id)
                return None
            if match.tokens > 0:
                self._account("hit", tokens=match.tokens,
                              note=f"{seq_id} m={match.tokens}")
            else:
                self._account("miss", note=seq_id)
            return match

    def make_room(self, blocks: int) -> int:
        """Shed up to ``blocks`` unpinned LRU cached blocks to the free
        list, returning the count actually shed. Branch tails
        (:meth:`KVPool.fork`) allocate straight off the free list,
        bypassing :meth:`admit`'s reclaim — the scheduler calls this
        before retrying a fork that found the free list parked in the
        cached ring."""
        with self._lock:
            return self._evict_locked(int(blocks))

    def finish_restore(self, match: PrefixMatch) -> None:
        """Unpin the COW tail once its content has been copied into the
        admitting sequence's rows."""
        if match.tail is None:
            return
        with self._lock:
            self.pool.unpin(match.tail)
            self._account("unpin", note=f"b{match.tail}")

    # -- donation + eviction -----------------------------------------------

    def release(self, seq_id: str, tokens, adapter: int = 0) -> int:
        """Retire-side: index the finished sequence's full blocks
        (dedup by digest — a block whose chain is already resident is
        not re-indexed) and free its table, retaining exactly the
        indexed blocks in the pool's cached ring. Returns the count of
        blocks that actually hit the free list."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        root = _root(adapter)
        with self._lock:
            table = self.pool.block_table(seq_id)
            retain: set[int] = set()
            parent = root
            for j in range(min(len(tokens) // bs, len(table))):
                d = _digest(parent, tokens[j * bs:(j + 1) * bs])
                node = self._nodes.get(d)
                if node is None:
                    node = _Node(d, parent, tokens[j * bs:(j + 1) * bs],
                                 table[j])
                    self._nodes[d] = node
                    self._by_phys[node.phys] = d
                    head = (self._nodes.get(parent)
                            if parent != root else None)
                    if head is not None:
                        head.children.add(d)
                    self._account("donate",
                                  note=f"{seq_id} b{node.phys}")
                if node.phys == table[j]:
                    retain.add(table[j])
                parent = d
            return self.pool.free(seq_id, retain=frozenset(retain))

    def ingest(self, tokens, adapter: int = 0) -> list[tuple[int, int]]:
        """Receive side of KV block streaming (:mod:`serve.disagg`):
        index ``tokens``'s full blocks as resident, adopting a
        cached-ring block (:meth:`KVPool.adopt_cached`) for each one
        the radix does not already hold. Returns ``[(chain_pos, phys)]``
        for the newly-indexed blocks — the ones whose streamed bytes
        still need writing into the device block store
        (already-resident blocks dedup by digest, exactly like
        :meth:`release`). Stops early, indexing a shorter chain, when
        the pool has no free block to adopt and nothing unpinned to
        shed — streamed warmth never displaces live reservations."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        root = _root(adapter)
        plan: list[tuple[int, int]] = []
        with self._lock:
            parent = root
            for j in range(len(tokens) // bs):
                blk = tokens[j * bs:(j + 1) * bs]
                d = _digest(parent, blk)
                node = self._nodes.get(d)
                if node is None:
                    phys = self.pool.adopt_cached()
                    if phys is None:
                        if not self._evict_locked(1):
                            break
                        phys = self.pool.adopt_cached()
                        if phys is None:
                            break
                    node = _Node(d, parent, blk, phys)
                    self._nodes[d] = node
                    self._by_phys[phys] = d
                    head = (self._nodes.get(parent)
                            if parent != root else None)
                    if head is not None:
                        head.children.add(d)
                    self._account("ingest", note=f"b{phys}")
                    plan.append((j, phys))
                parent = d
        return plan

    def abandon(self, seq_id: str) -> int:
        """Failure-path release: free the sequence's table without
        indexing anything new, but retain blocks the index already
        maps (shared prefix blocks owned by a resident chain) so a
        failed sequence can't yank content out from under the radix."""
        with self._lock:
            table = self.pool.block_table(seq_id)
            retain = frozenset(b for b in table if b in self._by_phys)
            self._account("abandon", note=seq_id)
            return self.pool.free(seq_id, retain=retain)

    def _evict_locked(self, need: int) -> int:
        """Shed up to ``need`` unpinned LRU leaf blocks. Counted per
        block through :meth:`_account`."""
        shed = 0
        progress = True
        while shed < need and progress:
            progress = False
            for phys in self.pool.cached_lru():
                d = self._by_phys.get(phys)
                if d is None:
                    # cached but never indexed (shouldn't happen):
                    # reclaim it anyway
                    if self.pool.release_cached(phys):
                        shed += 1
                        progress = True
                    continue
                node = self._nodes[d]
                if node.children & self._nodes.keys():
                    continue  # interior: evicting orphans descendants
                if not self.pool.release_cached(phys):
                    continue  # pinned (a COW restore in flight)
                self._drop_locked(node)
                self._account("evict", note=f"b{phys}")
                shed += 1
                progress = True
                break
        return shed

    def _drop_locked(self, node: _Node) -> None:
        del self._nodes[node.digest]
        self._by_phys.pop(node.phys, None)
        head = self._nodes.get(node.parent) if node.parent else None
        if head is not None:
            head.children.discard(node.digest)

    # -- introspection -----------------------------------------------------

    @property
    def nodes(self) -> int:
        with self._lock:
            return len(self._nodes)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return dict(
                prefix_hits=self.hits, prefix_misses=self.misses,
                prefix_evictions=self.evictions,
                prefix_tokens_saved=self.tokens_saved,
                prefix_hit_rate=(self.hits / total if total else 0.0),
                prefix_nodes=len(self._nodes),
            )
