"""Process-backed fleet: real subprocess replicas, crash-recoverable
coordinator, journal continuity through the native store.

The thread-backed :class:`serve.fleet.Fleet` proved the failover
*policy* (router, stranded re-admission, restart governor) with threads
standing in for processes and a :class:`serve.store.MemStore` standing
in for the wire. This module is the deployment shape: each replica is a
real subprocess (:mod:`serve.fleet_worker`, spawned with the same
:func:`launch.worker_env` contract the training agent uses), every word
between coordinator and workers travels through the REAL
:class:`runtime.native.StoreClient`, and — the new failure domain —
the *coordinator itself* may die and be replaced without cold-restarting
the fleet:

- **supervision over the wire** — the REAL
  :class:`runtime.failure.FailureDetector` reads worker heartbeats from
  the store; exits are classified by the per-replica
  :class:`launch.RestartPolicy` exactly like training workers
  (``GRACEFUL_EXIT_CODE`` free, crashes charged);
- **durable request journal** — every ``submit``/``place``/``final``
  is appended to a :class:`serve.store.StoreJournal` *before* the
  action takes effect, so a coordinator's death loses no request: the
  successor replays the journal, finds what was in flight, and stitches;
- **adoption, not restart** — :meth:`ProcessFleet.recover` bumps the
  ``coord/inc`` counter, measures the supervision gap from the dead
  coordinator's last ``coord/beat``, re-reads ``members``, and adopts
  every worker that is still heartbeating — their processes, KV state
  and queues untouched. Only requests stranded on replicas that died
  *during* the gap are re-admitted (prompt + ``prog/<rid>`` prefix;
  greedy decode makes the stitched stream bit-identical);
- **journal continuity** — Helm's decision journal persists through the
  same store (``Decision.as_json()`` bytes, appended verbatim); the
  successor's :class:`serve.autoscale.Autoscaler` resumes via
  ``resume_from`` — seq contiguous, hysteresis state chained, a new
  ``coordinator_incarnation`` stamped — so the concatenated journal
  replays standalone (``scripts/obs_watch.py --autoscale``) with no
  fork;
- **chaos-drilled** — ``kill_coordinator@after_s=`` raises
  :class:`runtime.chaos.CoordinatorKillError` in the poll loop (workers
  keep serving); ``store_partition@ms=`` blacks out every store op for
  a window, which both sides absorb as counted retries
  (``store_errors_total{op}``).

Breakwater (ISSUE 18) adds disaggregation and cross-host provisioning:

- **roles** — replicas spawn ``role=prefill|decode|unified``
  (``fleet_worker --role``); the ``members`` record carries the role,
  the ``serve_fleet_replicas{role}`` gauge tracks READY counts per
  pool, and the UNMODIFIED :meth:`serve.router.Router.place` routes
  stage-aware over the store-fed gauges;
- **cross-process KV handoff** — a finished prefill leg pushes its KV
  state through :mod:`serve.kv_wire` (versioned, checksummed
  ``kvwire/<req>/<seq>`` chunks; every store op counted-retried), the
  coordinator's :class:`_TransferPump` thread places the decode leg
  while the transfer is still in flight (the poll loop never blocks on
  a wire), and the decode worker's bounded pull degrades to a cold
  re-prefill on a dead wire — stitched output bit-identical either
  way, never a wedged request;
- **per-pool Helm** — ``scale_to(n, pool=)`` grows/drains one role's
  pool; :meth:`scalable_pools` / :meth:`pool_target` feed
  :meth:`serve.autoscale.FleetAutoscaler.step_all`, so prefill
  queue-depth pressure scales the prefill pool and the journaled
  decision carries the pool;
- **provisioning** — :class:`ProcessFleetProvisioner` hooks the spawn:
  the default :class:`LocalProvisioner` keeps ``subprocess.Popen``;
  :class:`TemplateProvisioner` formats a spawn-command template (e.g.
  ``ssh host {cmd}``) and the coordinator learns the worker's pid/host
  from the ``enroll/<idx>`` store handshake instead of the child
  handle.

Same lint-enforced contracts as the thread fleet: every replica state
change goes through :meth:`ProcessFleet._set_state` (counted +
flight-visible), every placement through the shared
:class:`serve.router.Router` choke point.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import queue
import shlex
import subprocess
import sys
import threading
import time
from typing import Optional

import numpy as np

from pytorch_distributed_nn_tpu.launch import RestartPolicy, worker_env
from pytorch_distributed_nn_tpu.obs import (
    audit,
    flight,
    meter,
    trace,
    watchtower,
)
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.runtime import chaos, failure
from pytorch_distributed_nn_tpu.serve import autoscale as _autoscale
from pytorch_distributed_nn_tpu.serve import kv_wire
from pytorch_distributed_nn_tpu.serve.router import (
    DEAD,
    DRAINING,
    QUARANTINED,
    READY,
    STARTING,
    Router,
)
from pytorch_distributed_nn_tpu.serve.store import (
    PrefixStore,
    StoreJournal,
    make_store,
)

log = logging.getLogger(__name__)

_ids = itertools.count()


class ProcTicket:
    """The client's handle on one process-fleet request. Survives both
    replica failover AND coordinator replacement: everything needed to
    rebuild it lives in the store journal."""

    def __init__(self, request_id: str, prompt: list, max_new_tokens: int,
                 tenant: str = "default") -> None:
        self.request_id = request_id
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.tenant = str(tenant)  # Abacus billing identity (obs/meter)
        self.t_submit = time.monotonic()
        self.t_first_token = 0.0
        self.t_done = 0.0
        # tokens recovered from dead lives, re-fed as prompt suffix
        self.prefix: list[int] = []
        self.failovers: list[dict] = []
        self.life = 0  # placement generation; workers echo it back
        # disaggregated leg: "" (unified), "prefill", or "decode";
        # the handoff flips prefill -> decode after the first token
        self.stage = ""
        # True while the transfer pump owns placement (between the
        # handoff and the pump's place attempt) — _retry_unplaced must
        # not double-dispatch a leg the pump is about to place
        self.pumping = False
        self.status = "pending"  # pending | done | rejected | failed
        self.reject_reason = ""
        self.tokens: Optional[np.ndarray] = None
        self.assigned: Optional[int] = None  # replica index, None=unplaced
        self.trace = None  # TraceContext (obs/trace.py), None when unarmed
        # Prism (serve/decoding.py): the DecodeSpec as its WIRE dict
        # (journal + dispatch records are JSON); None = greedy default
        self.decode: Optional[dict] = None
        self.done = threading.Event()

    @property
    def branches(self) -> int:
        if not self.decode:
            return 1
        return self.decode.get("best_of", 0) or self.decode.get("n", 1)

    @property
    def ok(self) -> bool:
        return self.status == "done"

    @property
    def ttft_s(self) -> float:
        return (self.t_first_token - self.t_submit
                if self.t_first_token else -1.0)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        if not self.done.wait(timeout):
            return None
        return self.tokens if self.ok else None


class _RemotePool:
    """Duck-type of :class:`serve.kv_pool.BlockPool`'s gauge surface —
    refreshed from the worker's ``gauge/<idx>`` key so the UNMODIFIED
    :class:`serve.router.Router` scores remote replicas."""

    def __init__(self, num_blocks: int) -> None:
        self.free_blocks = num_blocks
        self.num_blocks = num_blocks
        self.block_size = 1


class _RemoteScheduler:
    def __init__(self, max_queue: int, num_blocks: int) -> None:
        self.queue_depth = 0
        self.max_queue = max_queue
        self.pool = _RemotePool(num_blocks)


class _RemoteEngine:
    def __init__(self, max_queue: int, num_blocks: int) -> None:
        self.scheduler = _RemoteScheduler(max_queue, num_blocks)


class ProcReplica:
    """Book entry for one replica subprocess. ``state`` is written ONLY
    by :meth:`ProcessFleet._set_state` (the fleet.py lint contract)."""

    def __init__(self, index: int, policy: RestartPolicy,
                 max_queue: int, max_slots: int,
                 role: str = "unified") -> None:
        self.index = index
        self.name = f"p{index}"
        self.policy = policy
        self.engine = _RemoteEngine(max_queue, max_slots)
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.state = ""
        self.incarnations = 0
        self.restart_at: Optional[float] = None
        self.stop_reason = ""
        self.retiring = False
        # disaggregated pool membership: the router's stage-aware
        # place() reads this straight off the handle
        self.role = role
        # provisioned on another host (TemplateProvisioner): no child
        # handle — pid/host arrive through the enroll/<idx> handshake
        # and liveness is the heartbeat detector's job
        self.remote = False
        self.host = ""
        self.adopted = False  # inherited live from a dead coordinator
        self.spawned_at = time.monotonic()
        self.gauge_round = -1


class ProcessFleetProvisioner:
    """Spawn hook: how one replica worker process comes to exist.

    The coordinator builds the worker command + env (the
    ``worker_env`` contract) and hands them here. :meth:`spawn`
    returns the child ``Popen`` when the coordinator owns the process
    directly, or ``None`` for a remotely-provisioned worker — the
    coordinator then learns its pid/host from the worker's own
    ``enroll/<idx>`` store write (the enrollment handshake) and
    supervises it purely over heartbeats."""

    #: True when spawned workers are not this coordinator's children
    remote = False

    def spawn(self, handle, cmd: list, env: dict):
        raise NotImplementedError

    def close(self) -> None:
        """Release any wrapper processes the provisioner holds."""


class LocalProvisioner(ProcessFleetProvisioner):
    """The default: plain ``subprocess.Popen`` on this host."""

    def spawn(self, handle, cmd: list, env: dict):
        return subprocess.Popen(cmd, env=env)


class TemplateProvisioner(ProcessFleetProvisioner):
    """Cross-host spawn through a command template: ``{cmd}`` expands
    to the shell-quoted worker command, ``{index}``/``{role}`` to the
    replica's. ``"ssh host {cmd}"`` enrolls a worker on another box;
    ``"{cmd}"`` runs locally but still exercises the full enrollment
    handshake (the drill shape). The wrapper process (ssh, shell) is
    NOT the worker — the coordinator never reads its pid; liveness is
    heartbeats and identity is ``enroll/<idx>``."""

    remote = True

    def __init__(self, template: str) -> None:
        if "{cmd}" not in template:
            raise ValueError(
                f"spawn template needs a {{cmd}} placeholder, got "
                f"{template!r}")
        self.template = template
        self._wrappers: list[subprocess.Popen] = []

    def spawn(self, handle, cmd: list, env: dict):
        line = self.template.format(
            cmd=" ".join(shlex.quote(c) for c in cmd),
            index=handle.index, role=handle.role)
        self._wrappers.append(
            subprocess.Popen(line, shell=True, env=env))
        return None

    def close(self) -> None:
        for p in self._wrappers:
            if p.poll() is None:
                p.terminate()
        for p in self._wrappers:
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                p.kill()
        self._wrappers.clear()


class _TransferPump:
    """The coordinator's transfer-overlap thread (Breakwater): places
    a handed-off decode leg and watches the KV wire WITHOUT ever
    blocking the poll loop.

    Owns its own store connection (a blocking native get occupies its
    connection — the poll loop's client must stay free) and emits its
    own flight-ring events (``pump:enqueue`` / ``pump:place`` /
    ``pump:ready`` / ``pump:nometa``), which is how a drill proves the
    poll loop and the transfer overlapped. The decode leg is placed
    IMMEDIATELY — admission on the decode replica proceeds while the
    prefill worker's push is still in flight; the worker's own bounded
    :func:`serve.kv_wire.pull` decides warm vs cold at admit time. The
    meta watch afterwards is pure disposition: ``pump:ready`` when the
    commit point landed, ``pump:nometa`` when the wire went dead (the
    decode leg re-prefills cold — already placed, never wedged)."""

    def __init__(self, fleet: "ProcessFleet",
                 wire_deadline_s: float = 2.0) -> None:
        self._fleet = fleet
        self._wire_deadline = wire_deadline_s
        self._client = make_store(fleet.store_endpoint)
        self._ns = PrefixStore(self._client, fleet.namespace)
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self.events = 0  # pump flight events emitted (drill assert)
        self._thread = threading.Thread(
            target=self._run, name="procfleet-pump", daemon=True)
        self._thread.start()

    def _emit(self, kind: str, note: str) -> None:
        flight.record("fleet", f"pump:{kind}", note=note)
        self.events += 1

    def enqueue(self, ticket: "ProcTicket", src: int) -> None:
        self._emit("enqueue", f"{ticket.request_id} src=r{src}")
        self._q.put((ticket, src))

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                ticket, src = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self._pump_one(ticket, src)
            except Exception:
                log.exception("transfer pump failed for %s",
                              ticket.request_id)

    def _pump_one(self, t: "ProcTicket", src: int) -> None:
        with self._fleet._lock:
            if self._fleet.dead:
                return  # adoption replays the handoff, not this pump
            if not t.done.is_set():
                placed = self._fleet._place(t)
                where = ("r%d" % placed if placed is not None
                         else "pending")
                self._emit("place", f"{t.request_id} -> {where}")
            t.pumping = False  # _retry_unplaced may take over now
        # disposition watch: bounded wait for the wire's commit point,
        # counted retries through the one helper, never raises
        raw = failure.store_call(
            lambda: self._ns.get(kv_wire.meta_key(t.request_id),
                                 timeout_ms=200),
            op="pump_watch", deadline_s=self._wire_deadline,
            fallback=None)
        if raw is not None:
            self._emit("ready", f"{t.request_id} wire committed")
        else:
            self._emit("nometa",
                       f"{t.request_id} wire dead past "
                       f"{self._wire_deadline:.1f}s — decode leg "
                       f"runs cold")

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            self._client.close()
        except OSError:
            pass


class ProcessFleet:
    """N replica subprocesses behind one (replaceable) coordinator."""

    def __init__(self, *, replicas: int = 2, backend: str = "stub",
                 prefill: int = 0, decode: int = 0,
                 role: str = "unified",
                 provisioner: Optional[ProcessFleetProvisioner] = None,
                 wire_deadline_s: float = 2.0,
                 preset: str = "", ckpt: str = "",
                 namespace: str = "fleet",
                 store_endpoint: Optional[str] = None,
                 server=None,
                 max_slots: int = 4, max_queue: int = 64,
                 max_seq_len: int = 256, block_size: int = 16,
                 token_ms: float = 2.0,
                 heartbeat_interval_s: float = 0.05,
                 heartbeat_timeout_s: float = 2.0,
                 progress_window_s: Optional[float] = None,
                 poll_interval_s: float = 0.02,
                 join_timeout_s: float = 60.0,
                 max_restarts: int = 3,
                 restart_window_s: Optional[float] = None,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 1.0,
                 autoscale_spec: str = "",
                 forecast_replicas: Optional[int] = None,
                 metrics=None,
                 worker_extra_env: Optional[dict] = None,
                 flight_dir: Optional[str] = None,
                 python: str = sys.executable,
                 recover: bool = False) -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if (prefill > 0) != (decode > 0):
            raise ValueError(
                "disaggregated process fleet needs BOTH prefill>=1 "
                f"and decode>=1, got prefill={prefill} decode={decode}")
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be unified|prefill|decode, got {role!r}")
        self.backend = backend
        self.preset = preset
        self.ckpt = ckpt
        self.disagg = prefill > 0 and decode > 0
        self.role = role  # the non-disagg pool's role (fleet_deploy)
        self._provisioner = provisioner or LocalProvisioner()
        self._wire_deadline = wire_deadline_s
        self.namespace = namespace
        self.metrics = metrics
        self._max_slots = max_slots
        self._max_queue = max_queue
        self._max_seq_len = max_seq_len
        self._block_size = block_size
        self._token_ms = token_ms
        self._hb_interval = heartbeat_interval_s
        self._hb_timeout = heartbeat_timeout_s
        self._progress_window = progress_window_s
        self._poll_interval = poll_interval_s
        self._join_timeout = join_timeout_s
        self._policy_kw = dict(
            max_restarts=max_restarts, window_s=restart_window_s,
            backoff_base_s=backoff_base_s, backoff_max_s=backoff_max_s)
        self._worker_extra_env = dict(worker_extra_env or {})
        self._flight_dir = flight_dir
        self._python = python
        self.router = Router()

        # -- store: own server by default, never an in-process stub ---
        self._owns_server = False
        if server is None and store_endpoint is None:
            from pytorch_distributed_nn_tpu.runtime import native

            server = native.StoreServer(0)
            self._owns_server = True
        self._server = server
        if store_endpoint is None:
            store_endpoint = f"127.0.0.1:{server.port}"
        if store_endpoint == "mem":
            raise ValueError(
                "ProcessFleet workers are subprocesses; the store must "
                "be a real endpoint (host:port), not 'mem'")
        self.store_endpoint = store_endpoint
        self._client = make_store(store_endpoint)
        self._ns = PrefixStore(self._client, namespace)
        self.journal = StoreJournal(self._ns, "journal")
        self.helm_journal = StoreJournal(self._ns, "helm")

        # -- coordinator identity + instruments ------------------------
        reg = get_registry()
        self._c_replica_state = reg.counter(
            "serve_replica_state_total", "replica state transitions",
            labels=("state",))
        self._c_coord_starts = reg.counter(
            "fleet_coordinator_starts_total",
            "coordinator lives by start mode", labels=("mode",))
        self._g_coord_inc = reg.gauge(
            "fleet_coordinator_incarnation",
            "this coordinator's incarnation (store-allocated)")
        self._g_coord_gap = reg.gauge(
            "fleet_coordinator_gap_seconds",
            "supervision gap a recovering coordinator measured from "
            "its predecessor's last beat")
        self._c_recovered = reg.counter(
            "fleet_coordinator_recovered_total",
            "recovery dispositions (replicas adopted/respawned, "
            "requests finalized/readmitted)", labels=("outcome",))
        # same name/labels serve/disagg.py registers — the registry
        # get-or-creates by name, so both fleets share the instrument
        self._g_role_replicas = reg.gauge(
            "serve_fleet_replicas", "READY replicas by role",
            labels=("role",))
        mode = "recover" if recover else "fresh"
        self.incarnation = self._ns.add("coord/inc", 1) - 1
        self.gap_s = 0.0
        if recover and self._ns.check("coord/beat"):
            self.gap_s = max(time.time() - float(
                self._ns.get("coord/beat", timeout_ms=2000)), 0.0)
        self._c_coord_starts.inc(mode=mode)
        self._g_coord_inc.set(float(self.incarnation))
        self._g_coord_gap.set(self.gap_s)
        if recover:
            # the one event obs_doctor names the outage from: how long
            # the fleet ran unsupervised, and which life took over
            flight.record(
                "fleet", "coordinator_gap",
                note=f"gap_s={self.gap_s:.3f} inc={self.incarnation}")
        flight.record("fleet", "coordinator_up",
                      note=f"inc={self.incarnation} mode={mode}")
        self.journal.append({
            "event": "coordinator_up", "incarnation": self.incarnation,
            "mode": mode, "gap_s": round(self.gap_s, 3)})

        self._lock = threading.RLock()
        self._replicas: list[ProcReplica] = []
        self._tickets: dict[str, ProcTicket] = {}
        self.completed: list[dict] = []
        self.failovers = 0
        self.dead = False  # supervision loop died (chaos / abandon)
        self._detector: Optional[failure.FailureDetector] = None
        self._started = False
        self._sup_stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None
        self.recovery: dict = {}

        # -- Helm (resumed across coordinator lives) -------------------
        self._helm = None
        if autoscale_spec:
            cfg = _autoscale.parse_spec(autoscale_spec)
            tower = (watchtower.tower()
                     if watchtower.enabled() else None)
            scaler = _autoscale.Autoscaler(
                cfg, tower=tower, forecast_replicas=forecast_replicas,
                metrics=metrics, spec=autoscale_spec)
            scaler.coordinator_incarnation = self.incarnation
            if recover:
                scaler.resume_from(self.helm_journal.read_all())
            self._helm = _autoscale.FleetAutoscaler(self, scaler)

        # transfer pump: exists before recovery so a replayed handoff
        # has somewhere to land its decode leg
        self._pump = _TransferPump(self, wire_deadline_s)

        if recover:
            self._recover_members()
            # disagg is a property of the fleet the journal describes,
            # not of the successor's constructor args
            self.disagg = any(h.role in ("prefill", "decode")
                              for h in self._replicas)
            self._refresh_gauges()  # promotes adopted live replicas
            self._recover_tickets()
            self._target_replicas = len(
                [h for h in self._replicas if not h.retiring]) or 1
            self._pool_targets = {
                pool: len([h for h in self._replicas
                           if h.role == pool and not h.retiring]) or 1
                for pool in ("prefill", "decode")} if self.disagg \
                else {}
        elif self.disagg:
            for _ in range(prefill):
                self._spawn_new(reason="init", role="prefill")
            for _ in range(decode):
                self._spawn_new(reason="init", role="decode")
            self._target_replicas = prefill + decode
            self._pool_targets = {"prefill": prefill, "decode": decode}
        else:
            for _ in range(replicas):
                self._spawn_new(reason="init", role=self.role)
            self._target_replicas = replicas
            self._pool_targets = {}
        self._write_members()
        self._rebuild_detector()

    @classmethod
    def recover_from(cls, *, store_endpoint: str,
                     namespace: str = "fleet", **kw) -> "ProcessFleet":
        """Take over a fleet whose coordinator died: adopt surviving
        workers, finalize/re-admit what the journal says was in flight,
        resume the Helm journal. Workers are never restarted just
        because the coordinator was."""
        return cls(store_endpoint=store_endpoint, namespace=namespace,
                   recover=True, **kw)

    # -- the single replica-state choke point --------------------------

    def _set_state(self, h: ProcReplica, state: str,
                   reason: str = "") -> None:
        """EVERY replica state change funnels through here (the
        fleet.py lint contract): counted + flight-visible."""
        h.state = state
        self._c_replica_state.inc(state=state)
        flight.record("fleet", f"state:{state}",
                      note=f"{h.name} {reason}".strip())
        if self.metrics is not None:
            self.metrics.emit("fleet_state", replica=h.index,
                              state=state, reason=reason)

    # -- replica lifecycle ----------------------------------------------

    def _alloc_index(self) -> int:
        """Monotonic store-allocated replica index: never reused across
        restarts, scale events, or coordinator lives, so a retired
        slot's keys can't alias a newer replica's."""
        return self._ns.add("ridx", 1) - 1

    def _new_handle(self, index: int,
                    role: str = "unified") -> ProcReplica:
        return ProcReplica(index,
                           RestartPolicy(seed=index, **self._policy_kw),
                           self._max_queue, self._max_slots, role=role)

    def _spawn_new(self, *, reason: str,
                   role: str = "unified") -> ProcReplica:
        h = self._new_handle(self._alloc_index(), role=role)
        self._replicas.append(h)
        self._set_state(h, STARTING, reason=reason)
        self._launch(h)
        return h

    def _launch(self, h: ProcReplica) -> None:
        cmd = [self._python, "-m",
               "pytorch_distributed_nn_tpu.serve.fleet_worker",
               "--store", self.store_endpoint,
               "--namespace", self.namespace,
               "--replica-index", str(h.index),
               "--backend", self.backend,
               "--max-slots", str(self._max_slots),
               "--max-queue", str(self._max_queue),
               "--max-seq-len", str(self._max_seq_len),
               "--block-size", str(self._block_size),
               "--token-ms", str(self._token_ms),
               "--hb-interval", str(self._hb_interval),
               # a restarted index resumes the dispatch stream where
               # the store counter left it, not at zero
               "--start-k", str(self._ns.add(f"reqn/{h.index}", 0))]
        if h.role != "unified":
            cmd += ["--role", h.role]
        if self.preset:
            cmd += ["--preset", self.preset]
        if self.ckpt:
            cmd += ["--ckpt", self.ckpt]
        if self._progress_window is not None:
            cmd += ["--progress-window", str(self._progress_window)]
        extra = dict(self._worker_extra_env)
        if audit.enabled():
            # Lighthouse: a programmatically-armed coordinator arms its
            # worker processes too (env-armed fleets inherit anyway)
            extra.setdefault(audit.ENV_AUDIT, audit.spec())
        env = worker_env(
            rank=h.index, world_size=1, incarnation=0,
            heartbeat_interval_s=self._hb_interval,
            progress_timeout_s=self._progress_window,
            flight_dir=self._flight_dir,
            extra=extra)
        proc = self._provisioner.spawn(h, cmd, env)
        if proc is not None:
            h.proc = proc
            h.pid = proc.pid
            h.remote = False
        else:
            # remotely provisioned: pid/host arrive via the
            # enroll/<idx> handshake; heartbeats own liveness
            h.proc = None
            h.pid = None
            h.remote = True
        h.incarnations += 1
        h.restart_at = None
        h.spawned_at = time.monotonic()
        h.gauge_round = -1

    def _write_members(self) -> None:
        # role/host keys ABSENT for unified local replicas so a
        # pre-disagg fleet's members record stays byte-identical
        members = []
        for h in self._replicas:
            if h.state == DEAD:
                continue
            m = {"index": h.index, "pid": h.pid,
                 "retiring": h.retiring}
            if h.role != "unified":
                m["role"] = h.role
            # key ABSENT unless Lighthouse isolated the replica, so
            # pre-audit members records stay byte-identical
            if h.state == QUARANTINED:
                m["quarantined"] = h.stop_reason or "quarantined"
            if h.remote:
                m["remote"] = True
                if h.host:
                    m["host"] = h.host
            members.append(m)
        try:
            self._ns.set("members",
                         json.dumps(members, sort_keys=True).encode())
        except (OSError, TimeoutError):
            failure.count_store_error("coord_members")

    def _rebuild_detector(self) -> None:
        self._detector = failure.FailureDetector(
            self._ns, ranks=[h.index for h in self._replicas],
            incarnation=0, timeout_s=self._hb_timeout)

    def _proc_exit_code(self, h: ProcReplica) -> Optional[int]:
        """None while running. Spawned children report their real exit
        code; adopted workers (another coordinator's children — unless
        recovery ran in the same process, where waitpid still works)
        fall back to an existence probe."""
        if h.proc is not None:
            return h.proc.poll()
        if h.remote:
            # another host's process: no waitpid, no signal 0 — the
            # heartbeat detector (and the STARTING join timeout)
            # declare a remote worker dead, never this probe
            return None
        if h.pid is None:
            return chaos.CRASH_EXIT_CODE
        try:
            pid, status = os.waitpid(h.pid, os.WNOHANG)
            if pid == 0:
                return None
            return os.waitstatus_to_exitcode(status)
        except ChildProcessError:
            try:
                os.kill(h.pid, 0)
                return None
            except ProcessLookupError:
                return chaos.CRASH_EXIT_CODE
        except OSError:
            return None

    # -- recovery --------------------------------------------------------

    def _recover_members(self) -> None:
        members = []
        try:
            if self._ns.check("members"):
                members = json.loads(
                    self._ns.get("members", timeout_ms=2000).decode())
        except (OSError, TimeoutError, ValueError):
            failure.count_store_error("coord_members")
        adopted = respawned = 0
        probe = failure.FailureDetector(
            self._ns, ranks=[int(m["index"]) for m in members],
            incarnation=0, timeout_s=self._hb_timeout)
        ages = probe.last_beat_ages()
        for m in members:
            idx = int(m["index"])
            if m.get("quarantined"):
                # Lighthouse isolation outlives the coordinator: a
                # quarantined index is never adopted OR respawned —
                # integrity, not liveness, took it out
                continue
            h = self._new_handle(idx, role=m.get("role", "unified"))
            h.pid = int(m["pid"]) if m.get("pid") else None
            h.retiring = bool(m.get("retiring"))
            h.remote = bool(m.get("remote"))
            h.host = m.get("host", "")
            age = ages.get(idx)
            beating = age is not None and age <= self._hb_timeout
            if beating and self._proc_exit_code(h) is None:
                h.adopted = True
                h.incarnations = 1
                self._replicas.append(h)
                # STARTING only until the next gauge read proves it
                # serving — adoption never cold-restarts a live worker
                self._set_state(h, STARTING, reason="adopt")
                self._c_recovered.inc(outcome="adopted")
                self.journal.append({"event": "adopt", "replica": idx,
                                     "pid": h.pid})
                adopted += 1
            elif not h.retiring:
                self._c_recovered.inc(outcome="respawned")
                self._spawn_new(reason="recover_respawn", role=h.role)
                respawned += 1
        self.recovery.update(adopted=adopted, respawned=respawned)
        log.info("procfleet recover: adopted %d, respawned %d "
                 "(gap %.3fs)", adopted, respawned, self.gap_s)

    def _recover_tickets(self) -> None:
        tickets: dict[str, ProcTicket] = {}
        for rec in self.journal.read_all():
            ev = rec.get("event")
            if ev == "submit":
                t = ProcTicket(rec["request_id"], rec["prompt"],
                               rec["max_new_tokens"],
                               tenant=rec.get("tenant", "default"))
                t.decode = rec.get("decode")
                tickets[t.request_id] = t
            elif ev == "place":
                t = tickets.get(rec["request_id"])
                if t is not None:
                    t.assigned = int(rec["replica"])
                    t.life = int(rec.get("life", 0))
                    t.prefix = [int(x) for x in rec.get("prefix", [])]
                    t.stage = rec.get("stage", t.stage)
            elif ev == "handoff":
                t = tickets.get(rec["request_id"])
                if t is not None:
                    t.stage = "decode"
                    t.assigned = None
                    t.life = int(rec.get("life", t.life))
                    t.prefix = [int(x) for x in rec.get("prefix",
                                                        t.prefix)]
            elif ev == "final":
                tickets.pop(rec["request_id"], None)
        self._tickets = tickets
        # drill/runbook surface: every ticket rebuilt from the journal,
        # kept addressable even after finalization pops it in-flight
        self.recovered_tickets = dict(tickets)
        alive = {h.index for h in self._replicas if h.state != DEAD}
        finalized = readmitted = 0
        for t in list(tickets.values()):
            payload = self._read_done(t)
            if payload is not None:
                # finished during the gap: stitch from the store, no
                # token ever re-decoded. A prefill leg's done payload
                # is a handoff, not a finish — mid-handoff is exactly
                # where the kill_coordinator drill lands
                self._on_done_payload(t, payload)
                self._c_recovered.inc(outcome="finalized")
                finalized += 1
                continue
            if t.assigned is not None and t.assigned in alive:
                continue  # its adopted replica still owns it
            emitted = self._read_prog(t)
            self._readmit(t, emitted,
                          from_replica=(-1 if t.assigned is None
                                        else t.assigned),
                          t_detect=time.monotonic(),
                          reason="coordinator_recover")
            self._c_recovered.inc(outcome="readmitted")
            readmitted += 1
        self.recovery.update(finalized=finalized,
                             readmitted=readmitted,
                             in_flight=len(self._tickets))
        self.journal.append({
            "event": "recover_summary",
            "incarnation": self.incarnation,
            "gap_s": round(self.gap_s, 3), **{
                k: self.recovery[k] for k in
                ("adopted", "respawned", "finalized", "readmitted")}})

    # -- client surface --------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               request_id: Optional[str] = None,
               tenant: str = "default",
               decode=None) -> ProcTicket:
        """Admit once fleet-wide; journaled BEFORE dispatch so no
        coordinator death can lose it. Unplaceable requests (no READY
        replica yet, store blip) stay pending and are re-placed by the
        next poll — the process fleet queues, it does not reject.
        ``decode`` (a :class:`serve.decoding.DecodeSpec`) journals as
        its wire dict, so a successor coordinator re-places the same
        seeded sampling policy."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        ticket = ProcTicket(
            request_id
            or f"preq-{self.incarnation}-{next(_ids)}",
            prompt, int(max_new_tokens), tenant=tenant)
        if decode is not None:
            ticket.decode = decode.to_wire() or None
        ticket.trace = trace.on_submit(ticket.request_id)
        with self._lock:
            self._tickets[ticket.request_id] = ticket
            try:
                journal_rec = {
                    "event": "submit",
                    "request_id": ticket.request_id,
                    "prompt": ticket.prompt,
                    "max_new_tokens": ticket.max_new_tokens}
                # key ABSENT for the default tenant so single-tenant
                # journals stay byte-identical to pre-Abacus ones
                if ticket.tenant != "default":
                    journal_rec["tenant"] = ticket.tenant
                # Prism: same key-absent discipline — a greedy submit
                # journals byte-identically to a pre-Prism one
                if ticket.decode:
                    journal_rec["decode"] = ticket.decode
                self.journal.append(journal_rec)
            except (OSError, TimeoutError):
                failure.count_store_error("coord_journal")
            self._place(ticket)
        return ticket

    def _place(self, ticket: ProcTicket) -> Optional[int]:
        """One placement attempt through the shared router choke
        point; journal-then-dispatch. Returns the replica index, None
        when nothing is READY (ticket stays pending).

        Disaggregated fleets place in two legs through the UNMODIFIED
        stage-aware router: the prefill leg gets a budget of 1 (emit
        the first token, push the KV wire, retire), the decode leg the
        remainder — same shape as :meth:`serve.disagg.DisaggFleet`,
        but over store-fed gauges and the cross-process wire."""
        if self.disagg and not ticket.stage:
            # Prism best-of-n skips the prefill/decode split (no single
            # first token to hand off); see serve/disagg.py
            ticket.stage = ("prefill" if ticket.branches == 1
                            else "decode")
        if ticket.stage == "prefill":
            remaining = 1
        else:
            remaining = ticket.max_new_tokens - len(ticket.prefix)
        total = len(ticket.prompt) + len(ticket.prefix) + remaining
        h = self.router.place(self._replicas, total,
                              stage=ticket.stage or None,
                              branches=ticket.branches)
        if h is None:
            ticket.assigned = None
            return None
        rec = {"request_id": ticket.request_id,
               "prompt": ticket.prompt + ticket.prefix,
               "max_new_tokens": remaining,
               "life": ticket.life}
        # stage key ABSENT on a unified fleet so the dispatch wire
        # stays byte-identical to the pre-disagg protocol
        if ticket.stage:
            rec["stage"] = ticket.stage
        # Causeway (obs/trace.py, lint-pinned): the trace context rides
        # the dispatch record to the worker process — key ABSENT when
        # unarmed so the wire bytes are unchanged byte-for-byte
        if ticket.trace is not None:
            rec["trace"] = ticket.trace.to_wire()
        # Abacus: same key-absent discipline — default-tenant dispatch
        # records carry no tenant key, so the wire is unchanged unless
        # a caller actually names a tenant
        if ticket.tenant != "default":
            rec["tenant"] = ticket.tenant
        # Lighthouse (obs/audit.py): the chain seed over the carried
        # prefix rides the dispatch, so the worker's leg fingerprint
        # resumes where the dead/prefill leg left off — key ABSENT
        # unarmed, wire bytes unchanged
        if audit.enabled():
            rec["fp"] = audit.seed_of(ticket.prefix)
        # Prism: decode spec + RNG resume step ride the dispatch —
        # keys ABSENT for greedy/fresh requests (wire bytes unchanged)
        if ticket.decode:
            rec["decode"] = ticket.decode
            if ticket.prefix:
                rec["step0"] = len(ticket.prefix)
        try:
            place_rec = {
                "event": "place", "request_id": ticket.request_id,
                "replica": h.index, "life": ticket.life,
                "prefix": ticket.prefix}
            if ticket.stage:
                place_rec["stage"] = ticket.stage
            self.journal.append(place_rec)
            k = self._ns.add(f"reqn/{h.index}", 1) - 1
            self._ns.set(f"req/{h.index}/{k}",
                         json.dumps(rec, sort_keys=True).encode())
        except (OSError, TimeoutError):
            failure.count_store_error("coord_place")
            ticket.assigned = None
            return None
        ticket.assigned = h.index
        # optimistic queue-depth bump so a burst of placements between
        # gauge refreshes doesn't pile onto one replica
        h.engine.scheduler.queue_depth += 1
        return h.index

    def generate(self, prompt, max_new_tokens: int,
                 timeout: Optional[float] = None):
        ticket = self.submit(prompt, max_new_tokens)
        if not self._started:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while not ticket.done.is_set():
                self.poll()
                if deadline is not None and time.monotonic() > deadline:
                    break
                time.sleep(self._poll_interval)
        return ticket.result(timeout)

    # -- supervision -----------------------------------------------------

    def start(self) -> "ProcessFleet":
        if self._started:
            return self
        self._started = True
        self._sup_thread = threading.Thread(
            target=self._supervise, name="procfleet-supervisor",
            daemon=True)
        self._sup_thread.start()
        return self

    def _supervise(self) -> None:
        while not self._sup_stop.wait(self._poll_interval):
            try:
                self.poll()
            except chaos.CoordinatorKillError:
                self._die("chaos:kill_coordinator")
                return
            except Exception:
                log.exception("procfleet poll failed")

    def _die(self, reason: str) -> None:
        """Coordinator death (chaos drill / :meth:`abandon`): beats and
        supervision stop, worker PROCESSES are left running — exactly
        the wreckage :meth:`recover_from` must take over."""
        self.dead = True
        self._pump.stop()
        flight.record("fleet", "coordinator_down",
                      note=f"inc={self.incarnation} {reason}")
        log.warning("procfleet coordinator %d down: %s",
                    self.incarnation, reason)

    def abandon(self) -> None:
        """Drill helper: die like a crashed coordinator (no worker
        teardown, no store cleanup, journals left mid-sentence)."""
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=5.0)
            self._sup_thread = None
        self._die("abandoned")

    def poll(self) -> None:
        """One supervision pass. The chaos hook runs OUTSIDE the store
        try-block: an injected coordinator kill must escape; a store
        partition must not."""
        with self._lock:
            chaos.on_coordinator_poll()
            if self.dead:
                return
            try:
                self._ns.set("coord/beat", repr(time.time()).encode())
                self._refresh_gauges()
                self._check_enrollment()
                self._check_exits()
                self._check_stale()
                self._restart_due()
                self._retry_unplaced()
                self._check_progress()
                self._reap_retiring()
                if self._helm is not None:
                    for d in self._helm.step_all():
                        self.helm_journal.append_line(d.as_json())
            except (OSError, TimeoutError):
                # partition window: absorb, retry next tick
                failure.count_store_error("coord_poll")

    def _refresh_gauges(self) -> None:
        for h in self._replicas:
            if h.state in (DEAD, QUARANTINED):
                continue
            try:
                if not self._ns.check(f"gauge/{h.index}"):
                    continue
                g = json.loads(self._ns.get(
                    f"gauge/{h.index}", timeout_ms=500).decode())
            except (OSError, TimeoutError, ValueError):
                failure.count_store_error("coord_gauge")
                continue
            sched = h.engine.scheduler
            sched.queue_depth = int(g.get("queue_depth", 0))
            sched.max_queue = max(int(g.get("max_queue", 1)), 1)
            sched.pool.free_blocks = int(g.get("free_blocks", 0))
            sched.pool.num_blocks = max(int(g.get("num_blocks", 1)), 1)
            sched.pool.block_size = max(int(g.get("block_size", 1)), 1)
            h.gauge_round = int(g.get("round", 0))
            if h.state == STARTING and not h.retiring:
                # join gate: a worker publishing gauges is live and
                # serving — routable from here on
                self._set_state(h, READY, reason="join:gauge")
        self._publish_roles()

    def _publish_roles(self) -> None:
        """Refresh ``serve_fleet_replicas{role}`` from the live set.

        Same store-fed gauges the router places over: a role's count is
        its READY handles, so the gauge and ``Router.place(stage=)``
        can never disagree about pool capacity."""
        counts = {"unified": 0, "prefill": 0, "decode": 0}
        for h in self._replicas:
            if h.state == READY:
                counts[h.role] = counts.get(h.role, 0) + 1
        for role, n in counts.items():
            self._g_role_replicas.set(float(n), role=role)

    def _check_enrollment(self) -> None:
        """Complete the cross-host handshake for remote spawns.

        A :class:`TemplateProvisioner` launch returns no ``Popen`` —
        the worker materializes on another host and announces itself by
        writing ``enroll/<index>`` (pid + host) into the shared store.
        Until that record lands the handle has no pid and
        ``_proc_exit_code`` reports nothing; liveness is governed by
        the join timeout and heartbeats, exactly like a local worker
        whose process object was lost to a coordinator crash."""
        for h in self._replicas:
            if not h.remote or h.pid is not None or h.state == DEAD:
                continue
            try:
                if not self._ns.check(f"enroll/{h.index}"):
                    continue
                rec = json.loads(self._ns.get(
                    f"enroll/{h.index}", timeout_ms=500).decode())
            except (OSError, TimeoutError, ValueError):
                failure.count_store_error("coord_enroll")
                continue
            h.pid = int(rec.get("pid", 0)) or None
            h.host = str(rec.get("host", ""))
            flight.record("fleet", "enroll",
                          note=f"r{h.index} pid={h.pid} host={h.host}")
            self.journal.append_line(json.dumps({
                "event": "enroll", "replica": h.index,
                "pid": h.pid, "host": h.host, "role": h.role,
            }, sort_keys=True))
            self._write_members()

    def _check_exits(self) -> None:
        for h in self._replicas:
            # a QUARANTINED worker's exit is the quarantine's own kill
            # — it must not be reclassified as a crash and restarted
            if h.state in (DEAD, QUARANTINED):
                continue
            code = self._proc_exit_code(h)
            if code is None:
                if (h.state == STARTING and time.monotonic()
                        - h.spawned_at > self._join_timeout):
                    self._fail_replica(h, kind="hang",
                                       reason="join_timeout")
                continue
            if h.retiring:
                continue  # _reap_retiring credits the drain
            if code in (0, failure.GRACEFUL_EXIT_CODE):
                # drained outside a retire (SIGTERM from outside):
                # free restart, like any preemption
                self._fail_replica(h, kind="preempt",
                                   reason="preempt:graceful_exit",
                                   code=code)
            else:
                self._fail_replica(h, kind="crash",
                                   reason=f"crash:exit={code}",
                                   code=code)

    def _check_stale(self) -> None:
        # READY/DRAINING replicas whose process runs but whose beat
        # went stale (wedged decode loop, suppressed watchdog).
        # STARTING replicas are join_timeout's business — their stale
        # pre-restart beat must not re-kill a booting worker.
        alive = {h.index for h in self._replicas
                 if h.state in (READY, DRAINING)
                 and self._proc_exit_code(h) is None}
        if not alive or self._detector is None:
            return
        by_index = {h.index: h for h in self._replicas}
        for idx in self._detector.stale_ranks(alive=alive):
            self._fail_replica(by_index[idx], kind="hang",
                               reason="hang:heartbeat_stale")

    def _fail_replica(self, h: ProcReplica, *, kind: str, reason: str,
                      code: Optional[int] = None) -> None:
        stranded = [t for t in self._tickets.values()
                    if not t.done.is_set() and t.assigned == h.index]
        ids = [t.request_id for t in stranded]
        self._set_state(h, DEAD, reason=reason)
        if h.proc is not None and h.proc.poll() is None:
            h.proc.kill()  # a declared-dead wedged worker gets no vote
        elif h.proc is None and h.pid is not None and kind == "hang":
            try:
                os.kill(h.pid, 9)
            except (OSError, ProcessLookupError):
                pass
        flight.record("fleet", "replica_down",
                      note=f"{h.name} reason={reason} "
                           f"stranded={','.join(ids)}")
        flight.dump_now(f"replica_down:{h.name}", force=True)
        watchtower.on_replica_down(h.index, reason, ids)
        if self.metrics is not None:
            self.metrics.emit("fleet_replica_down", replica=h.index,
                              reason=reason, stranded=ids)
        t_detect = time.monotonic()
        for t in stranded:
            payload = self._read_done(t)
            if payload is not None:  # it actually finished first
                # a prefill leg that published done before dying
                # (kill_transfer mid-push) hands off — the decode leg
                # pulls a dead wire and re-prefills cold
                self._on_done_payload(t, payload)
                continue
            self._readmit(t, self._read_prog(t), from_replica=h.index,
                          t_detect=t_detect, reason=reason)
        duration = time.monotonic() - h.spawned_at
        decision = h.policy.on_exit(
            reason=kind, code=(code if code is not None
                               else chaos.CRASH_EXIT_CODE),
            duration_s=duration, beat_seen=True)
        if decision.action == "restart" and not h.retiring:
            h.restart_at = time.monotonic() + decision.delay_s
        else:
            h.restart_at = None
            h.stop_reason = decision.why
        self._write_members()

    # -- Lighthouse output-integrity auditing (obs/audit.py) -------------

    def _verify_fp(self, t: ProcTicket, tail: list) -> None:
        """Check the worker's published leg fingerprint (``fp/<rid>``,
        life-matched, written BEFORE ``done/<rid>``) against the
        coordinator's own chain over prefix + tail. A mismatch means
        the stream was corrupted somewhere between decode and the wire
        — page, then quarantine the worker (policy-gated)."""
        try:
            if not self._ns.check(f"fp/{t.request_id}"):
                return  # store blip / pre-audit worker: no evidence
            p = json.loads(self._ns.get(
                f"fp/{t.request_id}", timeout_ms=500).decode())
        except (OSError, TimeoutError, ValueError):
            failure.count_store_error("coord_fp")
            return
        if int(p.get("life", -1)) != t.life:
            return
        got = str(p.get("fp", ""))
        want = audit.chain("", list(t.prefix) + [int(x) for x in tail])
        if not got or got == want:
            return
        idx = (t.assigned if t.assigned is not None
               else int(p.get("replica", -1)))
        audit.on_divergence("worker", request_id=t.request_id,
                            pair=(f"p{idx}",), suspect=f"p{idx}",
                            note="fp chain mismatch")
        watchtower.on_output_divergence(
            "worker", request_id=t.request_id, pair=(f"p{idx}",),
            suspect=f"p{idx}")
        if audit.quarantine_enabled():
            h = next((x for x in self._replicas if x.index == idx),
                     None)
            if h is not None:
                self._quarantine_replica(
                    h, reason=f"worker_divergence:{t.request_id}")

    def _quarantine_replica(self, h: ProcReplica, *,
                            reason: str) -> None:
        """Isolate a confirmed-diverging worker: QUARANTINED through
        the counted choke point, the process killed, its in-flight
        requests re-admitted on survivors — and never restarted (the
        policy governor never sees this exit; :meth:`_check_exits`
        skips quarantined handles)."""
        if h.state in (DEAD, QUARANTINED):
            return
        stranded = [t for t in self._tickets.values()
                    if not t.done.is_set() and t.assigned == h.index]
        ids = [t.request_id for t in stranded]
        self._set_state(h, QUARANTINED, reason=reason)
        if h.proc is not None and h.proc.poll() is None:
            h.proc.kill()
        elif h.pid is not None:
            try:
                os.kill(h.pid, 9)
            except (OSError, ProcessLookupError):
                pass
        h.restart_at = None
        h.stop_reason = f"quarantined:{reason}"
        audit.on_quarantine(h.name, reason)
        flight.record("fleet", "quarantine",
                      note=f"{h.name} reason={reason} "
                           f"stranded={','.join(ids)}")
        flight.dump_now(f"quarantine:{h.name}", force=True)
        if self.metrics is not None:
            self.metrics.emit("fleet_quarantine", replica=h.index,
                              reason=reason, stranded=ids)
        log.warning("procfleet: replica %s QUARANTINED (%s), "
                    "re-admitting %d request(s)", h.name, reason,
                    len(ids))
        t_detect = time.monotonic()
        for t in stranded:
            self._readmit(t, self._read_prog(t), from_replica=h.index,
                          t_detect=t_detect,
                          reason=f"quarantine:{reason}")
        self._write_members()

    def _read_prog(self, t: ProcTicket) -> list[int]:
        """Life-checked progress read: tokens a dead life emitted. A
        record from an OLDER life must be ignored — its tokens are
        already inside ``t.prefix`` and counting them twice is exactly
        the duplicate-emission bug the life field exists to stop."""
        try:
            if not self._ns.check(f"prog/{t.request_id}"):
                return []
            p = json.loads(self._ns.get(
                f"prog/{t.request_id}", timeout_ms=500).decode())
        except (OSError, TimeoutError, ValueError):
            failure.count_store_error("coord_prog")
            return []
        if int(p.get("life", -1)) != t.life:
            return []
        if t.branches > 1:
            # best-of-n re-admits from the bare prompt: one branch's
            # tail is not "the" stream, and deterministic seeding
            # re-derives every branch identically anyway
            return []
        return [int(x) for x in p.get("tokens", [])]

    def _read_done(self, t: ProcTicket) -> Optional[dict]:
        try:
            if not self._ns.check(f"done/{t.request_id}"):
                return None
            p = json.loads(self._ns.get(
                f"done/{t.request_id}", timeout_ms=500).decode())
        except (OSError, TimeoutError, ValueError):
            failure.count_store_error("coord_done")
            return None
        return p if int(p.get("life", -1)) == t.life else None

    def _readmit(self, t: ProcTicket, emitted: list[int], *,
                 from_replica: int, t_detect: float,
                 reason: str) -> None:
        t.prefix.extend(emitted)
        t.life += 1
        # a prefill leg that already banked its first token re-admits
        # as a decode leg (there is nothing left to prefill); its pull
        # finds no wire and re-prefills cold on the decode replica
        if t.stage == "prefill" and t.prefix:
            t.stage = "decode"
        # Causeway: the re-admitted life is a child leg of the same
        # trace — linked to the original, never a fresh trace_id
        nxt = trace.on_resubmit(t.trace)
        if nxt is not None:
            t.trace = nxt
        if len(t.prefix) >= t.max_new_tokens:
            self._finalize_from_payload(
                t, {"life": t.life, "status": "done", "tokens": []})
            return
        self.failovers += 1
        placed = self._place(t)
        fo = dict(from_replica=from_replica,
                  to_replica=(-1 if placed is None else placed),
                  reason=reason,
                  readmit_s=round(time.monotonic() - t_detect, 6),
                  prefix_tokens=len(t.prefix))
        t.failovers.append(fo)
        trace.on_segment(t.trace, "failover", t_detect,
                         time.monotonic(), request_id=t.request_id,
                         from_replica=from_replica, reason=reason)
        flight.record("fleet", "readmit",
                      note=f"{t.request_id} r{from_replica}->"
                           f"r{fo['to_replica']} "
                           f"prefix={len(t.prefix)}")
        if self.metrics is not None:
            self.metrics.emit("fleet_failover",
                              request_id=t.request_id, **fo)

    def _restart_due(self) -> None:
        now = time.monotonic()
        for h in self._replicas:
            if (h.state == DEAD and not h.retiring
                    and h.restart_at is not None
                    and now >= h.restart_at):
                self._set_state(h, STARTING,
                                reason=f"restart #{h.incarnations}")
                h.proc = None
                h.pid = None
                h.adopted = False
                self._launch(h)
                self._write_members()

    def _retry_unplaced(self) -> None:
        for t in self._tickets.values():
            if (not t.done.is_set() and t.assigned is None
                    and not t.pumping):
                self._place(t)

    def _check_progress(self) -> None:
        """Finalize finished requests; stamp first-token times. A
        prefill leg's done payload routes to the handoff instead."""
        for t in list(self._tickets.values()):
            if t.done.is_set() or t.assigned is None:
                continue
            payload = self._read_done(t)
            if payload is not None:
                self._on_done_payload(t, payload)
                continue
            if t.t_first_token == 0.0 and (t.prefix
                                           or self._read_prog(t)):
                t.t_first_token = time.monotonic()

    def _on_done_payload(self, t: ProcTicket, payload: dict) -> None:
        """Route one life-matched ``done/<rid>`` payload: a completed
        prefill leg hands off to the decode pool; anything else
        finalizes. The ONE junction all three readers use
        (:meth:`_check_progress`, :meth:`_fail_replica`,
        :meth:`_recover_tickets`) so a drill can land the death at any
        of them and take the same path."""
        if (t.stage == "prefill"
                and payload.get("status", "done") == "done"):
            self._handoff(t, payload)
        else:
            self._finalize_from_payload(t, payload)

    def _handoff(self, t: ProcTicket, payload: dict) -> None:
        """Prefill -> decode handoff: bank the first token, journal
        the boundary, and hand the decode leg to the transfer pump —
        placement and the KV wire watch happen on the pump thread, so
        this (poll-loop) path never blocks on a transfer."""
        tail = [int(x) for x in payload.get("tokens", [])]
        src = t.assigned if t.assigned is not None else -1
        t.prefix.extend(tail)
        if t.t_first_token == 0.0 and t.prefix:
            t.t_first_token = time.monotonic()
        # EOS-on-first-token or a budget of 1: nothing left to decode
        if not tail or len(t.prefix) >= t.max_new_tokens:
            self._finalize_from_payload(
                t, {"life": t.life, "status": "done", "tokens": []})
            return
        t.life += 1
        t.stage = "decode"
        t.assigned = None
        # Causeway: the decode leg is a child leg of the same trace
        nxt = trace.on_resubmit(t.trace)
        if nxt is not None:
            t.trace = nxt
        failure.store_call(
            lambda: self.journal.append({
                "event": "handoff", "request_id": t.request_id,
                "from_replica": src, "life": t.life,
                "prefix": t.prefix}),
            op="coord_journal", deadline_s=1.0, fallback=None)
        flight.record("fleet", "handoff",
                      note=f"{t.request_id} r{src}->decode "
                           f"prefix={len(t.prefix)}")
        if self.metrics is not None:
            self.metrics.emit("fleet_handoff",
                              request_id=t.request_id,
                              from_replica=src,
                              prefix_tokens=len(t.prefix))
        t.pumping = True
        self._pump.enqueue(t, src)

    def _finalize_from_payload(self, t: ProcTicket,
                               payload: dict) -> None:
        status = payload.get("status", "done")
        tail = [int(x) for x in payload.get("tokens", [])]
        if status == "done" and audit.enabled():
            self._verify_fp(t, tail)
        if status == "done":
            t.tokens = np.asarray(t.prefix + tail, np.int32)
            t.status = "done"
            if t.t_first_token == 0.0:
                t.t_first_token = time.monotonic()
        else:
            t.status = "rejected"
            t.reject_reason = payload.get("reason", status)
        t.t_done = time.monotonic()
        rec = dict(request_id=t.request_id,
                   prompt_len=len(t.prompt),
                   new_tokens=(len(t.tokens)
                               if t.tokens is not None else 0),
                   status=t.status,
                   ttft_s=round(t.ttft_s, 6),
                   total_s=round(t.t_done - t.t_submit, 6),
                   replica=(f"p{t.assigned}"
                            if t.assigned is not None else ""),
                   failovers=t.failovers)
        if t.status == "done":
            self.completed.append(rec)
        try:
            self.journal.append({"event": "final",
                                 "request_id": t.request_id,
                                 "status": t.status,
                                 "new_tokens": rec["new_tokens"],
                                 "life": t.life})
        except (OSError, TimeoutError):
            failure.count_store_error("coord_journal")
        self._tickets.pop(t.request_id, None)
        if t.stage:
            # best-effort wire GC: the decode leg is finalized, the
            # kvwire/* records are dead weight in the store
            kv_wire.cleanup(self._ns, t.request_id)
        t.done.set()

    # -- elastic scaling -------------------------------------------------

    def scale_to(self, n: int, *, reason: str = "",
                 pool: str | None = None) -> dict:
        """Helm's actuator, process edition: up spawns fresh indexes
        (join gate: STARTING until the first gauge lands), down drains
        the highest non-retiring slots through ``ctl/<idx>=drain`` —
        the worker finishes everything it holds, exits
        ``GRACEFUL_EXIT_CODE``, and a later poll reaps it.

        ``pool=`` scopes the target to one disaggregated role: ``n``
        then counts only that pool's replicas, new spawns carry the
        pool as their ``--role``, and drains pick the highest index
        *within* the pool — the other pool's slots are untouched."""
        n = int(n)
        if n < 1:
            raise ValueError(f"scale_to: n must be >= 1, got {n}")
        role = pool if pool is not None else "unified"
        with self._lock:
            current = [h for h in self._replicas
                       if not h.retiring
                       and h.state not in (DEAD, QUARANTINED)
                       and (pool is None or h.role == pool)]
            delta = n - len(current)
            added, retiring = 0, 0
            if delta > 0:
                for _ in range(delta):
                    self._spawn_new(reason="scale_up", role=role)
                    added += 1
            elif delta < 0:
                doomed = sorted(current, key=lambda r: -r.index)
                for h in doomed[:-delta]:
                    h.retiring = True
                    h.restart_at = None
                    self._set_state(h, DRAINING, reason="scale_down")
                    try:
                        self._ns.set(f"ctl/{h.index}", b"drain")
                    except (OSError, TimeoutError):
                        failure.count_store_error("coord_ctl")
                    retiring += 1
            if pool is None:
                self._target_replicas = n
            else:
                self._pool_targets[pool] = n
                self._target_replicas = sum(self._pool_targets.values())
            flight.record(
                "fleet", "scale_to",
                note=f"target={n} added={added} retiring={retiring}"
                     + (f" pool={pool}" if pool else "")
                     + (f" {reason}" if reason else ""))
            if self.metrics is not None:
                self.metrics.emit("fleet_scale", target=n, added=added,
                                  retiring=retiring, reason=reason)
            self._write_members()
            self._rebuild_detector()
        return dict(target=n, added=added, retiring=retiring)

    def scalable_pools(self) -> tuple:
        """Pools Helm scales independently — disaggregated fleets
        expose both stages; unified fleets scale as one pool (empty
        tuple keeps :class:`FleetAutoscaler` on its legacy path)."""
        return ("prefill", "decode") if self.disagg else ()

    def pool_target(self, pool: str) -> int:
        return int(self._pool_targets.get(pool, 1))

    def _reap_retiring(self) -> None:
        done = [h for h in self._replicas if h.retiring
                and (h.state == DEAD
                     or self._proc_exit_code(h) is not None)]
        if not done:
            return
        for h in done:
            if h.state != DEAD:
                h.policy.on_exit(
                    reason="preempt", code=failure.GRACEFUL_EXIT_CODE,
                    duration_s=time.monotonic() - h.spawned_at,
                    beat_seen=True)
            self._replicas.remove(h)
            flight.record("fleet", "retired", note=h.name)
        self._write_members()
        self._rebuild_detector()

    # -- shutdown --------------------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        if self._sup_thread is not None:
            self._sup_stop.set()
            self._sup_thread.join(timeout=5.0)
            self._sup_thread = None
        self._started = False
        self._pump.stop()
        for h in self._replicas:
            try:
                self._ns.set(f"ctl/{h.index}", b"stop")
            except (OSError, TimeoutError):
                failure.count_store_error("coord_ctl")
        deadline = time.monotonic() + timeout
        for h in self._replicas:
            if h.proc is None:
                continue
            try:
                h.proc.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(timeout=5.0)
        for h in self._replicas:
            if h.proc is None and h.pid is not None and not h.remote:
                try:
                    os.kill(h.pid, 15)
                except (OSError, ProcessLookupError):
                    pass
        self._provisioner.close()
        try:
            self._client.close()
        except OSError:
            pass
        if self._owns_server and self._server is not None:
            self._server.stop()

    def __enter__(self) -> "ProcessFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- introspection ---------------------------------------------------

    @property
    def replicas(self) -> list[ProcReplica]:
        return list(self._replicas)

    @property
    def live_replicas(self) -> int:
        return sum(1 for h in self._replicas if h.state == READY)

    @property
    def target_replicas(self) -> int:
        return self._target_replicas

    def wait_ready(self, n: int, timeout: float = 60.0) -> bool:
        """Block until ``n`` replicas are READY (driving poll() itself
        when the supervisor thread isn't running)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self._started:
                self.poll()
            if self.live_replicas >= n:
                return True
            time.sleep(self._poll_interval)
        return self.live_replicas >= n

    def wait_all(self, tickets, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        for t in tickets:
            if not t.wait(max(deadline - time.monotonic(), 0.01)):
                return False
        return True

    def summary(self) -> dict:
        per_replica = []
        for h in self._replicas:
            per_replica.append(dict(
                replica=h.name, state=h.state, pid=h.pid,
                adopted=h.adopted, incarnations=h.incarnations,
                budget_restarts=h.policy.budget_restarts,
                preempt_restarts=h.policy.preempt_restarts,
                stop_reason=h.stop_reason))
        out = dict(
            coordinator_incarnation=self.incarnation,
            gap_s=round(self.gap_s, 3),
            replicas=len(self._replicas),
            live=self.live_replicas,
            requests_done=len(self.completed),
            in_flight=len(self._tickets),
            failovers=self.failovers,
            tokens_out=int(sum(r["new_tokens"]
                               for r in self.completed)),
            recovery=dict(self.recovery),
            per_replica=per_replica,
        )
        if meter.enabled():
            # Abacus fleet rollup: worker processes publish their
            # ledgers at meter/<rank> (fleet_worker serve loop); merge
            # them with the coordinator's own (wire-byte) ledger
            from pytorch_distributed_nn_tpu.obs import aggregate
            ledgers = aggregate.collect_ledgers(
                self._ns, [h.index for h in self._replicas])
            local = meter.export_ledgers()
            if local:
                ledgers = meter.merge_ledgers([ledgers, local])
            out["meter"] = dict(
                ledgers=ledgers,
                totals=meter.ledger_totals(ledgers))
        if audit.enabled():
            out["audit"] = audit.summary()
        return out
