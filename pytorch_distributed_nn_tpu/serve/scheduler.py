"""Request scheduler: bounded admission queue + prefill/decode policy.

The serving control plane. Clients call :meth:`Scheduler.submit` from
any thread; the engine loop (one thread, :mod:`serve.engine`) calls
:meth:`next_admissions` once per decode round to pull newly admitted
requests into free batch slots, and :meth:`retire` / :meth:`fail` to
release them. Policy decisions live here so the engine stays a dumb
batch-stepper:

- **backpressure**: the waiting queue is bounded (``max_queue``); a
  submit that finds it full is rejected immediately with reason
  ``backpressure`` instead of growing an unbounded buffer the server
  then OOMs on. Chaos load-shedding (``serve_reject@p=``) and oversize
  prompts (``too_large``) reject at the same choke point;
- **anti-starvation**: admission is STRICT FIFO *per tenant* with no
  bypass, deficit-round-robin across tenants. Each tenant holds its
  own FIFO deque; admission rotates through the tenant ring taking at
  most one request per tenant per turn, so a tenant with a thousand
  queued requests cannot monopolize the prefill budget — the light
  tenant's head is at most one rotation away. Within a tenant the old
  invariant holds: if the head does not fit (batch slot or KV-pool
  reservation), nothing is admitted this round — smaller requests
  cannot leapfrog a big one forever, and with
  reservation-at-admission (:mod:`serve.kv_pool`) every running
  sequence finishes within its token budget, so every admitted request
  finishes within a bounded number of scheduler rounds (tested under
  sustained overload in tests/test_serve.py). A reserve failure breaks
  the whole round, not just the tenant — skipping to a neighbor's
  smaller request would starve the big-request tenant forever;
- **tenant quotas**: ``tenant_quotas={"name": n}`` caps a tenant's
  *live* residency (queued + running) at n; a submit past the cap is
  rejected ``tenant_quota`` at the same choke point as backpressure.
  The cap bounds concurrency, not total service: as the tenant's
  requests retire, new ones fit again — a flash crowd sheds its excess
  instead of starving its neighbors (drilled by chaos
  ``tenant_flood@tenant=...:rps=...``);
- **prefix-cache admission**: with a :class:`serve.prefix_cache
  .PrefixCache` attached, admission goes through
  :meth:`PrefixCache.admit` instead of a bare ``pool.reserve`` — a
  resident shared prefix is reserved by reference and the engine
  prefills only the suffix; retirement donates the finished sequence's
  full blocks back to the index (:meth:`retire` →
  :meth:`PrefixCache.release`);
- **interleave**: at most ``max_prefills_per_round`` queued requests
  are admitted per round. Prefill is O(prompt) compute injected into
  the decode cadence — unbounded admission would stall every running
  stream's next token behind a burst of prefills (TTFT for the new
  requests at the cost of inter-token latency for everyone else);
- **deadlines**: a request whose deadline passes while still queued is
  rejected (``deadline``) at the next round rather than prefillled into
  a batch slot it can no longer use.

Every request state change goes through :meth:`Scheduler._transition`,
which increments the ``serve_requests_total{state=}`` counter AND the
per-tenant ``serve_tenant_requests_total{tenant,state}`` counter — the
test_quality.py lint enforces that no admit/reject/retire path can
bypass the accounting. Rejections additionally bump
``serve_rejects_total{reason=}`` and land a ``serve`` event in the
flight ring, so an overloaded server's shed traffic is visible in
post-mortems, not just in client-side errors.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Optional

import numpy as np

from pytorch_distributed_nn_tpu.obs import flight, meter, trace, watchtower
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.runtime import chaos
from pytorch_distributed_nn_tpu.serve.decoding import DecodeSpec, TokenStream
from pytorch_distributed_nn_tpu.serve.kv_pool import KVPool

# request lifecycle (terminal states: REJECTED, DONE, FAILED)
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
REJECTED = "rejected"
FAILED = "failed"

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle record. ``done`` is set
    exactly once, on any terminal transition — clients block on it."""

    prompt: np.ndarray  # (L,) int32
    max_new_tokens: int
    request_id: str
    deadline_s: Optional[float] = None  # absolute time.monotonic()
    state: str = QUEUED
    reject_reason: str = ""
    tokens: Optional[np.ndarray] = None  # generated tokens, (<=n,) int32
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    # timing (time.monotonic()) — TTFT/latency histograms feed on these
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0
    # logical-request origin times (fleet-level): a resubmitted leg —
    # failover re-admission or a disagg decode-leg rewrite — carries
    # the ORIGINAL arrival in t_origin (0.0: this leg is the arrival)
    # and, when an earlier leg already delivered the first token, that
    # token's time in t_first_origin (0.0: not delivered yet). TTFT is
    # always charged from t_origin, exactly once per logical request.
    t_origin: float = 0.0
    t_first_origin: float = 0.0
    # scheduler-round bookkeeping (the anti-starvation test's evidence)
    round_submitted: int = -1
    round_admitted: int = -1
    round_done: int = -1
    # fleet failover re-admission (serve/fleet.py): this request id was
    # already counted queued/running in its first life on a replica
    # that died — _transition must not double-count those states
    resubmitted: bool = False
    # multi-tenant serving (Mosaic): quota/fairness identity + the
    # per-request LoRA adapter; prefix_match is the PrefixMatch the
    # admission pass stored (the engine's restore/suffix-prefill input)
    tenant: str = "default"
    adapter: int = 0
    prefix_match: object = None
    # Causeway (obs/trace.py): the propagated TraceContext, or None
    # when tracing is unarmed / the request is not sampled
    trace: object = None
    # Lighthouse (obs/audit.py): the fingerprint-chain seed this leg
    # resumes from — the chain over the tokens an earlier leg already
    # emitted (failover re-admission / disagg handoff), "" for a fresh
    # request or an unarmed process
    fp_seed: str = ""
    # True while this request holds a slot in its tenant's live-quota
    # count (set on QUEUED, dropped on any terminal transition)
    quota_held: bool = False
    # Prism (serve/decoding.py): how this request's tokens are chosen.
    # None = greedy single-branch — the byte-identity default every
    # pre-Prism caller gets. decode_step0 is the sampling-RNG step this
    # leg resumes at (= tokens earlier legs already emitted: a disagg
    # decode leg or a failover re-admission continues the fold_in
    # sequence instead of restarting it).
    decode: object = None
    decode_step0: int = 0
    # incremental streaming: the TokenStream the engine's _emit_chunk
    # funnel feeds; None when the client didn't ask to stream
    stream: object = None
    # n-best results for branched requests, best-first:
    # [{"tokens": [...], "logprob": float}]; logprob is the winner's
    # cumulative logprob (req.tokens = the winner's stream)
    n_best: object = None
    logprob: float = 0.0

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens

    @property
    def branches(self) -> int:
        """Batch rows / KV tails this request decodes in parallel."""
        return self.decode.branches if self.decode is not None else 1

    @property
    def ok(self) -> bool:
        return self.state == DONE


def branch_seq_ids(req: Request) -> list[str]:
    """Pool sequence ids for a request's decode branches. Branch 0 IS
    the request id (an n=1 request's accounting is byte-identical to
    pre-Prism); extra branches suffix ``#bK``."""
    rid = req.request_id
    return [rid] + [f"{rid}#b{k}" for k in range(1, req.branches)]


class Scheduler:
    """Admission queue + policy over a shared :class:`KVPool`."""

    def __init__(self, pool: KVPool, *, max_queue: int = 64,
                 max_seq_len: int = 0,
                 max_prefills_per_round: int = 2,
                 tenant_quotas: Optional[dict] = None,
                 prefix_cache=None) -> None:
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_prefills_per_round < 1:
            raise ValueError("max_prefills_per_round must be >= 1, got "
                             f"{max_prefills_per_round}")
        self.pool = pool
        self.max_queue = max_queue
        self.max_seq_len = int(max_seq_len)
        self.max_prefills_per_round = max_prefills_per_round
        self.tenant_quotas = dict(tenant_quotas or {})
        for tenant, quota in self.tenant_quotas.items():
            if quota < 1:
                raise ValueError(f"tenant quota must be >= 1, got "
                                 f"{quota} for {tenant!r}")
        self.prefix_cache = prefix_cache  # PrefixCache | None
        self._lock = threading.Lock()
        # per-tenant FIFO deques + the DRR rotation ring (tenant names
        # in rotation order; the front tenant has next claim)
        self._queues: dict[str, collections.deque[Request]] = {}
        self._rr: collections.deque[str] = collections.deque()
        self._queued = 0  # total waiting across tenants (max_queue cap)
        self._live: dict[str, int] = {}  # tenant -> queued + running
        self.round = 0  # advanced by the engine, one per decode round
        self.draining = False
        self.metrics = None  # MetricsLogger; set by the owning engine
        reg = get_registry()
        self._c_requests = reg.counter(
            "serve_requests_total", "request state transitions",
            labels=("state",))
        self._c_tenant = reg.counter(
            "serve_tenant_requests_total",
            "request state transitions, per tenant",
            labels=("tenant", "state"))
        self._c_rejects = reg.counter(
            "serve_rejects_total", "requests rejected at admission",
            labels=("reason",))
        self._g_queue = reg.gauge(
            "serve_queue_depth", "requests waiting for a batch slot")

    # -- the single state-change choke point -------------------------------

    def _transition(self, req: Request, state: str,
                    reason: str = "") -> None:
        """EVERY request state change funnels through here (lint-
        enforced): the counter can't drift from reality, and terminal
        states release the waiting client exactly once."""
        req.state = state
        # Causeway breadcrumb (inert one-comparison no-op unless
        # TPUNN_TRACE armed AND this request was sampled): every state
        # change of a traced request marks its trace, lint-pinned to
        # this one choke point
        trace.on_transition(req.trace, state,
                            request_id=req.request_id)
        # Abacus tenant binding (inert unless TPUNN_METER armed, same
        # contract): QUEUED binds request_id -> tenant BEFORE the
        # admission pass's pool reservation bills any block-seconds,
        # lint-pinned to this one choke point like the trace mark above
        meter.on_request_state(req.request_id, req.tenant, state)
        # fleet re-admission idempotency: a request re-submitted with
        # the same id after a replica death already counted its
        # queued/running transitions in its first life — one logical
        # request must land in serve_requests_total{state} once per
        # state, or the fleet's request accounting drifts up with every
        # failover. Terminal states still count (the first life never
        # reached one); rejects stay per-occurrence (each reject IS a
        # distinct shed event and already spends the TTFT budget once).
        if not (req.resubmitted and state in (QUEUED, RUNNING)):
            self._c_requests.inc(state=state)
            self._c_tenant.inc(tenant=req.tenant, state=state)
        # tenant live-residency (the quota denominator): held from
        # QUEUED until any terminal state — running requests still
        # count against their tenant's cap
        if state == QUEUED and not req.quota_held:
            req.quota_held = True
            self._live[req.tenant] = self._live.get(req.tenant, 0) + 1
        elif state in (DONE, REJECTED, FAILED) and req.quota_held:
            req.quota_held = False
            self._live[req.tenant] -= 1
        if state == REJECTED:
            req.reject_reason = reason
            self._c_rejects.inc(reason=reason)
            flight.record("serve", f"reject:{reason}", note=req.request_id)
            # a shed request spends the TTFT SLO's error budget — the
            # watchtower's burn-rate detector must see it (inert no-op
            # when TPUNN_WATCH is unset), and the JSONL stream must
            # carry it too or obs_watch replay can't reproduce the
            # burn page the live tower raised
            watchtower.on_serve_reject(req.request_id, reason,
                                       tenant=req.tenant)
            if self.metrics is not None:
                self.metrics.emit("serve_reject",
                                  request_id=req.request_id, reason=reason,
                                  tenant=req.tenant)
        if state in (DONE, REJECTED, FAILED):
            req.t_done = time.monotonic()
            req.round_done = self.round
            if req.stream is not None:
                # idempotent terminal close: the engine's final
                # _emit_chunk already closed a DONE stream; a rejected
                # or failed request terminates its (empty) stream here
                # so a streaming client never hangs on a dead request
                req.stream.close()
            req.done.set()

    # -- client side -------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               resubmit: bool = False,
               tenant: str = "default",
               adapter: int = 0,
               trace_ctx: object = None,
               t_origin: Optional[float] = None,
               t_first_origin: float = 0.0,
               fp_seed: str = "",
               decode: object = None,
               decode_step0: int = 0,
               stream: bool = False) -> Request:
        """Thread-safe admission attempt. Always returns a Request; a
        rejected one is already terminal (``done`` set, ``state ==
        REJECTED``, ``reject_reason`` says why). ``resubmit`` marks a
        fleet failover re-admission (same ``request_id`` as a request
        stranded on a dead replica): its queued/running transitions are
        not re-counted (see :meth:`_transition`). ``t_origin`` /
        ``t_first_origin`` carry the logical request's original arrival
        and (if already delivered) first-token times across legs, so
        TTFT is charged from first submit exactly once;
        ``trace_ctx`` is the Causeway context riding the leg. A
        standalone (fleet-less) submit mints its own context when
        tracing is armed."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if decode is not None and not isinstance(decode, DecodeSpec):
            raise ValueError(
                f"decode must be a serve.decoding.DecodeSpec, got "
                f"{type(decode).__name__}")
        if decode == DecodeSpec():
            # an explicit all-defaults spec IS the greedy path: drop it
            # so every downstream key-absent / byte-identity contract
            # holds trivially (the inert-defaults lint's runtime half)
            decode = None
        if decode_step0 < 0:
            raise ValueError(
                f"decode_step0 must be >= 0, got {decode_step0}")
        if stream and decode is not None and decode.branches > 1:
            raise ValueError(
                "stream=True requires a single branch (best_of/n == 1):"
                " n-best ranking needs every full stream before it can "
                "pick a winner")
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            request_id=request_id or f"req-{next(_ids)}",
            deadline_s=deadline_s, t_submit=time.monotonic(),
            resubmitted=bool(resubmit),
            tenant=str(tenant), adapter=int(adapter),
            t_origin=float(t_origin) if t_origin else 0.0,
            t_first_origin=float(t_first_origin),
            fp_seed=str(fp_seed),
            decode=decode, decode_step0=int(decode_step0),
        )
        if stream:
            req.stream = TokenStream(req.request_id)
        # fleet legs arrive with their context minted at Fleet.submit;
        # a bare engine/scheduler mints here (same choke point role)
        req.trace = (trace_ctx if trace_ctx is not None or resubmit
                     else trace.on_submit(req.request_id,
                                          tenant=req.tenant))
        quota = self.tenant_quotas.get(req.tenant)
        with self._lock:
            req.round_submitted = self.round
            if self.draining:
                self._transition(req, REJECTED, reason="draining")
            elif self.max_seq_len and req.total_tokens > self.max_seq_len:
                self._transition(req, REJECTED, reason="too_large")
            elif chaos.on_admit(req.request_id):
                # chaos already emitted its own flight event (emit-first
                # lint); this transition adds the scheduler's view
                self._transition(req, REJECTED, reason="chaos")
            elif quota is not None \
                    and self._live.get(req.tenant, 0) >= quota:
                self._transition(req, REJECTED, reason="tenant_quota")
            elif self._queued >= self.max_queue:
                self._transition(req, REJECTED, reason="backpressure")
            else:
                q = self._queues.get(req.tenant)
                if q is None:
                    q = self._queues[req.tenant] = collections.deque()
                    self._rr.append(req.tenant)
                q.append(req)
                self._queued += 1
                self._transition(req, QUEUED)
            self._g_queue.set(self._queued)
        return req

    # -- engine side (one thread) ------------------------------------------

    def _reserve_locked(self, head: Request) -> bool:
        """One admission's KV reservation: through the prefix cache
        when attached (shared-prefix blocks reserved by reference, the
        match stored on the request for the engine's restore pass),
        bare ``pool.reserve`` otherwise. A branched (best-of-n) head
        then COW-forks one tail per extra branch off the primary —
        all-or-nothing: a tail that doesn't fit rolls the whole
        admission back. False = backpressure."""
        if self.prefix_cache is not None:
            match = self.prefix_cache.admit(
                head.request_id, head.prompt, head.total_tokens,
                adapter=head.adapter)
            if match is None:
                return False
            head.prefix_match = match
        elif not self.pool.reserve(head.request_id, head.total_tokens):
            return False
        sids = branch_seq_ids(head)
        for k, sid in enumerate(sids):
            if k == 0:
                continue
            # THE pool.fork call site (lint-pinned): branches share the
            # primary's full prompt blocks by refcount, so n branches
            # cost one prompt block set + n tails
            fork = lambda: self.pool.fork(
                head.request_id, sid, head.total_tokens,
                shared_tokens=len(head.prompt))
            if fork():
                continue
            if self.prefix_cache is not None:
                # the tail allocates straight off the free list, which
                # may be parked in the cached ring; without the same
                # LRU reclaim admit() gives the primary, a branched
                # head wedges the whole queue once donations fill the
                # pool (nothing running -> nothing ever frees)
                short = (self.pool.blocks_for(head.total_tokens)
                         - len(head.prompt) // self.pool.block_size
                         - self.pool.free_blocks)
                if short > 0 and self.prefix_cache.make_room(short) \
                        and fork():
                    continue
            for forked in sids[1:k]:
                self.pool.free(forked)
            if self.prefix_cache is not None:
                # unpin the COW tail the admit pinned, then drop the
                # primary without donating anything new
                self.prefix_cache.finish_restore(head.prefix_match)
                head.prefix_match = None
                self.prefix_cache.abandon(head.request_id)
            else:
                self.pool.free(head.request_id)
            return False
        return True

    def next_admissions(self, free_slots: int) -> list[Request]:
        """Pop eligible requests for this round: deficit round-robin
        across tenants (one request per tenant per rotation turn),
        strict FIFO within a tenant. Each admission must fit a free
        batch slot AND reserve its worst-case KV blocks. A head that
        can't reserve ends the whole round — no bypass, across tenants
        too (that's the anti-starvation invariant, not an inefficiency
        to optimize away without replacing the fairness proof)."""
        admitted: list[Request] = []
        now = time.monotonic()
        with self._lock:
            while (self._queued and free_slots > 0
                   and len(admitted) < self.max_prefills_per_round):
                # front of the rotation with work; ring stays put so
                # an emptied tenant doesn't burn a turn
                for _ in range(len(self._rr)):
                    if self._queues[self._rr[0]]:
                        break
                    self._rr.rotate(-1)
                q = self._queues[self._rr[0]]
                if not q:
                    break
                head = q[0]
                if head.deadline_s is not None and now > head.deadline_s:
                    q.popleft()
                    self._queued -= 1
                    self._transition(head, REJECTED, reason="deadline")
                    continue
                if head.branches > free_slots:
                    break  # n-way needs n rows NOW — no bypass, same
                    # anti-starvation rule as a failed reservation
                if not self._reserve_locked(head):
                    break  # no bypass: wait for blocks to free
                q.popleft()
                self._queued -= 1
                head.t_admit = now
                head.round_admitted = self.round
                self._transition(head, RUNNING)
                admitted.append(head)
                free_slots -= head.branches
                self._rr.rotate(-1)  # this tenant's turn is spent
            self._g_queue.set(self._queued)
        return admitted

    def retire(self, req: Request, tokens: np.ndarray) -> None:
        """A sequence finished (eos or budget): release its blocks and
        hand the tokens to the waiting client. With a prefix cache the
        release is a *donation*: the full blocks covering the written
        rows (prompt + all emitted tokens except the last, whose KV row
        was never computed) are indexed and parked cached instead of
        freed. The engine has already saved those rows to the device
        block store by the time this runs."""
        req.tokens = np.asarray(tokens, np.int32)
        if self.prefix_cache is not None:
            covered = (np.concatenate([req.prompt, req.tokens[:-1]])
                       if len(req.tokens) else req.prompt)
            self.prefix_cache.release(req.request_id, covered,
                                      adapter=req.adapter)
        else:
            self.pool.free(req.request_id)
        with self._lock:
            self._transition(req, DONE)

    def release_branch(self, req: Request, seq_id: str) -> None:
        """Per-branch retirement for a best-of-n request: drop ONE
        branch's reservation the moment it hits EOS/budget while its
        siblings keep decoding (refcounted prompt blocks stay live
        until the last sharer drops). Branched releases never donate
        to the prefix radix — n near-duplicate chains would churn the
        index for no reuse win — but the primary goes through
        ``abandon`` so radix-owned prompt blocks it borrowed stay with
        their chains."""
        if self.prefix_cache is not None and seq_id == req.request_id:
            self.prefix_cache.abandon(seq_id)
        else:
            self.pool.free(seq_id)

    def finish_branches(self, req: Request, tokens, n_best: list,
                        logprob: float) -> None:
        """Terminal transition for a branched request: every branch's
        reservation was already dropped via :meth:`release_branch`;
        the engine hands over the ranked results (``tokens`` = the
        winner's stream)."""
        req.tokens = np.asarray(tokens, np.int32)
        req.n_best = n_best
        req.logprob = float(logprob)
        with self._lock:
            self._transition(req, DONE)

    def fail(self, req: Request, reason: str) -> None:
        """Evict a running sequence (engine error path). Blocks are
        freed — every branch's, for a best-of-n request (freeing an
        unknown seq id is a benign no-op, so branches that already
        retired don't double-free); the client sees FAILED, not a
        hang."""
        for sid in branch_seq_ids(req):
            if self.prefix_cache is not None and sid == req.request_id:
                self.prefix_cache.abandon(sid)
            else:
                self.pool.free(sid)
        with self._lock:
            req.reject_reason = reason
            self._transition(req, FAILED)
        flight.record("serve", f"evict:{reason}", note=req.request_id)

    def drain(self) -> int:
        """Enter drain mode: stop admitting, reject everything still
        queued (reason ``draining``) so clients unblock; running
        sequences are the engine's to finish. Returns rejected count."""
        with self._lock:
            self.draining = True
            n = self._queued
            for q in self._queues.values():
                while q:
                    self._transition(q.popleft(), REJECTED,
                                     reason="draining")
            self._queued = 0
            self._g_queue.set(0)
        return n

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._queued
