"""Deterministic stub decoder for no-backend fleet drills.

The coordinator-restart and failover drills need a "model" with one
property and one property only: greedy decode must be a **pure function
of the token prefix**, exactly like the real engine's greedy path —
because that is the invariant the fleet's stitched re-admission leans
on (prompt + emitted-so-far re-fed as the new prompt reproduces the
continuation bit-for-bit). A rolling-hash next-token rule gives us that
with zero backend: any worker, any process, any incarnation decodes the
identical stream for the same prefix.

Used by ``serve/fleet_worker.py --backend stub`` (the tier-1
coordinator-restart drill) and by the drills' uninterrupted-reference
computation. Stdlib-only.
"""

from __future__ import annotations

from typing import Iterable

STUB_VOCAB = 4099  # prime: every hash bit lands in the token stream


def stub_next_token(prefix: Iterable[int],
                    vocab: int = STUB_VOCAB) -> int:
    """Next greedy token for a sequence prefix: an LCG-style rolling
    hash over the WHOLE prefix — suffix-sensitive, so a wrong stitch
    (dropped/duplicated token anywhere) derails every later token and
    the bit-identical assertions actually bite."""
    h = 0x811C9DC5
    for t in prefix:
        h = (h * 1103515245 + int(t) + 12345) & 0x7FFFFFFF
    return h % vocab


def stub_decode(prompt: Iterable[int], max_new_tokens: int,
                vocab: int = STUB_VOCAB) -> list[int]:
    """The uninterrupted reference: decode ``max_new_tokens`` from
    ``prompt`` in one life. Drills diff stitched fleet output against
    exactly this."""
    seq = [int(t) for t in prompt]
    out: list[int] = []
    for _ in range(int(max_new_tokens)):
        t = stub_next_token(seq, vocab)
        out.append(t)
        seq.append(t)
    return out
