"""Prism: per-request decoding policy for the serving engine.

The policy layer between the router and the jitted decode step. A
:class:`DecodeSpec` names *how* one request's tokens are chosen —
temperature / top-k / top-p sampling, how many parallel branches to
decode, which branch the client gets back — and rides the request from
:meth:`serve.server.InferenceServer.submit` through the scheduler into
:class:`serve.engine.ServingEngine`, where the jitted sampled step
consumes it as per-row device arrays.

Contracts (all lint- or golden-enforced):

- **inert defaults**: ``DecodeSpec()`` (temperature 0, one branch) IS
  the greedy path. The engine routes default requests through the
  exact pre-Prism jits (``_serve_prefill`` / ``_serve_step``), so
  greedy outputs, JSONL records, and Lighthouse fingerprint chains
  stay byte-identical to a build without this module;
- **seeded determinism**: every sampled token is drawn with a key
  derived *inside the jit* as ``fold_in(fold_in(key(seed), branch),
  step)`` — a pure function of ``(seed, branch, step)``, independent
  of batch composition, slot index, replica, or restart. Same
  ``(request, seed)`` ⇒ byte-identical streams across runs, across a
  thread fleet vs a process fleet, and across a disagg prefill→decode
  handoff (the decode leg resumes at ``step = len(prefix)``);
- **per-row masking is traced**: temperature / top_k / top_p arrive as
  ``(slots,)`` device arrays, so one compiled program serves every mix
  of greedy and sampled rows (a static per-value spec would recompile
  per distinct request). A ``temperature == 0`` row takes the greedy
  ``where`` branch and emits exactly the argmax token;
- **n-best is COW**: ``best_of`` branches share the prompt's
  refcounted KV blocks via :meth:`serve.kv_pool.KVPool.fork` and
  occupy ordinary batch rows; selection is by cumulative logprob
  (accumulated inside the jitted step, under the *model* distribution
  so greedy and sampled branches rank on the same scale).

:class:`TokenStream` is the client half of incremental streaming: the
engine's single ``_emit_chunk`` funnel feeds it, the client iterates
chunks as they land. Chunking never changes the retired fingerprint —
the Lighthouse fold runs over the full token list at retirement,
however the stream was cut.
"""

from __future__ import annotations

import dataclasses
import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np

_WIRE_FIELDS = ("temperature", "top_k", "top_p", "n", "best_of", "seed")


@dataclasses.dataclass(frozen=True)
class DecodeSpec:
    """How one request's tokens are chosen. Immutable; validation is
    loud at construction (chaos-grammar style) so a bad spec never
    reaches the scheduler."""

    temperature: float = 0.0  # 0.0 = greedy (argmax); seed is inert
    top_k: int = 0            # 0 = no top-k mask
    top_p: float = 0.0        # 0.0 = no nucleus mask; else (0, 1]
    n: int = 1                # completions returned (req.n_best)
    best_of: int = 0          # branches decoded; 0 = n
    seed: int = 0             # per-request RNG root

    def __post_init__(self) -> None:
        if not (self.temperature >= 0.0 and self.temperature == self.temperature):
            raise ValueError(
                f"temperature must be finite and >= 0, got "
                f"{self.temperature!r}")
        if not (isinstance(self.top_k, int) and self.top_k >= 0):
            raise ValueError(f"top_k must be an int >= 0, got "
                             f"{self.top_k!r}")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got "
                             f"{self.top_p!r}")
        if not (isinstance(self.n, int) and self.n >= 1):
            raise ValueError(f"n must be an int >= 1, got {self.n!r}")
        if not (isinstance(self.best_of, int) and self.best_of >= 0):
            raise ValueError(f"best_of must be an int >= 0, got "
                             f"{self.best_of!r}")
        if self.best_of and self.best_of < self.n:
            raise ValueError(
                f"best_of ({self.best_of}) must be >= n ({self.n}) — "
                f"cannot return more completions than were decoded")
        if not (isinstance(self.seed, int)
                and 0 <= self.seed < 2 ** 31):
            raise ValueError(
                f"seed must be an int in [0, 2**31), got {self.seed!r}")

    @property
    def branches(self) -> int:
        """Parallel completions actually decoded (batch rows + KV
        tails this request occupies)."""
        return self.best_of or self.n

    @property
    def sampled(self) -> bool:
        """True when this spec needs the sampled jit path. Temperature
        0 with a single branch is greedy regardless of top_k/top_p
        (the argmax token survives any top-k/top-p mask), so those
        specs keep the byte-identity fast path."""
        return not (self.temperature == 0.0 and self.branches == 1)

    def to_wire(self) -> dict:
        """Non-default fields only — the process-fleet dispatch record
        keeps its key-absent discipline (a default spec adds no key at
        all, so the wire bytes are unchanged)."""
        default = DecodeSpec()
        return {f: getattr(self, f) for f in _WIRE_FIELDS
                if getattr(self, f) != getattr(default, f)}

    @classmethod
    def from_wire(cls, d: dict) -> "DecodeSpec":
        unknown = set(d) - set(_WIRE_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown DecodeSpec wire keys {sorted(unknown)!r} — "
                f"known: {list(_WIRE_FIELDS)}")
        return cls(**d)


class TokenStream:
    """Client-side iterator over one request's incremental token
    chunks. The engine's ``_emit_chunk`` funnel is the only producer
    (:func:`_feed`); :meth:`close` is idempotent and fires on every
    terminal transition, so a rejected or failed request yields an
    empty (but terminated) stream instead of a hang. One-shot:
    iterate once."""

    def __init__(self, request_id: str = "") -> None:
        self.request_id = request_id
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._closed = False
        self.chunks = 0  # chunks fed (engine-side accounting mirror)

    def _feed(self, chunk) -> None:
        """Engine-only: push one token chunk (the ``_emit_chunk``
        choke point is this method's single caller, lint-pinned)."""
        self._q.put(np.asarray(chunk, np.int32))
        self.chunks += 1

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._q.put(None)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            yield item

    def tokens(self) -> np.ndarray:
        """Drain the stream (blocking until close) and return all
        tokens concatenated — the non-incremental view."""
        chunks = list(self)
        if not chunks:
            return np.zeros((0,), np.int32)
        return np.concatenate(chunks)


# -- jit-traceable sampling math (consumed inside the engine's jits) ---


def row_keys(seeds, branches, steps):
    """Per-row PRNG keys, derived entirely on device:
    ``fold_in(fold_in(key(seed), branch), step)``. A pure function of
    the three ints — the determinism contract's whole foundation."""
    def one(seed, branch, step):
        k = jax.random.PRNGKey(seed)
        k = jax.random.fold_in(k, branch)
        return jax.random.fold_in(k, step)
    return jax.vmap(one)(seeds, branches, steps)


def _mask_one(logits, top_k, top_p):
    """One row's top-k then top-p mask with TRACED k/p (zero disables
    each). Sort-based: ``lax.top_k`` needs a static k, which would
    recompile per distinct request — a sorted copy gives the k-th
    value and the nucleus cutoff with traced parameters. Composition
    order matches :func:`inference.generate._sample`: the nucleus is
    computed over the already top-k-masked distribution."""
    v = logits.shape[-1]
    desc = jnp.sort(logits)[::-1]
    kth = desc[jnp.clip(top_k, 1, v) - 1]
    keep_k = (top_k <= 0) | (logits >= kth)
    logits = jnp.where(keep_k, logits, -jnp.inf)
    desc = jnp.where((top_k <= 0) | (desc >= kth), desc, -jnp.inf)
    probs = jax.nn.softmax(desc)
    cum = jnp.cumsum(probs)
    nucleus = cum - probs < top_p  # first sorted token always kept
    cutoff = jnp.min(jnp.where(nucleus, desc, jnp.inf))
    keep_p = (top_p <= 0.0) | (logits >= cutoff)
    return jnp.where(keep_p, logits, -jnp.inf)


def sample_rows(logits, temps, top_ks, top_ps, keys):
    """(B,) sampled tokens from (B, V) logits with per-row traced
    temperature/top_k/top_p and per-row keys. A temperature-0 row
    takes the greedy ``where`` branch — exactly the argmax, whatever
    its mask parameters say (mixed greedy+sampled batches decode each
    row correctly)."""
    def one(l, t, k, p, key):
        greedy = jnp.argmax(l)
        masked = _mask_one(l, k, p)
        scaled = masked / jnp.maximum(t, 1e-6)
        drawn = jax.random.categorical(key, scaled)
        return jnp.where(t == 0.0, greedy, drawn)
    return jax.vmap(one)(logits, temps, top_ks, top_ps, keys)


def token_logprobs(logits, toks):
    """(B,) log-probabilities of the chosen tokens under the *model*
    distribution (raw logits, before masking/scaling) — the n-best
    ranking scale, meaningful across greedy and sampled branches."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, toks[:, None], axis=1)[:, 0]
