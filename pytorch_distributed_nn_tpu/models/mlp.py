"""2-layer MLP — BASELINE.json config 1's model ("2-layer MLP on MNIST"),
the reference's minimal `Net(nn.Module)` (SURVEY.md §2a single-process
baseline row), built as a flax module.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy


class MLP(nn.Module):
    features: Sequence[int] = (128, 10)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, feat in enumerate(self.features):
            x = nn.Dense(feat, dtype=self.dtype,
                         param_dtype=self.param_dtype)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


@register("mlp")
def build_mlp(cfg: ModelConfig) -> MLP:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    features = tuple(cfg.extra.get("features", (128, 10)))
    return MLP(features=features, dtype=policy.compute_dtype,
               param_dtype=policy.param_dtype)
