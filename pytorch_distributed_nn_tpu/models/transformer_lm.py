"""Decoder-only Transformer-LM — BASELINE.json config 4's model
("Transformer-LM pipeline-parallel"; SURVEY.md §2a Models row).

GPT-style pre-LN blocks. The block stack is written as a single scanned
module when ``remat`` is on — ``nn.remat_scan`` gives O(1) compile-time in
depth and rematerialised activations (SURVEY.md §7 hard part (e)); the
pipeline strategy instead slices the stack into per-stage segments.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.nn.attention import MultiHeadAttention
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy


class DecoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout: float = 0.0
    ln_eps: float = 1e-5
    attn_impl: str = "auto"
    # FFN override hook: (block, y, train) -> y, creating its submodules in
    # the block's scope. None = the standard dense MLP. This is how the MoE
    # family (models/moe_lm.py) swaps in expert layers without duplicating
    # the block.
    ffn: Optional[Callable] = None
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, decode: bool = False,
                 cache_positions=None):
        d = x.shape[-1]
        y = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln1")(x)
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=d // self.num_heads,
            causal=True, impl=self.attn_impl, dtype=self.dtype,
            param_dtype=self.param_dtype, name="attn",
        )(y, decode=decode, cache_positions=cache_positions)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln2")(x)
        if self.ffn is not None:
            y = self.ffn(self, y, train)
        else:
            y = nn.Dense(self.mlp_dim, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="mlp_in")(y)
            y = nn.gelu(y)
            y = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                         name="mlp_out")(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return x + y


class TransformerLM(nn.Module):
    vocab_size: int = 32000
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 2048
    dropout: float = 0.0
    # HF-conventional (GPT2Config.layer_norm_epsilon): converted
    # checkpoints reproduce the original's logits without an override.
    # COMPAT: the round-1 default was 1e-6 (bert/vit: 1e-12) — a round-1
    # checkpoint restored without extra={'ln_eps': 1e-6} sees slightly
    # different forward math (same caveat class as the resnet padding
    # note in models/resnet.py).
    ln_eps: float = 1e-5
    remat: bool = False
    attn_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # subclasses whose routing is chunk-global (MoE) turn this off
    supports_decode: bool = True

    def block_kwargs(self) -> dict:
        return dict(num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                    dropout=self.dropout, attn_impl=self.attn_impl,
                    ln_eps=self.ln_eps, dtype=self.dtype,
                    param_dtype=self.param_dtype)

    def layer_ffn(self, i: int) -> Optional[Callable]:
        """Per-layer FFN override for block i (see DecoderBlock.ffn).
        The base LM uses the dense MLP everywhere; the MoE subclass
        returns expert layers on its cadence."""
        return None

    @nn.compact
    def __call__(self, tokens, *, train: bool = False,
                 positions: Optional[jnp.ndarray] = None,
                 decode: bool = False, last_only: bool = False,
                 return_hidden: bool = False, cache_positions=None):
        T = tokens.shape[1]
        if T > self.max_len:
            raise ValueError(
                f"sequence length {T} exceeds max_len {self.max_len}"
            )
        x = nn.Embed(self.vocab_size, self.d_model,
                     param_dtype=self.param_dtype, name="tok_embed")(tokens)
        if decode and not self.supports_decode:
            # MoE routing is group-global (capacity and prior-claim
            # counts depend on every token in the chunk), so cached
            # decode would silently break generate()'s token-identity
            # contract — reject like pipeline.py does.
            raise ValueError(
                f"{type(self).__name__} does not support decode caching"
            )
        if decode and positions is not None:
            raise ValueError(
                "decode mode derives positions from the cache counter; "
                "an explicit `positions` argument would be ignored"
            )
        if decode:
            # the learned positional table needs absolute positions, so
            # the model keeps its own running index next to the
            # attention layers' KV cache_index vars. In per-row mode
            # (cache_positions given) each row's position comes from its
            # own cache depth instead, and the shared counter is left
            # untouched — rows at different depths share one batch.
            pos_index = self.variable(
                "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
            )
            if not self.is_initializing():
                if cache_positions is not None:
                    positions = (cache_positions.astype(jnp.int32)[:, None]
                                 + jnp.arange(T)[None])
                else:
                    positions = pos_index.value + jnp.arange(T)[None]
                    pos_index.value = pos_index.value + T
        if positions is None:
            positions = jnp.arange(T)[None]
        pos = nn.Embed(self.max_len, self.d_model,
                       param_dtype=self.param_dtype,
                       name="pos_embed")(positions)
        x = (x + pos).astype(self.dtype)
        block_cls = DecoderBlock
        if self.remat:
            # static_argnums counts (self, x, train, decode) — train must
            # be static or `deterministic=not train` fails on a tracer
            block_cls = nn.remat(DecoderBlock, static_argnums=(2, 3))
        for i in range(self.num_layers):
            x = block_cls(**self.block_kwargs(), ffn=self.layer_ffn(i),
                          name=f"block{i}")(x, train, decode,
                                            cache_positions)
        if last_only:
            x = x[:, -1:]
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_f")(x)
        if return_hidden:
            return x
        return nn.Dense(self.vocab_size, use_bias=False, dtype=jnp.float32,
                        param_dtype=self.param_dtype, name="lm_head")(x)


@register("transformer_lm")
def build_transformer_lm(cfg: ModelConfig) -> TransformerLM:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    e = cfg.extra
    return TransformerLM(
        vocab_size=e.get("vocab_size", 32000),
        num_layers=e.get("num_layers", 12),
        d_model=e.get("d_model", 768),
        num_heads=e.get("num_heads", 12),
        mlp_dim=e.get("mlp_dim", 3072),
        max_len=e.get("max_len", 2048),
        dropout=e.get("dropout", 0.0),
        ln_eps=e.get("ln_eps", 1e-5),
        remat=cfg.remat,
        attn_impl=e.get("attn_impl", "auto"),
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    )
