"""ResNet-50 — BASELINE.json config 2's model ("ResNet-50 / ImageNet,
pure data-parallel DDP allreduce"; SURVEY.md §2a Models row).

NHWC layout (TPU-native: channels-last feeds the MXU's 128-lane minor
dimension), BatchNorm running stats in the ``batch_stats`` collection.
Geometry matches torch exactly (symmetric paddings, not flax 'SAME'),
so torchvision ``resnet50`` checkpoints convert logit-equivalently
(utils/torch_interop.py) — note checkpoints trained before round 2's
padding alignment see shifted stride-2 receptive fields on restore.
Under compiler-sharded DP the batch statistics are computed over the
*global* batch (SyncBN semantics) because the batch axis is sharded, not
vmapped — strictly stronger than torch DDP's local BN.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.nn.batchnorm import TpuBatchNorm
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy


def _make_norm(bn_impl: str, *, train: bool, dtype, param_dtype,
               **kwargs):
    """BatchNorm factory: 'flax' = flax.linen.BatchNorm (the original
    lowering — stats fused into conv epilogues by XLA), anything else
    = TpuBatchNorm with that stats_impl (nn/batchnorm.py: 'fused' |
    'unfused' | 'unfused_fwd' | 'unfused_bwd' | 'pallas'). Semantics
    identical (oracle: tests/test_batchnorm.py); the choice is a
    measured lowering A/B — see docs/design.md "ResNet-50 BN kernel
    A/B"."""
    if bn_impl == "flax":
        return partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=dtype,
                       param_dtype=param_dtype, **kwargs)
    return partial(TpuBatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=dtype,
                   param_dtype=param_dtype, stats_impl=bn_impl, **kwargs)


def space_to_depth(x, block: int = 2):
    """(N, H, W, C) → (N, H/b, W/b, b*b*C), channel order (bh, bw, c)."""
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(f"H/W {h}x{w} not divisible by block {block}")
    x = x.reshape(n, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // block, w // block, block * block * c)


def conv7_to_s2d_kernel(kernel):
    """Exact stem rewrite: the (7, 7, C, F) stride-2/pad-3 kernel as the
    (4, 4, 4C, F) stride-1/pad-(2,1) kernel over the 2x2 space-to-depth
    input. Output pixel o reads original taps at input offsets
    2o-3..2o+3; in block space that is blocks o-2..o+1 whose elements
    sit at offsets 2o-4..2o+3 — so pad the kernel LEFT with one zero
    tap (offset -4) to 8x8, then space-to-depth the tap grid exactly
    like the input. Same taps, same products, regrouped — logits match
    the 7x7 stem to float-associativity (tests/test_models.py).
    """
    k = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))  # 8x8xCxF
    kh, kw, c, f = k.shape
    k = k.reshape(kh // 2, 2, kw // 2, 2, c, f)
    # match the input's (bh, bw, c) channel interleave
    return k.transpose(0, 2, 1, 3, 4, 5).reshape(kh // 2, kw // 2,
                                                 4 * c, f)


def s2d_kernel_to_conv7(kernel):
    """Inverse of :func:`conv7_to_s2d_kernel`: (4, 4, 4C, F) → the
    original (7, 7, C, F) — exporting an s2d-stem checkpoint back to
    torchvision layout (utils/torch_interop.py)."""
    kh, kw, c4, f = kernel.shape
    c = c4 // 4
    k = kernel.reshape(kh, kw, 2, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    k = k.reshape(kh * 2, kw * 2, c, f)
    return k[1:, 1:]  # strip the zero pad tap (offset -4)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    bn_impl: str = "flax"

    @nn.compact
    def __call__(self, x, *, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=self.param_dtype)
        norm = _make_norm(self.bn_impl, train=train, dtype=self.dtype,
                          param_dtype=self.param_dtype)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        # explicit symmetric padding = torch Conv2d(padding=1) geometry
        # (flax 'SAME' pads asymmetrically at stride 2) — keeps
        # converted torchvision weights logit-equivalent
        y = conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                 padding=[(1, 1)] * 2, name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        # zero-init final BN scale: residual branch starts as identity
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides,) * 2,
                            name="conv_proj")(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    num_classes: int = 1000
    # "conv7": the torch-geometry 7x7/stride-2 stem (torchvision
    # checkpoint interop). "s2d": the MLPerf-TPU space-to-depth stem —
    # 2x2 s2d then a 4x4/stride-1 conv, mathematically the SAME map
    # (conv7_to_s2d_kernel converts checkpoints exactly) but with 12
    # input channels instead of 3, so XLA's im2col feeds the MXU dense
    # columns instead of 3-channel-starved ones.
    stem: str = "conv7"
    bn_impl: str = "flax"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        if self.stem == "s2d":
            x = space_to_depth(x, 2)
            x = nn.Conv(self.width, (4, 4), strides=(1, 1),
                        padding=[(2, 1)] * 2, use_bias=False,
                        dtype=self.dtype, param_dtype=self.param_dtype,
                        name="conv_init_s2d")(x)
        elif self.stem == "conv7":
            x = nn.Conv(self.width, (7, 7), strides=(2, 2),
                        padding=[(3, 3)] * 2, use_bias=False,
                        dtype=self.dtype, param_dtype=self.param_dtype,
                        name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = _make_norm(self.bn_impl, train=train, dtype=self.dtype,
                       param_dtype=self.param_dtype)(name="bn_init")(x)
        x = nn.relu(x)
        # torch MaxPool2d(3, 2, padding=1) geometry (see BottleneckBlock)
        x = nn.max_pool(x, (3, 3), strides=(2, 2),
                        padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    self.width * 2 ** stage, strides=strides,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    bn_impl=self.bn_impl,
                    name=f"stage{stage}_block{block}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=self.param_dtype, name="head")(x)


@register("resnet50")
def build_resnet50(cfg: ModelConfig) -> ResNet:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    return ResNet(
        stage_sizes=tuple(cfg.extra.get("stage_sizes", (3, 4, 6, 3))),
        width=cfg.extra.get("width", 64),
        num_classes=cfg.extra.get("num_classes", 1000),
        stem=cfg.extra.get("stem", "conv7"),
        bn_impl=cfg.extra.get("bn_impl", "flax"),
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    )
