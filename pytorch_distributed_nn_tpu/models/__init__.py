"""Model zoo — every family named by BASELINE.json configs 1-5 plus the
reference's classic small nets (SURVEY.md §2a Models row), as flax.linen
modules with bf16 compute and optional remat."""

from __future__ import annotations

from typing import Any, Callable

from pytorch_distributed_nn_tpu.config import ModelConfig

_REGISTRY: dict[str, Callable[..., Any]] = {}


def register(name: str):
    def wrap(builder):
        _REGISTRY[name] = builder
        return builder

    return wrap


def get_model(cfg: ModelConfig):
    """Build the flax module for a ModelConfig. Builders accept the config
    and return a linen Module."""
    # import for registration side effects
    from pytorch_distributed_nn_tpu.models import (  # noqa: F401
        bert,
        lenet,
        llama,
        mlp,
        moe_lm,
        resnet,
        transformer_lm,
        vit,
    )

    if cfg.name not in _REGISTRY:
        raise KeyError(
            f"unknown model {cfg.name!r}; have {sorted(_REGISTRY)}"
        )
    if cfg.remat_offload and cfg.name != "llama3_8b":
        # only the llama builder consumes the flag; silently dropping
        # it would let a run expected to fit via host offload OOM
        # instead (the same failure mode llama.py guards against for
        # offload-without-remat)
        raise ValueError(
            f"remat_offload is implemented for llama3_8b only; model "
            f"{cfg.name!r} would silently ignore it"
        )
    return _REGISTRY[cfg.name](cfg)


def available_models() -> list[str]:
    from pytorch_distributed_nn_tpu.models import (  # noqa: F401
        bert,
        lenet,
        llama,
        mlp,
        moe_lm,
        resnet,
        transformer_lm,
        vit,
    )

    return sorted(_REGISTRY)
