"""Vision Transformer — beyond the reference's model list (SURVEY.md §2a
names MLP/LeNet/ResNet for vision), included for zoo breadth: the
transformer stack a reference user would reach for next, built from the
same attention module as the LM families so TP sharding rules and flash
attention apply unchanged.

Pre-LN encoder (ViT-style), learned positional embeddings, CLS token,
patchify via a non-overlapping Conv — all MXU-friendly shapes.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.nn.attention import MultiHeadAttention
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout: float = 0.0
    attn_impl: str = "xla"
    # HF-conventional (ViTConfig.layer_norm_eps): converted checkpoints
    # reproduce the original's logits without an override
    ln_eps: float = 1e-12
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        d = x.shape[-1]
        y = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln1")(x)
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=d // self.num_heads,
            causal=False, impl=self.attn_impl, dtype=self.dtype,
            param_dtype=self.param_dtype, name="attn",
        )(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln2")(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="mlp_out")(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return x + y


class ViT(nn.Module):
    num_classes: int = 10
    patch_size: int = 4
    num_layers: int = 6
    d_model: int = 192
    num_heads: int = 3
    mlp_dim: int = 768
    dropout: float = 0.0
    # 'xla' default: ViT patch counts are short sequences (e.g. 65 at
    # 32px/4) where the einsum path wins; 'auto'/'flash' available for
    # high-resolution patch grids
    attn_impl: str = "xla"
    ln_eps: float = 1e-12  # see EncoderBlock
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if x.ndim == 3:  # grayscale (B, H, W) → NHWC
            x = x[..., None]
        H, W = x.shape[1], x.shape[2]
        p = self.patch_size
        if H % p or W % p:
            raise ValueError(
                f"image {H}x{W} not divisible by patch_size {p}"
            )
        x = nn.Conv(self.d_model, (p, p), strides=(p, p),
                    padding="VALID", dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    name="patch_embed")(x.astype(self.dtype))
        B = x.shape[0]
        x = x.reshape(B, -1, self.d_model)  # (B, N, D)
        cls = self.param("cls", nn.initializers.zeros,
                         (1, 1, self.d_model), self.param_dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, self.d_model)).astype(
                self.dtype), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.d_model),
                         self.param_dtype)
        x = x + pos.astype(self.dtype)
        for i in range(self.num_layers):
            x = EncoderBlock(
                num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                dropout=self.dropout, attn_impl=self.attn_impl,
                ln_eps=self.ln_eps, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"layer{i}",
            )(x, train=train)
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_f")(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=self.param_dtype, name="head")(
            x[:, 0])  # CLS token


@register("vit")
def build_vit(cfg: ModelConfig) -> ViT:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    e = cfg.extra
    return ViT(
        num_classes=e.get("num_classes", 10),
        patch_size=e.get("patch_size", 4),
        num_layers=e.get("num_layers", 6),
        d_model=e.get("d_model", 192),
        num_heads=e.get("num_heads", 3),
        mlp_dim=e.get("mlp_dim", 768),
        ln_eps=e.get("ln_eps", 1e-12),
        dropout=e.get("dropout", 0.0),
        attn_impl=e.get("attn_impl", "xla"),
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    )
