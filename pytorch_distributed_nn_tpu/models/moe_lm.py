"""Mixture-of-experts decoder LM — the model behind the EP strategy row
(SURVEY.md §2c "EP (expert/MoE)").

The reference has no MoE model; this extends the Transformer-LM family
(models/transformer_lm.py) through its per-layer FFN hook: dense FFNs are
swapped for :class:`~pytorch_distributed_nn_tpu.parallel.expert.MoEMLP`
on a configurable cadence (``moe_every``, Mixtral-style = every layer,
GShard-style = every other). Attention, norms, and embeddings are
inherited unchanged, so TP/fsdp layout rules apply to them verbatim while
the expert weights pick up the ``expert`` axis.
"""

from __future__ import annotations

from typing import Callable, Optional

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.models.transformer_lm import TransformerLM
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy
from pytorch_distributed_nn_tpu.parallel.expert import MoEMLP


class MoETransformerLM(TransformerLM):
    num_experts: int = 8
    k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    group_size: int = 1024  # routing group (see MoEMLP)
    moe_every: int = 1  # 1 = every layer (Mixtral), 2 = every other (GShard)
    # routing is chunk-global (capacity + prior-claim counts span the
    # group), so cached decode would diverge from full-context recompute
    supports_decode: bool = False

    def layer_ffn(self, i: int) -> Optional[Callable]:
        if i % self.moe_every != self.moe_every - 1:
            return None

        def moe_ffn(block, y, train):
            return MoEMLP(
                num_experts=self.num_experts, mlp_dim=block.mlp_dim,
                k=self.k, capacity_factor=self.capacity_factor,
                aux_loss_weight=self.aux_loss_weight,
                group_size=self.group_size, dtype=block.dtype,
                param_dtype=block.param_dtype, name="moe",
            )(y, train=train)

        return moe_ffn


@register("moe_lm")
def build_moe_lm(cfg: ModelConfig) -> MoETransformerLM:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    e = cfg.extra
    return MoETransformerLM(
        vocab_size=e.get("vocab_size", 32000),
        num_layers=e.get("num_layers", 12),
        d_model=e.get("d_model", 768),
        num_heads=e.get("num_heads", 12),
        mlp_dim=e.get("mlp_dim", 3072),
        num_experts=e.get("num_experts", 8),
        k=e.get("k", 2),
        capacity_factor=e.get("capacity_factor", 1.25),
        aux_loss_weight=e.get("aux_loss_weight", 0.01),
        group_size=e.get("group_size", 1024),
        moe_every=e.get("moe_every", 1),
        max_len=e.get("max_len", 2048),
        dropout=e.get("dropout", 0.0),
        remat=cfg.remat,
        attn_impl=e.get("attn_impl", "auto"),
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    )
