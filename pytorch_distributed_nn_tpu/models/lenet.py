"""LeNet-style CNN for MNIST/CIFAR — the reference's classic small conv
net (SURVEY.md §2a Models row, [R] "LeNet-ish CNN").
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy


class LeNet(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        # NHWC; grayscale inputs arrive as (B, 28, 28) → add channel dim
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype,
                    param_dtype=self.param_dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype,
                    param_dtype=self.param_dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype,
                             param_dtype=self.param_dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype,
                             param_dtype=self.param_dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        param_dtype=self.param_dtype)(x)


@register("lenet")
def build_lenet(cfg: ModelConfig) -> LeNet:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    return LeNet(num_classes=cfg.extra.get("num_classes", 10),
                 dtype=policy.compute_dtype,
                 param_dtype=policy.param_dtype)
