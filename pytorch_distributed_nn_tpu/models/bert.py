"""Implemented in a later milestone (model zoo build-out)."""
