"""BERT-base — BASELINE.json config 3's model ("BERT-base pretraining,
large fused gradient buckets"; SURVEY.md §2a Models row).

Bidirectional encoder + masked-LM head. Pretraining uses the
``mlm_synthetic`` dataset (inputs with masked positions, labels -1 on
unmasked positions) with :func:`train.losses.masked_lm_xent`.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.nn.attention import MultiHeadAttention
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout: float = 0.0
    ln_eps: float = 1e-12
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = False):
        # post-LN (original BERT): sublayer → add → LN
        d = x.shape[-1]
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=d // self.num_heads,
            causal=False, dtype=self.dtype, param_dtype=self.param_dtype,
            name="attn",
        )(x, mask=mask)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln1")(x + y)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlp_in")(x)
        y = nn.gelu(y)
        y = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="mlp_out")(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                            param_dtype=self.param_dtype,
                            name="ln2")(x + y)


class Bert(nn.Module):
    vocab_size: int = 30522
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    dropout: float = 0.0
    # HF-conventional (BertConfig.layer_norm_eps): converted checkpoints
    # reproduce the original's logits without remembering an override
    ln_eps: float = 1e-12
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, *, train: bool = False,
                 attention_mask: Optional[jnp.ndarray] = None,
                 token_types: Optional[jnp.ndarray] = None):
        T = tokens.shape[1]
        if T > self.max_len:
            raise ValueError(
                f"sequence length {T} exceeds max_len {self.max_len}"
            )
        x = nn.Embed(self.vocab_size, self.d_model,
                     param_dtype=self.param_dtype, name="tok_embed")(tokens)
        pos = nn.Embed(self.max_len, self.d_model,
                       param_dtype=self.param_dtype,
                       name="pos_embed")(jnp.arange(T)[None])
        x = x + pos
        if token_types is not None:
            x = x + nn.Embed(self.type_vocab, self.d_model,
                             param_dtype=self.param_dtype,
                             name="type_embed")(token_types)
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_embed")(x.astype(self.dtype))
        for i in range(self.num_layers):
            x = EncoderBlock(
                num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                dropout=self.dropout, ln_eps=self.ln_eps, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"layer{i}",
            )(x, mask=attention_mask, train=train)
        # MLM head: dense + gelu + LN, then decode to vocab
        x = nn.Dense(self.d_model, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlm_dense")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="mlm_ln")(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        param_dtype=self.param_dtype, name="mlm_decoder")(x)


@register("bert_base")
def build_bert_base(cfg: ModelConfig) -> Bert:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    e = cfg.extra
    return Bert(
        vocab_size=e.get("vocab_size", 30522),
        num_layers=e.get("num_layers", 12),
        d_model=e.get("d_model", 768),
        num_heads=e.get("num_heads", 12),
        mlp_dim=e.get("mlp_dim", 3072),
        max_len=e.get("max_len", 512),
        dropout=e.get("dropout", 0.0),
        ln_eps=e.get("ln_eps", 1e-12),
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    )
