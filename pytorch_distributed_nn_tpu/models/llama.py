"""Llama-3-style decoder — BASELINE.json config 5's model ("Llama-3-8B
sharded data-parallel"; SURVEY.md §2a Models row).

RMSNorm, rotary embeddings (theta 500k), SwiGLU MLP, grouped-query
attention (32 q heads / 8 kv heads at 8B scale), no biases, untied LM
head — the architecture, not the weights (zero-egress container). The
``llama3_8b`` builder defaults to the real 8B dims; tests shrink via
``ModelConfig.extra``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name

from pytorch_distributed_nn_tpu.config import ModelConfig
from pytorch_distributed_nn_tpu.models import register
from pytorch_distributed_nn_tpu.nn.attention import MultiHeadAttention
from pytorch_distributed_nn_tpu.nn.dtypes import get_policy
from pytorch_distributed_nn_tpu.nn.quantized import Int8Dense, Int8Embed


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           self.param_dtype)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (normed * scale).astype(self.dtype)


class LlamaBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    mlp_dim: int
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    attn_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    quantized: bool = False
    cache_dtype: str = "compute"
    fused_proj: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False, decode: bool = False,
                 cache_positions=None, lora=None):
        # inert tag unless the enclosing remat uses a name-aware policy
        # (remat_offload): then this marks the block boundary as
        # offloadable to pinned host memory instead of living in HBM
        # for the whole backward (the MaxText long-context pattern)
        x = checkpoint_name(x, "block_in")
        d = x.shape[-1]
        y = RMSNorm(eps=self.norm_eps, dtype=self.dtype,
                    param_dtype=self.param_dtype, name="attn_norm")(x)
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=d // self.num_heads,
            num_kv_heads=self.num_kv_heads, causal=True, rotary=True,
            rope_theta=self.rope_theta, impl=self.attn_impl,
            use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, quantized=self.quantized,
            cache_dtype=self.cache_dtype,
            fused_qkv=self.quantized and self.fused_proj,
            name="attn",
        )(y, decode=decode, cache_positions=cache_positions, lora=lora)
        x = x + y
        y = RMSNorm(eps=self.norm_eps, dtype=self.dtype,
                    param_dtype=self.param_dtype, name="mlp_norm")(x)
        if self.quantized:
            dense = lambda f, name: Int8Dense(  # noqa: E731
                f, dtype=self.dtype, name=name)
        else:
            dense = lambda f, name: nn.Dense(  # noqa: E731
                f, use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype, name=name)
        if self.quantized and self.fused_proj:
            # one int8 matmul for gate|up (exact: per-out-channel
            # scales are concat-invariant) — decode is per-op-launch
            # bound, see MultiHeadAttention.fused_qkv
            gate_up = dense(2 * self.mlp_dim, "gate_up")(y)
            gate = gate_up[..., :self.mlp_dim]
            up = gate_up[..., self.mlp_dim:]
        else:
            gate = dense(self.mlp_dim, "gate_proj")(y)
            up = dense(self.mlp_dim, "up_proj")(y)
        y = dense(d, "down_proj")(nn.silu(gate) * up)
        return x + y


class Llama(nn.Module):
    vocab_size: int = 128256
    num_layers: int = 32
    d_model: int = 4096
    num_heads: int = 32
    num_kv_heads: int = 8
    mlp_dim: int = 14336
    rope_theta: float = 500000.0
    # rms_norm_eps: 1e-5 for Llama-3 (HF default is 1e-6 — set
    # extra["norm_eps"] to the checkpoint's value when converting)
    norm_eps: float = 1e-5
    remat: bool = False
    remat_offload: bool = False
    attn_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # weight-only int8 (nn/quantized.py): every kernel stored int8 with
    # per-out-channel scales, dequantized in VMEM by the Pallas matmul.
    # ~8 GB for the true 8B params — the mode that fits the flagship on
    # one 16 GB v5e chip (inference path; training stays float)
    quantized: bool = False
    # decode KV-cache storage ("compute" | "int8"): int8 halves cache
    # HBM via per-(token, head) scales (nn/attention.py), roughly
    # doubling the servable decode batch on one chip
    cache_dtype: str = "compute"
    # quantized path: fused qkv / gate|up projection kernels (fewer,
    # larger int8 matmuls — decode latency is per-op-launch bound;
    # +8% at b=1, docs/design.md "Int8 decode"). Default OFF: the
    # unfused tree is the persisted int8 checkpoint layout contract
    # (ops/pallas/int8_matmul.py storage note), and flipping it
    # silently would break restores of existing quantized trees.
    # bench's decode path and new conversions opt in via
    # model.extra["fused_proj"] = True.
    fused_proj: bool = False

    @nn.compact
    def __call__(self, tokens, *, train: bool = False,
                 decode: bool = False, last_only: bool = False,
                 return_hidden: bool = False, cache_positions=None,
                 lora_bank=None, adapter_ids=None):
        """``last_only`` returns logits for the final position only
        (B, 1, V) — decode prefill needs just the next-token row, and
        at real vocab sizes the (P-1) unused head projections dominate
        prefill cost. ``return_hidden`` skips the lm_head and returns
        the final-norm'd (B, T, D) trunk output — the chunked-xent path
        (train/losses.py) applies the head blockwise so full logits
        never materialize. ``cache_positions`` (B,) int32: per-row KV
        cache indices for continuous batching — see
        nn.attention.MultiHeadAttention.

        ``lora_bank`` + ``adapter_ids``: per-request LoRA (nn/lora.py).
        The bank is the stacked ``(n, L, ...)`` factor dict; each batch
        row selects its adapter via ``adapter_ids`` (B,) int32 — one
        gather per factor per layer, so rows on different fine-tunes
        share one batched forward (the multi-tenant serving path)."""
        if self.quantized:
            x = Int8Embed(self.vocab_size, self.d_model,
                          dtype=self.dtype, name="tok_embed")(tokens)
        else:
            x = nn.Embed(self.vocab_size, self.d_model,
                         param_dtype=self.param_dtype,
                         name="tok_embed")(tokens).astype(self.dtype)
        if self.remat_offload and not self.remat:
            raise ValueError(
                "remat_offload moves remat-saved block boundaries to "
                "host RAM — it needs model.remat=True (without remat "
                "there are no saved boundaries to offload, and "
                "silently ignoring the flag would let a run expected "
                "to fit via offload OOM instead)"
            )
        if self.remat:
            # remat_offload moves the saved block-boundary activations
            # (the "block_in" tags) to pinned host RAM: HBM then holds
            # only the layer being recomputed, which is what makes
            # 128k-token single-chip training fit (device<->host DMA
            # overlaps with the backward's compute)
            policy = None
            if self.remat_offload:
                policy = jax.checkpoint_policies.\
                    save_and_offload_only_these_names(
                        names_which_can_be_saved=[],
                        names_which_can_be_offloaded=["block_in"],
                        offload_src="device", offload_dst="pinned_host",
                    )
            block_cls = nn.remat(LlamaBlock, static_argnums=(2, 3),
                                 policy=policy)
        else:
            block_cls = LlamaBlock
        if lora_bank is not None:
            from pytorch_distributed_nn_tpu.nn.lora import layer_slice
            ids = adapter_ids
            if ids is None:
                ids = jnp.zeros((tokens.shape[0],), jnp.int32)
        for i in range(self.num_layers):
            if lora_bank is None:
                lora = None
            else:
                # gather each row's adapter factors for this layer —
                # lora stays a traced positional so the remat wrapper
                # (static_argnums covers train/decode only) is happy
                lora = tuple(f[ids] for f in layer_slice(lora_bank, i))
            x = block_cls(
                num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
                mlp_dim=self.mlp_dim, rope_theta=self.rope_theta,
                norm_eps=self.norm_eps,
                attn_impl=self.attn_impl, dtype=self.dtype,
                param_dtype=self.param_dtype, quantized=self.quantized,
                cache_dtype=self.cache_dtype,
                fused_proj=self.fused_proj,
                name=f"layer{i}",
            )(x, train, decode, cache_positions, lora)
        if last_only:
            x = x[:, -1:]
        x = RMSNorm(eps=self.norm_eps, dtype=self.dtype,
                    param_dtype=self.param_dtype, name="final_norm")(x)
        if return_hidden:
            return x
        if self.quantized:
            return Int8Dense(self.vocab_size, dtype=jnp.float32,
                             name="lm_head")(x)
        return nn.Dense(self.vocab_size, use_bias=False, dtype=jnp.float32,
                        param_dtype=self.param_dtype, name="lm_head")(x)


@register("llama3_8b")
def build_llama3_8b(cfg: ModelConfig) -> Llama:
    policy = get_policy(cfg.dtype, cfg.compute_dtype)
    e = cfg.extra
    return Llama(
        vocab_size=e.get("vocab_size", 128256),
        num_layers=e.get("num_layers", 32),
        d_model=e.get("d_model", 4096),
        num_heads=e.get("num_heads", 32),
        num_kv_heads=e.get("num_kv_heads", 8),
        mlp_dim=e.get("mlp_dim", 14336),
        rope_theta=e.get("rope_theta", 500000.0),
        norm_eps=e.get("norm_eps", 1e-5),
        remat=cfg.remat,
        remat_offload=cfg.remat_offload,
        attn_impl=e.get("attn_impl", "auto"),
        quantized=e.get("quantized", False),
        cache_dtype=e.get("cache_dtype", "compute"),
        fused_proj=e.get("fused_proj", False),
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
    )
