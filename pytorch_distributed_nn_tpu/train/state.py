"""TrainState: the pytree the whole framework threads through steps.

Replaces the reference's implicit (model, optimizer) object pair —
everything a step touches (params, mutable model state like BatchNorm
stats, optimizer state, step counter) lives in one immutable pytree so it
can be sharded, donated, and checkpointed uniformly.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    model_state: Any  # e.g. BatchNorm running stats ({} if none)
    opt_state: Any
    rng: jax.Array  # base key for per-step stochastic ops (dropout)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    apply_fn: Callable = struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )

    @classmethod
    def create(cls, *, apply_fn, params, tx, model_state=None,
               rng=None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            model_state={} if model_state is None else model_state,
            opt_state=tx.init(params),
            rng=jax.random.key(0) if rng is None else rng,
            tx=tx,
            apply_fn=apply_fn,
        )


def param_count(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def param_bytes(params) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
