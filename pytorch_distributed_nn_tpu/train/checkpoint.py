"""Sharded checkpoint / resume (Orbax).

The reference's checkpointing is at most ``torch.save``/``torch.load`` of
a state dict on rank 0 (SURVEY.md §5 "Checkpoint / resume" row). The
TPU-native design is strictly stronger:

- **sharded**: every host writes only the array shards it owns (Orbax
  OCDBT); no rank-0 gather, no single-file bottleneck — a Llama-8B
  checkpoint never materialises on one host;
- **async**: the save runs on a background thread against a snapshot of
  device buffers, so the train loop keeps stepping (the analogue of
  DDP's "checkpoint off the critical path" practice);
- **topology-flexible resume**: restore takes the *target* TrainState
  (with its shardings) as the template, so a checkpoint written on one
  mesh restores onto another — Orbax reshards on read. This covers the
  elastic-restart story (SURVEY.md §5 "Failure detection" row): restart
  on fewer/more chips and resume from the last step.

Layout: ``<dir>/<step>/`` per step, plus Orbax metadata. The data-stream
position is restored from the saved ``data_step`` so no batch is replayed
or skipped on resume (the dataset is deterministic by (seed, step) —
datasets.py determinism contract).
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from pytorch_distributed_nn_tpu import obs
from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.runtime import chaos
from pytorch_distributed_nn_tpu.train.state import TrainState

log = logging.getLogger(__name__)

_ARRAYS = "arrays"  # TrainState array leaves
_META = "meta"  # small host-side json (data_step, preset, ...)


class CheckpointManager:
    """Thin policy wrapper over ``ocp.CheckpointManager``.

    ``save`` is async by default; ``close`` drains the writer. The
    manager keeps ``max_to_keep`` newest steps.
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3,
                 async_save: bool = True) -> None:
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    # -- save ------------------------------------------------------------

    def save(self, state: TrainState, *, data_step: int,
             extra_meta: dict[str, Any] | None = None,
             force: bool = False) -> bool:
        """Queue an async save of ``state`` at its current step."""
        step = int(jax.device_get(state.step))
        meta = {"data_step": int(data_step), "step": step}
        if extra_meta:
            meta.update(extra_meta)
        # span covers only the host-side queueing (async save): the
        # background write shows up in `wait`/`close` spans instead
        ev = flight.record("checkpoint", "save", step=step,
                           note="queue", complete=False)
        with obs.span("checkpoint/save", step=step):
            saved = self._mgr.save(
                step,
                args=ocp.args.Composite(**{
                    _ARRAYS: ocp.args.StandardSave(_array_tree(state)),
                    _META: ocp.args.JsonSave(meta),
                }),
                force=force,
            )
        flight.complete(ev)
        if saved:
            obs.get_registry().counter(
                "checkpoint_saves_total", "checkpoint saves queued").inc()
            log.info("queued checkpoint save at step %d -> %s", step,
                     self.directory)
            # chaos hook (runtime/chaos.py corrupt_ckpt): tears THIS
            # step's files after the write lands — the torn-latest
            # failure mode restore's fallback path covers
            chaos.on_checkpoint_saved(self, step)
        return saved

    # -- restore ---------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, template: TrainState,
                step: int | None = None) -> tuple[TrainState, dict]:
        """Restore into the layout of ``template`` (its shardings define
        the target placement — resume works across topology changes).
        Returns ``(state, meta)``.

        Integrity fallback: with no explicit ``step``, a torn/corrupt
        latest step (killed mid-write, bit rot, injected chaos) falls
        back to the next-newest kept step instead of raising — losing a
        checkpoint interval beats losing the job. Each skip increments
        ``checkpoint_restore_fallbacks_total`` and lands a flight event.
        An explicitly requested step still raises: the caller asked for
        exactly that state."""
        if step is not None:
            return self._restore_step(template, step)
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        last_err: Exception | None = None
        for i, s in enumerate(steps):
            try:
                return self._restore_step(template, s)
            except Exception as e:  # noqa: BLE001 — orbax raises many
                last_err = e
                obs.get_registry().counter(
                    "checkpoint_restore_fallbacks_total",
                    "restores that skipped a torn/corrupt step").inc()
                flight.record("checkpoint", "restore_fallback", step=s,
                              note=f"{type(e).__name__}")
                log.warning(
                    "checkpoint step %d is torn/corrupt (%s: %s); "
                    "falling back to %s", s, type(e).__name__, e,
                    steps[i + 1] if i + 1 < len(steps) else "nothing",
                )
        raise RuntimeError(
            f"every kept checkpoint step {steps} under {self.directory} "
            f"failed to restore"
        ) from last_err

    def _restore_step(self, template: TrainState,
                      step: int) -> tuple[TrainState, dict]:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            _array_tree(template),
        )
        ev = flight.record("checkpoint", "restore", step=step,
                           complete=False)
        with obs.span("checkpoint/restore", step=step):
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(**{
                    _ARRAYS: ocp.args.StandardRestore(abstract),
                    _META: ocp.args.JsonRestore(),
                }),
            )
        flight.complete(ev)
        obs.get_registry().counter(
            "checkpoint_restores_total", "checkpoint restores").inc()
        state = _merge_array_tree(template, restored[_ARRAYS])
        return state, dict(restored[_META])

    # -- lifecycle -------------------------------------------------------

    def wait(self) -> None:
        with obs.span("checkpoint/wait"):
            self._mgr.wait_until_finished()

    def close(self) -> None:
        with obs.span("checkpoint/drain"):
            self._mgr.wait_until_finished()
            self._mgr.close()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())


def _array_tree(state: TrainState) -> dict:
    """The checkpointable slice of TrainState: array leaves only (tx and
    apply_fn are code, rebuilt from config on restore)."""
    return {
        "step": state.step,
        "params": state.params,
        "model_state": state.model_state,
        "opt_state": state.opt_state,
        "rng": jax.random.key_data(state.rng),
    }


def _merge_array_tree(template: TrainState, tree: dict) -> TrainState:
    rng = tree["rng"]
    if not jax.dtypes.issubdtype(np.asarray(rng).dtype, jax.dtypes.prng_key):
        rng = jax.random.wrap_key_data(np.asarray(jax.device_get(rng)))
    return template.replace(
        step=tree["step"],
        params=tree["params"],
        model_state=tree["model_state"],
        opt_state=tree["opt_state"],
        rng=rng,
    )
