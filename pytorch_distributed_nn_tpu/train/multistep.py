"""Multi-step dispatch: fuse k train steps into ONE device program.

The reference's training loop is one `optimizer.step()` per Python
iteration — fine when each step is milliseconds of GPU work. On TPU the
idiomatic loop hoists the iteration itself onto the device: `lax.scan`
over a leading-axis-stacked batch pool runs k optimizer steps per
dispatch, so host/tunnel round-trip latency amortizes k-fold. For
dispatch-bound workloads this IS the throughput: the r3 bench's
`mlp_mnist` moves from ~300k samples/s (one dispatch per step through
the axon tunnel) to chip-bound rates with `--multistep`.

Semantics: identical math to k sequential `step_fn` calls on the same
batches — the scan threads the TrainState through in order, and the
returned metrics are the last step's (matching what a Python loop
would hold after k iterations). Metrics for ALL k steps come back
stacked under the ``"all"`` key so logging can still see every step.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def make_multistep(step_fn: Callable, k: int) -> Callable:
    """Wrap a ``step(state, x, y) -> (state, metrics)`` into
    ``multistep(state, xs, ys) -> (state, metrics)`` running ``k``
    fused steps. ``xs``/``ys`` carry a leading POOL axis of any length
    P <= k: step i trains on slice ``i % P`` (the same cycling a host
    loop over a batch pool does), so a small device-resident pool need
    not be duplicated k times in HBM — the scan runs over step indices
    and dynamically indexes the pool.

    ``step_fn`` may already be jitted (inner jit inlines into the outer
    trace). The state is donated: k steps in flight never hold two
    copies of the optimizer state.
    """
    if k < 1:
        raise ValueError(f"multistep k must be >= 1, got {k}")

    def multistep(state, xs, ys):
        pool = jax.tree.leaves(xs)[0].shape[0]
        if pool > k:
            raise ValueError(
                f"batch pool ({pool}) larger than step count ({k}): "
                f"{pool - k} batches would silently never train"
            )

        def body(s, i):
            x = jax.tree.map(lambda a: a[i % pool], xs)
            y = jax.tree.map(lambda a: a[i % pool], ys)
            s, m = step_fn(s, x, y)
            return s, m

        state, ms = jax.lax.scan(body, state, jnp.arange(k))
        last = jax.tree.map(lambda a: a[-1], ms)
        last["all"] = ms
        return state, last

    return jax.jit(multistep, donate_argnums=(0,))
