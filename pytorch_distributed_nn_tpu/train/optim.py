"""Optimizer factory over optax.

The reference calls ``torch.optim.SGD``/``Adam`` after its hand-rolled or
DDP-driven gradient averaging (SURVEY.md §3.1-3.2). Here the optimizer is
an optax gradient-transformation chain built from
:class:`~pytorch_distributed_nn_tpu.config.OptimConfig`; under sharded DP
the same chain runs on parameter shards unchanged (optax transforms are
elementwise over the pytree), which is what makes ZeRO-style optimizer
state sharding free (SURVEY.md §2c sharded-DP row).
"""

from __future__ import annotations

import optax

from pytorch_distributed_nn_tpu.config import OptimConfig


def make_schedule(cfg: OptimConfig, total_steps: int) -> optax.Schedule:
    if cfg.schedule == "constant":
        base = optax.constant_schedule(cfg.lr)
    elif cfg.schedule == "cosine":
        base = optax.cosine_decay_schedule(
            cfg.lr, decay_steps=max(total_steps - cfg.warmup_steps, 1)
        )
    elif cfg.schedule == "linear":
        base = optax.linear_schedule(
            cfg.lr, 0.0, max(total_steps - cfg.warmup_steps, 1)
        )
    elif cfg.schedule == "step":
        # torch StepLR / torchvision-recipe decay: multiply by
        # step_gamma at each boundary (fractions of the post-warmup
        # run). Milestones that round to the same integer boundary
        # compound (gamma^k) rather than silently collapsing.
        span = max(total_steps - cfg.warmup_steps, 1)
        boundaries: dict[int, float] = {}
        for frac in cfg.step_milestones:
            b = max(int(span * frac), 1)
            boundaries[b] = boundaries.get(b, 1.0) * cfg.step_gamma
        base = optax.piecewise_constant_schedule(cfg.lr, boundaries)
    else:
        raise ValueError(f"unknown schedule {cfg.schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, cfg.lr, cfg.warmup_steps)
        return optax.join_schedules([warmup, base], [cfg.warmup_steps])
    return base


def _decay_mask(params):
    """True where decay applies: skip 1-D leaves (norm scales, biases,
    per-channel stats) — the standard LLM recipe when
    ``decay_mask_norms`` is on."""
    import jax

    return jax.tree.map(lambda p: p.ndim > 1, params)


def make_optimizer(cfg: OptimConfig,
                   total_steps: int = 10_000) -> optax.GradientTransformation:
    schedule = make_schedule(cfg, total_steps)
    mask = _decay_mask if cfg.decay_mask_norms else None
    mu_dtype = cfg.mu_dtype or None  # bf16 halves first-moment HBM
    if mu_dtype and cfg.name not in ("momentum", "adam", "adamw", "lion"):
        # optax.lamb/sgd/adafactor expose no moment-dtype control —
        # silently ignoring the setting would fake the HBM saving
        raise ValueError(
            f"mu_dtype is not supported for optimizer {cfg.name!r} "
            "(momentum/adam/adamw/lion only)"
        )
    if cfg.name == "sgd":
        opt = optax.sgd(schedule)
    elif cfg.name == "momentum":
        opt = optax.sgd(schedule, momentum=cfg.momentum,
                        accumulator_dtype=mu_dtype)
    elif cfg.name == "adam":
        opt = optax.adam(schedule, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                         mu_dtype=mu_dtype)
    elif cfg.name == "adamw":
        opt = optax.adamw(schedule, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                          weight_decay=cfg.weight_decay, mask=mask,
                          mu_dtype=mu_dtype)
    elif cfg.name == "adafactor":
        # The TPU-native memory-factored optimizer (Shazeer & Stern): 2nd
        # moments stored as row/col factors, O(n+m) not O(nm) state per
        # matrix — what makes billion-param training fit without ZeRO.
        opt = optax.adafactor(schedule,
                              weight_decay_rate=cfg.weight_decay or None,
                              weight_decay_mask=mask)
    elif cfg.name == "lamb":
        opt = optax.lamb(schedule, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                         weight_decay=cfg.weight_decay, mask=mask)
    elif cfg.name == "lion":
        opt = optax.lion(schedule, b1=cfg.b1, b2=cfg.b2,
                         weight_decay=cfg.weight_decay, mask=mask,
                         mu_dtype=mu_dtype)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")

    chain = []
    if cfg.grad_clip_norm > 0:
        chain.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    if cfg.weight_decay > 0 and cfg.name in ("sgd", "momentum", "adam"):
        # L2-into-grad semantics (torch's SGD/Adam weight_decay); adamw
        # applies decoupled decay internally instead.
        chain.append(optax.add_decayed_weights(cfg.weight_decay,
                                               mask=mask))
    chain.append(opt)
    return optax.chain(*chain) if len(chain) > 1 else opt
