"""The Trainer: config → mesh → model → data → strategy → step loop.

This is the counterpart of the reference's per-strategy ``train.py``
drivers collapsed into one driver (SURVEY.md §1 Entrypoints row): the
hot loop is one jit-compiled step; everything else (logging cadence,
checkpointing, metrics host-sync) happens off the critical path.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

import jax
import numpy as np

from pytorch_distributed_nn_tpu import obs
from pytorch_distributed_nn_tpu.config import TrainConfig
from pytorch_distributed_nn_tpu.data import DataLoader, get_dataset
from pytorch_distributed_nn_tpu.models import get_model
from pytorch_distributed_nn_tpu.obs import aggregate as obs_aggregate
from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs import runtime_gauges
from pytorch_distributed_nn_tpu.obs import watchtower
from pytorch_distributed_nn_tpu.obs import xray
from pytorch_distributed_nn_tpu.ops import collectives as cc
from pytorch_distributed_nn_tpu.runtime import chaos
from pytorch_distributed_nn_tpu.runtime import failure
from pytorch_distributed_nn_tpu.parallel import make_train_step
from pytorch_distributed_nn_tpu.runtime.mesh import make_mesh
from pytorch_distributed_nn_tpu.train.losses import get_loss_fn
from pytorch_distributed_nn_tpu.train.optim import make_optimizer
from pytorch_distributed_nn_tpu.train.state import TrainState, param_count

log = logging.getLogger(__name__)


@dataclasses.dataclass
class StepRecord:
    step: int
    loss: float
    seconds: float


@dataclasses.dataclass
class EvalRecord:
    step: int
    loss: float
    accuracy: float


# Eval batches come from the SAME (seed, step)-keyed generator as
# training — same class templates / token process, i.e. the same task —
# but from a step range training can never reach, so the samples are
# held out. (A different *seed* would change the templates themselves:
# a different task, on which no trained model can score.) File-backed
# datasets additionally honor data.holdout_frac for a true row/token
# split — see data/datasets.py.
from pytorch_distributed_nn_tpu.data.datasets import (
    EVAL_STEP_OFFSET as _EVAL_STEP_OFFSET,
)


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None) -> None:
        self.cfg = cfg
        # chaos engine (TPUNN_CHAOS): armed once per process, inert and
        # allocation-free on the step path when the env is unset
        chaos.maybe_init()
        # watchtower (TPUNN_WATCH): online anomaly/SLO detection over
        # the hooks below — same inert-when-unset contract as chaos
        watchtower.maybe_init()
        # xray (TPUNN_XRAY): anomaly-triggered device profiling; pages
        # raised by the tower above start bounded captures
        xray.maybe_init()
        self._preemptible = False
        self.mesh = mesh if mesh is not None else make_mesh(
            cfg.mesh.resolve(len(jax.devices()))
        )
        # sequence parallelism: model-level ring attention builds its
        # nested shard_map against the ambient mesh — scoped per call
        # (a process-global set_mesh would leak into unrelated code)
        self._seq_parallel = self.mesh.shape.get("seq", 1) > 1
        self.dataset = get_dataset(
            cfg.data.dataset,
            seed=cfg.seed,
            batch_size=cfg.data.batch_size,
            seq_len=cfg.data.seq_len,
            vocab_size=cfg.data.vocab_size,
            path=cfg.data.path,
            token_dtype=cfg.data.token_dtype,
            sample=cfg.data.sample,
            holdout_frac=cfg.data.holdout_frac,
            image_size=cfg.data.image_size,
            num_workers=cfg.data.num_workers,
        )
        self.loader = DataLoader(self.dataset, self.mesh,
                                 prefetch=cfg.data.prefetch)
        self.loss_fn = get_loss_fn(
            cfg.data.dataset, label_smoothing=cfg.label_smoothing
        )
        self.model = get_model(cfg.model)
        self.state = self._init_state()
        step_fn, place_fn = make_train_step(cfg, self.mesh, self.loss_fn,
                                            model=self.model)
        if self._seq_parallel:
            step_fn = self._with_mesh(step_fn)
            place_fn = self._with_mesh(place_fn)
        self.step_fn = step_fn
        self.state = place_fn(self.state)
        self.history: list[StepRecord] = []
        self.eval_history: list[EvalRecord] = []
        self.last_metrics = None  # most recent step/dispatch metrics
        self._eval_step = None  # built lazily on first evaluate()
        self._eval_batches: dict[int, tuple] = {}  # device-resident cache
        self.data_step = 0  # next dataset step to consume (resume-aware)
        # unified telemetry (obs/): goodput meter + registry instruments
        # feeding the JSONL stream and the Prometheus exposition
        self.goodput = obs.GoodputMeter()
        _reg = obs.get_registry()
        self._c_steps = _reg.counter(
            "train_steps_total", "optimizer steps completed")
        self._c_samples = _reg.counter(
            "train_samples_total", "training samples consumed")
        self._g_loss = _reg.gauge("train_loss", "last logged train loss")
        self._h_step = _reg.histogram(
            "train_step_seconds", "wall time per step window")
        runtime_gauges.export_mesh_gauges(self.mesh, _reg)
        self.metrics = None
        if cfg.metrics_path:
            from pytorch_distributed_nn_tpu.utils.metrics import (
                MetricsLogger,
            )

            self.metrics = MetricsLogger(cfg.metrics_path)
            # flight dumps land next to the run's JSONL unless the
            # elastic agent's TPUNN_FLIGHT_DIR contract says otherwise
            import pathlib

            flight.set_dump_dir(pathlib.Path(cfg.metrics_path).parent)
            if watchtower.enabled():
                # alerts ride the same JSONL stream as the metrics
                # they fired on (the tower armed before this logger
                # existed)
                watchtower.tower().metrics = self.metrics
        self.ckpt = None
        try:
            if cfg.checkpoint_dir:
                from pytorch_distributed_nn_tpu.train.checkpoint import (
                    CheckpointManager,
                )

                self.ckpt = CheckpointManager(cfg.checkpoint_dir)
                if cfg.resume and self.ckpt.latest_step() is not None:
                    with self.goodput.phase("checkpoint"):
                        self.state, meta = self.ckpt.restore(self.state)
                    self.data_step = meta["data_step"]
                    log.info("resumed from step %d (data_step %d)",
                             meta["step"], self.data_step)
        except Exception:
            # a failed restore must not leak the metrics file handle
            # (MetricsLogger is a context manager; Trainer mirrors it)
            if self.metrics is not None:
                self.metrics.close()
            raise
        # preemption notice handling (SIGTERM → finish step → sync save
        # → GRACEFUL_EXIT_CODE); no-op outside the agent/TPUNN_PREEMPT.
        # Installed last so a failed constructor can't leak the handler.
        self._preemptible = failure.install_preemption_handler()

    # context manager: `with Trainer(cfg) as t:` closes the metrics
    # JSONL handle and drains async checkpoint writes on ANY exit path
    def __enter__(self) -> "Trainer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _with_mesh(self, fn):
        """Run ``fn`` with this trainer's mesh as the ambient mesh (the
        nested shard_map of model-level ring attention resolves against
        it at trace time)."""
        def wrapped(*args, **kwargs):
            with jax.set_mesh(self.mesh):
                return fn(*args, **kwargs)

        return wrapped

    def _init_state(self) -> TrainState:
        cfg = self.cfg
        rng = jax.random.key(cfg.seed)
        x0, _ = self.dataset.batch(0)
        # init on one example — shapes only; keeps init cheap for big nets
        init = self.model.init
        if self._seq_parallel:  # ring attention traces a shard_map
            init = self._with_mesh(init)
        # local_devices: under multi-process jax.devices()[0] is rank
        # 0's device — non-addressable elsewhere (and segfaults CPU
        # backends when used as default_device on other ranks)
        with jax.default_device(jax.local_devices()[0]):
            variables = init(rng, x0[:1], train=False)
        params = variables.pop("params")
        model_state = dict(variables)
        # per-step transients (MoE aux losses / router diagnostics), not
        # state to carry — forward() re-collects them every step
        model_state.pop("losses", None)
        model_state.pop("diagnostics", None)
        tx = make_optimizer(cfg.optim, total_steps=cfg.steps)
        state = TrainState.create(
            apply_fn=self.model.apply, params=params, tx=tx,
            model_state=model_state,
            rng=jax.random.key(cfg.seed + 1),  # dropout stream != init key
        )
        log.info("model %s: %.2fM params", cfg.model.name,
                 param_count(params) / 1e6)
        return state

    def train(self, steps: int | None = None) -> list[StepRecord]:
        cfg = self.cfg
        if steps is None:
            # default = the REMAINING budget: a resumed run finishes at
            # cfg.steps total, it doesn't run cfg.steps more (the LR
            # schedule was built for cfg.steps)
            steps = max(cfg.steps - self.data_step, 0)
        if cfg.multistep_k > 1:
            return self._train_multistep(steps)
        self.loader.start_step = self.data_step  # don't replay batches
        it = iter(self.loader)
        try:
            return self._train_loop(it, steps)
        finally:
            # join the prefetch producer: a daemon thread left blocked
            # mid-queue-put at interpreter exit SIGABRTs (the same race
            # bench.py's loader loop guards against)
            it.close()

    def _train_loop(self, it, steps: int) -> list[StepRecord]:
        cfg = self.cfg
        gp = self.goodput
        t_last = time.perf_counter()
        g_last = self.data_step  # step count behind each logged record
        for i in range(steps):
            gp.step_start()
            with gp.phase("data"):
                x, y = next(it)
            self.data_step += 1
            g = self.data_step  # 1-based global step just dispatched
            # step-boundary marker in the flight ring: trace-time
            # collective records inherit this step, and per-rank step
            # timestamps drive obs_doctor's straggler percentiles
            flight.mark_step(g)
            chaos.on_step(g)  # fault injection point (crash/slow/preempt)
            xray.on_step(g)  # capture window clock / interval trigger
            if i == 0 and gp.wire_bytes_per_step is None:
                # trace-time collective accounting rides the first
                # dispatch (the call that traces step_fn): recorded
                # wire bytes are the goodput breakdown's cross-check
                # for the collective share
                with cc.recording() as comm_records:
                    with gp.phase("compute"):
                        with flight.dispatch("train_step", step=g):
                            self.state, metrics = self.step_fn(
                                self.state, x, y)
                if comm_records:
                    gp.wire_bytes_per_step = cc.wire_bytes(comm_records)
                    # per-op attribution cross-checks collective time
                    # against these analytic wire bytes
                    xray.on_wire_bytes(gp.wire_bytes_per_step)
                if xray.enabled():
                    # analytic per-chip step FLOPs turn the capture's
                    # time shares into achieved FLOP/s + roofline
                    # fractions; the cost model is only worth its
                    # (one-off) HLO pass when a capture could use it
                    try:
                        from pytorch_distributed_nn_tpu.utils.flops \
                            import train_flops_per_sample

                        xray.on_flops(
                            train_flops_per_sample(cfg)
                            * cfg.data.batch_size
                            / max(len(jax.devices()), 1))
                    except Exception as e:  # noqa: BLE001
                        log.debug("xray flops context unavailable: %s",
                                  e)
            else:
                with gp.phase("compute"):
                    with flight.dispatch("train_step", step=g):
                        self.state, metrics = self.step_fn(self.state,
                                                           x, y)
            self.last_metrics = metrics
            self._c_steps.inc()
            self._c_samples.inc(cfg.data.batch_size)
            # Progress watchdog food (launch.py --progress-timeout).
            # Dispatch is async, but a hung device op stalls this loop
            # within a few iterations via dispatch-queue backpressure,
            # so per-iteration notification tracks real device progress.
            failure.notify_progress()
            if (self.ckpt is not None and cfg.checkpoint_every
                    and g % cfg.checkpoint_every == 0):
                with gp.phase("checkpoint"):
                    self.ckpt.save(self.state, data_step=self.data_step)
            if cfg.eval_every and g % cfg.eval_every == 0:
                with gp.phase("eval"):
                    self.evaluate()
            logged = cfg.log_every and ((g - 1) % cfg.log_every == 0
                                        or i == steps - 1)
            if logged:
                # the device_get is the loop's execution fence: device
                # time queued behind async dispatch surfaces here, so
                # it counts as compute, not "other"
                with gp.phase("compute"):
                    loss = float(jax.device_get(metrics["loss"]))
                now = time.perf_counter()
                rec = StepRecord(step=g - 1, loss=loss,
                                 seconds=now - t_last)
                t_last = now
                self.history.append(rec)
                self._g_loss.set(loss)
                watchtower.on_loss(g - 1, loss)
                if self.metrics is not None:
                    covered = g - g_last  # actual steps in this record
                    self.metrics.emit(
                        "train_step", step=rec.step, loss=rec.loss,
                        seconds=round(rec.seconds, 4),
                        samples_per_sec=round(
                            covered * cfg.data.batch_size
                            / max(rec.seconds, 1e-9), 2),
                    )
                g_last = g
                if jax.process_index() == 0:
                    log.info("step %d loss %.4f (%.3fs)", g - 1, loss,
                             rec.seconds)
            bd = gp.step_end(step=g - 1)
            self._h_step.observe(bd.wall_s)
            watchtower.on_train_step(g - 1, bd.wall_s)
            if logged:
                self._flush_telemetry(step=g - 1)
            if failure.preempt_requested():
                self._graceful_preempt(g)
        # sync before returning so wall-clock timings are honest
        jax.block_until_ready(self.state.params)
        # Post-loop work (checkpoint drain, eval) is unbounded: back to
        # liveness-only heartbeats so it can't read as a hang.
        failure.notify_done()
        return self.history

    def _graceful_preempt(self, step: int) -> None:
        """Preemption notice arrived (SIGTERM → runtime.failure flag):
        the in-flight step has completed, so force a SYNCHRONOUS
        checkpoint save and exit with the graceful code the elastic
        agent does not charge against the restart budget. Raises
        ``SystemExit`` — the ``with Trainer(...)`` context and the
        worker script's normal exit path still run."""
        log.warning("preemption notice at step %d: saving final "
                    "checkpoint and exiting gracefully", step)
        flight.record("preempt", "graceful_exit", step=step)
        if self.ckpt is not None:
            with self.goodput.phase("checkpoint"):
                self.ckpt.save(self.state, data_step=self.data_step,
                               force=True)
                self.ckpt.wait()  # synchronous: the process is dying
        obs.get_registry().counter(
            "preempt_exits_total", "graceful preemption exits").inc()
        if self.metrics is not None:
            self.metrics.emit("preempt", step=step - 1,
                              data_step=self.data_step,
                              saved=self.ckpt is not None)
        failure.notify_done()
        flight.dump_now("preempt:graceful_exit", force=True)
        raise SystemExit(failure.GRACEFUL_EXIT_CODE)

    def _flush_telemetry(self, step: int) -> None:
        """Log-cadence telemetry fanout: goodput window -> JSONL,
        heartbeat/runtime gauges refreshed, registry snapshot to the
        Prometheus textfile and (under the agent) the native store."""
        win = self.goodput.window_summary()
        if self.metrics is not None:
            self.metrics.emit("goodput", step=step, **win)
        runtime_gauges.update_heartbeat_gauges()
        reg = obs.get_registry()
        gp_gauge = reg.gauge("goodput_frac",
                             "compute+collective share of wall time")
        gp_gauge.set(win["goodput_frac"])
        watchtower.on_goodput(step, win["goodput_frac"])
        if self.cfg.prom_path:
            reg.write_prometheus(self.cfg.prom_path)
        obs_aggregate.maybe_publish(reg)

    def _get_multistep(self, k: int):
        """Compiled k-fused step, cached per k (the final dispatch of a
        budget not divisible by multistep_k runs a shorter scan)."""
        from pytorch_distributed_nn_tpu.train.multistep import (
            make_multistep,
        )

        if not hasattr(self, "_mstep_cache"):
            self._mstep_cache = {}
        if k not in self._mstep_cache:
            fn = make_multistep(self.step_fn, k)
            self._mstep_cache[k] = (self._with_mesh(fn)
                                    if self._seq_parallel else fn)
        return self._mstep_cache[k]

    def _train_multistep(self, steps: int) -> list[StepRecord]:
        """The device-side training loop: ``multistep_k`` optimizer
        steps per dispatch (train/multistep.py). Math-identical to the
        per-step loop on the same batches; logging stays per-step via
        the scan's stacked metrics, while checkpoint/eval cadences
        round UP to the next dispatch boundary (the scan cannot pause
        mid-flight). ``multistep_pool`` > 0 swaps fresh per-step
        batches for a cycled device-resident pool (benchmark mode —
        repeats data to exclude host transfer from the measurement).
        """
        cfg = self.cfg
        k, pool = cfg.multistep_k, cfg.multistep_pool
        window_sizes = [k] * (steps // k)
        if steps % k:
            window_sizes.append(steps % k)
        if pool:
            if not hasattr(self, "_pool_batches"):
                self._pool_batches = self.loader.stacked_batch_at(
                    self.data_step, min(pool, k))
            xs_pool, ys_pool = self._pool_batches
            batches = None
        else:
            # fresh data: prefetching stacked iterator, so the next
            # window's host generation + transfer overlaps this
            # window's device scan
            batches = self.loader.iter_stacked(
                window_sizes, start_step=self.data_step)
        t_last = time.perf_counter()
        g_last = self.data_step
        remaining = steps
        try:
            return self._multistep_loop(batches, pool, xs_pool if pool
                                        else None,
                                        ys_pool if pool else None, k,
                                        steps, t_last, g_last)
        finally:
            if batches is not None:
                # same prefetch-producer join as train(): an abandoned
                # stacked iterator leaves a daemon thread blocked in
                # q.put -> SIGABRT at interpreter exit
                batches.close()

    def _multistep_loop(self, batches, pool, xs_pool, ys_pool, k,
                        steps, t_last, g_last):
        cfg = self.cfg
        gp = self.goodput
        remaining = steps
        while remaining > 0:
            k_eff = min(k, remaining)
            gp.step_start()
            with gp.phase("data"):
                if pool:
                    xs, ys = xs_pool, ys_pool
                    if jax.tree.leaves(xs)[0].shape[0] > k_eff:
                        xs = jax.tree.map(lambda a: a[:k_eff], xs)
                        ys = jax.tree.map(lambda a: a[:k_eff], ys)
                else:
                    xs, ys = next(batches)
            flight.mark_step(self.data_step + 1, note=f"k={k_eff}")
            chaos.on_step(self.data_step + 1)  # fault injection point
            xray.on_step(self.data_step + 1)  # capture window clock
            with gp.phase("compute"):
                with flight.dispatch("multistep", step=self.data_step + 1,
                                     note=f"k={k_eff}"):
                    self.state, metrics = self._get_multistep(k_eff)(
                        self.state, xs, ys)
            self.data_step += k_eff
            remaining -= k_eff
            g = self.data_step  # 1-based step count after this window
            self.last_metrics = metrics
            self._c_steps.inc(k_eff)
            self._c_samples.inc(k_eff * cfg.data.batch_size)
            failure.notify_progress()
            if (self.ckpt is not None and cfg.checkpoint_every
                    and g // cfg.checkpoint_every
                    > (g - k_eff) // cfg.checkpoint_every):
                with gp.phase("checkpoint"):
                    self.ckpt.save(self.state, data_step=self.data_step)
            if (cfg.eval_every and g // cfg.eval_every
                    > (g - k_eff) // cfg.eval_every):
                with gp.phase("eval"):
                    self.evaluate()
            logged = []
            if cfg.log_every:
                # per-step losses from the scan's stacked metrics: one
                # (k_eff,) fetch covers every logged step in the window
                logged = [s for s in range(g - k_eff + 1, g + 1)
                          if (s - 1) % cfg.log_every == 0
                          or (remaining == 0 and s == g)]
                if logged:
                    with gp.phase("compute"):  # fence: device catches up
                        losses = np.asarray(jax.device_get(
                            metrics["all"]["loss"]), np.float32)
                    now = time.perf_counter()
                    window_dt = now - t_last
                    window_span = max(g - g_last, 1)  # steps since last
                    for s in logged:
                        covered = s - g_last
                        rec = StepRecord(
                            step=s - 1,
                            loss=float(losses[s - (g - k_eff) - 1]),
                            seconds=window_dt * covered / window_span,
                        )
                        self.history.append(rec)
                        if self.metrics is not None:
                            self.metrics.emit(
                                "train_step", step=rec.step,
                                loss=rec.loss,
                                seconds=round(rec.seconds, 4),
                                samples_per_sec=round(
                                    covered * cfg.data.batch_size
                                    / max(rec.seconds, 1e-9), 2),
                            )
                        g_last = s
                        if jax.process_index() == 0:
                            log.info("step %d loss %.4f (%.3fs)",
                                     rec.step, rec.loss, rec.seconds)
                    t_last = now
                    self._g_loss.set(float(losses[-1]))
                    watchtower.on_loss(g - 1, float(losses[-1]))
            bd = gp.step_end(step=g - 1, steps_covered=k_eff)
            self._h_step.observe(bd.wall_s)
            watchtower.on_train_step(g - 1, bd.wall_s / max(k_eff, 1))
            if logged:
                self._flush_telemetry(step=g - 1)
            if failure.preempt_requested():
                self._graceful_preempt(g)
        # execution fence: ONE scalar device_get of the final fused
        # loss (which depends on every prior step). block_until_ready
        # here would issue one sync RPC per param leaf — measured
        # ~12 ms each through the axon tunnel, dwarfing the fused
        # dispatches it fences — and can return early there anyway.
        if self.last_metrics is not None:
            float(jax.device_get(self.last_metrics["loss"]))
        failure.notify_done()
        return self.history

    def _build_eval(self) -> None:
        import jax.numpy as jnp

        cfg = self.cfg
        if cfg.parallel.strategy == "pipeline":
            # forward-only pipelined eval on the stacked stage params
            from pytorch_distributed_nn_tpu.parallel.pipeline import (
                make_pipeline_eval_step,
            )

            self._eval_step = make_pipeline_eval_step(
                cfg, self.mesh, self.loss_fn, self.model
            )
            return
        from pytorch_distributed_nn_tpu.parallel.dp import forward

        loss_fn = self.loss_fn
        xent_chunk = self.cfg.xent_chunk

        # mirror api.make_train_step: when the whole sequence fits in
        # one chunk, training used the dense loss — eval must too
        if xent_chunk and self.cfg.data.seq_len > xent_chunk:
            # long-context LM: dense (B, T, V) eval logits would OOM the
            # same way training would — evaluate chunked too
            from pytorch_distributed_nn_tpu.train.losses import (
                chunked_lm_eval,
            )

            def eval_step(state, x, y):
                hidden, _, _ = forward(
                    state, state.params, x, train=False,
                    apply_kwargs={"return_hidden": True},
                )
                kernel = state.params["lm_head"]["kernel"]
                loss, acc = chunked_lm_eval(hidden, kernel, y,
                                            chunk=xent_chunk)
                return loss, acc
        else:
            def eval_step(state, x, y):
                # dp.forward is the one place that knows how to assemble
                # variables/mutable collections; eval must not fork it
                logits, _, _ = forward(state, state.params, x,
                                       train=False)
                loss = loss_fn(logits, y)
                # masked accuracy: labels < 0 mean "ignore" (BERT MLM)
                valid = y >= 0
                hit = jnp.logical_and(logits.argmax(-1) == y, valid)
                acc = hit.sum() / jnp.maximum(valid.sum(), 1)
                return loss.astype(jnp.float32), acc.astype(jnp.float32)

        self._eval_step = jax.jit(eval_step)
        if self._seq_parallel:
            self._eval_step = self._with_mesh(self._eval_step)

    def evaluate(self, num_batches: int | None = None) -> EvalRecord:
        """Forward-only pass over the held-out stream; returns (and
        records) mean loss and masked accuracy. ``EvalRecord.step`` uses
        the same 0-based convention as ``StepRecord`` (-1 = before any
        training)."""
        n = self.cfg.eval_batches if num_batches is None else num_batches
        if n <= 0:
            raise ValueError(f"evaluate needs >= 1 batches, got {n}")
        # Disarm the progress watchdog across the (unbounded) eval-step
        # compile; per-batch completions below re-arm and feed it.
        failure.notify_done()
        if self._eval_step is None:
            self._build_eval()
        losses, accs = [], []
        with obs.span("train/eval", batches=n):
            for i in range(n):
                if i not in self._eval_batches:
                    # the stream is deterministic, so each batch is
                    # generated and transferred once and reused by
                    # every eval pass
                    self._eval_batches[i] = self.loader.batch_at(
                        _EVAL_STEP_OFFSET + i
                    )
                x, y = self._eval_batches[i]
                loss, acc = self._eval_step(self.state, x, y)
                losses.append(float(jax.device_get(loss)))
                accs.append(float(jax.device_get(acc)))
                failure.notify_progress()  # eval batches are progress
        rec = EvalRecord(step=self.data_step - 1,
                         loss=float(np.mean(losses)),
                         accuracy=float(np.mean(accs)))
        self.eval_history.append(rec)
        if self.metrics is not None:
            self.metrics.emit("eval", step=rec.step, loss=rec.loss,
                              accuracy=rec.accuracy)
        if jax.process_index() == 0:
            log.info("eval @ step %d: loss %.4f acc %.4f",
                     rec.step, rec.loss, rec.accuracy)
        return rec

    def save_checkpoint(self, *, force: bool = True) -> bool:
        if self.ckpt is None:
            raise RuntimeError("no checkpoint_dir configured")
        return self.ckpt.save(self.state, data_step=self.data_step,
                              force=force)

    def close(self) -> None:
        if self._preemptible:
            failure.uninstall_preemption_handler()
        if self.ckpt is not None:
            self.ckpt.close()
        if self.metrics is not None:
            if self.goodput.steps:
                # whole-run breakdown as the stream's closing record
                self.metrics.emit("goodput_summary",
                                  **self.goodput.summary())
            self.metrics.close()
        if self.cfg.prom_path:
            obs.get_registry().write_prometheus(self.cfg.prom_path)

    def losses(self) -> list[float]:
        return [r.loss for r in self.history]


def run_preset(preset: str, **overrides: Any) -> list[StepRecord]:
    from pytorch_distributed_nn_tpu.config import get_config

    trainer = Trainer(get_config(preset, **overrides))
    return trainer.train()
