"""Training drivers: optimizer factory, train state, and the Trainer — the
TPU-native counterpart of the reference's per-strategy ``train.py``
entrypoints (SURVEY.md §1 "Entrypoints / training drivers" row)."""
