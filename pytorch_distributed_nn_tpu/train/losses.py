"""Loss functions. Mean-reduction over the *global* batch, matching the
reference's ``nn.CrossEntropyLoss`` default so distributed loss curves are
directly comparable to single-device ones (SURVEY.md §4)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def valid_mask(labels) -> jnp.ndarray:
    """THE ignore-index convention, in one place: targets >= 0 are
    valid, negative targets (-1, torch ignore_index style) contribute
    neither loss nor denominator. Every consumer of the convention —
    masked_lm_xent, the smoothed variant, eval accuracy, and the 1F1B
    pipeline's per-microbatch valid-count weighting
    (parallel/pipeline.py) — must derive its mask here so a future
    loss with different masking can't silently diverge from one path
    only."""
    return labels >= 0


def softmax_xent(logits, labels) -> jnp.ndarray:
    """Classification: logits (B, C) float, labels (B,) int."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


def lm_xent(logits, targets) -> jnp.ndarray:
    """Causal LM: logits (B, T, V), targets (B, T) int."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    ).mean()


def masked_lm_xent(logits, labels) -> jnp.ndarray:
    """BERT MLM: logits (B, T, V); labels (B, T) with -1 = ignore. Mean
    over masked positions only (torch ``CrossEntropyLoss(ignore_index)``
    semantics).

    Note: the denominator is the *local* masked count. Under the
    compiler-sharded 'dp' path the whole batch is one computation, so
    this is the exact global mean; under 'dp_explicit' each device
    divides by its shard's count before the pmean — which is precisely
    torch DDP's per-rank behavior for ignore_index losses (reference
    parity), not the global mean."""
    valid = valid_mask(labels)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), jnp.maximum(labels, 0)
    )
    per_tok = jnp.where(valid, per_tok, 0.0)
    return per_tok.sum() / jnp.maximum(valid.sum(), 1)


def _to_chunks(hidden, targets, chunk: int):
    """(B, T, ·) -> per-chunk scan operands (nb, B, chunk, ·), or None
    when T is indivisible — logged loudly, because the dense fallback
    materializes the (B, T, V) logits the chunked path exists to avoid
    (api.make_train_step rejects this at config time; direct callers
    get the warning)."""
    B, T = targets.shape
    if T % chunk:
        import logging

        logging.getLogger(__name__).warning(
            "chunked LM loss: T=%d %% chunk=%d != 0 — dense fallback, "
            "(B, T, V) logits WILL materialize", T, chunk,
        )
        return None
    nb = T // chunk
    h = hidden.reshape(B, nb, chunk, -1).transpose(1, 0, 2, 3)
    t = targets.reshape(B, nb, chunk).transpose(1, 0, 2)
    return h, t


def chunked_lm_xent(hidden, kernel, targets, *, chunk: int = 2048
                    ) -> jnp.ndarray:
    """Causal-LM xent without ever materializing the (B, T, V) logits.

    At long context the logits — not attention — are the HBM limiter
    (B=1, T=32k, V=128k f32 is 16 GB before gradients). This computes
    the head projection + cross-entropy per T-chunk inside a
    ``lax.scan`` whose body is ``jax.checkpoint``-ed, so forward AND
    backward keep only one (B, chunk, V) logits block live.

    hidden: (B, T, D) final-norm'd trunk output (model ``return_hidden``
    path); kernel: (D, V) lm_head weight; targets: (B, T) int.
    Numerically identical to ``lm_xent(hidden @ kernel, targets)``.
    """
    chunks = _to_chunks(hidden, targets, chunk)
    if chunks is None:
        return lm_xent(
            jnp.einsum("btd,dv->btv", hidden, kernel), targets
        )
    h, t = chunks
    B, T, _ = hidden.shape

    @jax.checkpoint
    def body(acc, ht):
        h_blk, t_blk = ht
        logits = jnp.einsum(
            "bcd,dv->bcv", h_blk, kernel,
            preferred_element_type=jnp.float32,
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), t_blk
        ).sum()
        return acc + loss, None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (h, t))
    return total / (B * T)


def chunked_lm_eval(hidden, kernel, targets, *, chunk: int = 2048
                    ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Eval twin of :func:`chunked_lm_xent`: (mean loss, accuracy)
    per T-chunk, still never materializing full logits (an eval pass at
    long context would otherwise OOM exactly like training did)."""
    chunks = _to_chunks(hidden, targets, chunk)
    if chunks is None:
        logits = jnp.einsum("btd,dv->btv", hidden, kernel)
        return lm_xent(logits, targets), accuracy(logits, targets)
    h, t = chunks
    B, T, _ = hidden.shape

    def body(carry, ht):
        loss_acc, hit_acc = carry
        h_blk, t_blk = ht
        logits = jnp.einsum(
            "bcd,dv->bcv", h_blk, kernel,
            preferred_element_type=jnp.float32,
        ).astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, t_blk
        ).sum()
        hits = (logits.argmax(-1) == t_blk).sum()
        return (loss_acc + loss, hit_acc + hits), None

    (loss, hits), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (h, t),
    )
    n = B * T
    return loss / n, hits.astype(jnp.float32) / n


def accuracy(logits, labels) -> jnp.ndarray:
    return (logits.argmax(-1) == labels).mean()


_LOSSES = {
    "lm_synthetic": lm_xent,
    "token_file": lm_xent,
    "mlm_synthetic": masked_lm_xent,
}


def _smoothed(base, eps: float):
    """torch ``CrossEntropyLoss(label_smoothing=eps)`` semantics:
    per-element loss = (1-eps)·nll + eps·(uniform xent over classes);
    the -1=ignore masking of :func:`masked_lm_xent` is preserved by
    applying the same formula under its mask."""

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        uniform = -logp.mean(-1)
        nll = -jnp.take_along_axis(
            logp, jnp.maximum(labels, 0)[..., None], axis=-1
        )[..., 0]
        per = (1.0 - eps) * nll + eps * uniform
        if base is masked_lm_xent:
            valid = valid_mask(labels)
            per = jnp.where(valid, per, 0.0)
            return per.sum() / jnp.maximum(valid.sum(), 1)
        return per.mean()

    return loss_fn


def get_loss_fn(dataset_name: str, *, label_smoothing: float = 0.0):
    base = _LOSSES.get(dataset_name, softmax_xent)
    if label_smoothing == 0.0:
        return base
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}"
        )
    return _smoothed(base, label_smoothing)


def model_nll(model, params, batches) -> float:
    """Teacher-forced mean per-token NLL of a causal LM over an
    iterable of (tokens, targets) batches — the whole-model quality
    metric behind ``bench.py --metric quality`` (int8-vs-bf16 NLL
    delta; VERDICT r4 Missing #3). Works for float and int8-quantized
    param trees alike (the model's lm_head emits f32 logits either
    way). Perplexity = exp(return value).

    The xent lives INSIDE the jit: the (B, T, V) logits then exist
    once on device (f32, 2.1 GB at the 8B's B=1/T=4096/V=128k) with
    the log-softmax reduction fused behind them, instead of surviving
    the program boundary and feeding eager optax temporaries of the
    same size. Raise B with the 8B only as that peak allows."""

    @jax.jit
    def batch_nll(params, x, y):
        logits = model.apply({"params": params}, x, train=False)
        return lm_xent(logits, y)

    total, count = 0.0, 0
    for x, y in batches:
        nll = batch_nll(params, jnp.asarray(x), jnp.asarray(y))
        n = int(jnp.asarray(y).size)
        total += float(jax.device_get(nll)) * n
        count += n
    if count == 0:
        raise ValueError("model_nll needs at least one batch")
    return total / count
