"""Loss functions. Mean-reduction over the *global* batch, matching the
reference's ``nn.CrossEntropyLoss`` default so distributed loss curves are
directly comparable to single-device ones (SURVEY.md §4)."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def softmax_xent(logits, labels) -> jnp.ndarray:
    """Classification: logits (B, C) float, labels (B,) int."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


def lm_xent(logits, targets) -> jnp.ndarray:
    """Causal LM: logits (B, T, V), targets (B, T) int."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    ).mean()


def masked_lm_xent(logits, labels) -> jnp.ndarray:
    """BERT MLM: logits (B, T, V); labels (B, T) with -1 = ignore. Mean
    over masked positions only (torch ``CrossEntropyLoss(ignore_index)``
    semantics).

    Note: the denominator is the *local* masked count. Under the
    compiler-sharded 'dp' path the whole batch is one computation, so
    this is the exact global mean; under 'dp_explicit' each device
    divides by its shard's count before the pmean — which is precisely
    torch DDP's per-rank behavior for ignore_index losses (reference
    parity), not the global mean."""
    valid = labels >= 0
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), jnp.maximum(labels, 0)
    )
    per_tok = jnp.where(valid, per_tok, 0.0)
    return per_tok.sum() / jnp.maximum(valid.sum(), 1)


def accuracy(logits, labels) -> jnp.ndarray:
    return (logits.argmax(-1) == labels).mean()


_LOSSES = {
    "lm_synthetic": lm_xent,
    "mlm_synthetic": masked_lm_xent,
}


def get_loss_fn(dataset_name: str):
    return _LOSSES.get(dataset_name, softmax_xent)
