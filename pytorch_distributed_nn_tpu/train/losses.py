"""Loss functions. Mean-reduction over the *global* batch, matching the
reference's ``nn.CrossEntropyLoss`` default so distributed loss curves are
directly comparable to single-device ones (SURVEY.md §4)."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def softmax_xent(logits, labels) -> jnp.ndarray:
    """Classification: logits (B, C) float, labels (B,) int."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


def lm_xent(logits, targets) -> jnp.ndarray:
    """Causal LM: logits (B, T, V), targets (B, T) int."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    ).mean()


def accuracy(logits, labels) -> jnp.ndarray:
    return (logits.argmax(-1) == labels).mean()


def get_loss_fn(dataset_name: str):
    return lm_xent if dataset_name == "lm_synthetic" else softmax_xent
