"""``tpu-launch`` — the ``torchrun`` replacement (elastic agent).

The reference is launched as ``torchrun --nproc-per-node N train.py``:
an agent process spawns N workers with the ``RANK``/``WORLD_SIZE``/
``MASTER_ADDR``/``MASTER_PORT`` env contract, watches them, and on a
worker failure tears the gang down and restarts it up to
``--max-restarts`` times (SURVEY.md §1 Launch row, §2b torchrun row,
§5 Failure-detection row). This module is the TPU-native equivalent:

- spawns N local worker processes with both the JAX-native
  (``PROCESS_ID``/``NUM_PROCESSES``/``COORDINATOR_ADDRESS``) and the
  torch-style (``RANK``/``WORLD_SIZE``/``MASTER_ADDR``/``MASTER_PORT``)
  env contracts, so either convention works in the worker
  (:mod:`runtime.bootstrap` reads both);
- monitors worker liveness two ways: exit codes (crash) and — when
  ``--heartbeat-timeout`` is set — heartbeats into a node-local C++
  store (native/store.cpp) it hosts (hang — a deadlocked collective
  never exits, so exit codes are not enough); each node's agent watches
  only the ranks it spawned;
- on failure, kills the whole gang and relaunches it with an
  incremented ``TPUNN_RESTART`` incarnation, governed by
  :class:`RestartPolicy`: a restart-budget *window* (max N per T
  seconds), exponential backoff + jitter between incarnations,
  fail-fast on repeated identical pre-heartbeat crashes, and free
  restarts for graceful preemption exits
  (``failure.GRACEFUL_EXIT_CODE`` — docs/robustness.md). Recovery of
  *progress* is the worker's job: resume from the latest checkpoint
  (``train.checkpoint.CheckpointManager.restore``), the standard TPU
  fail-fast + restart-from-checkpoint practice.

CLI::

    python -m pytorch_distributed_nn_tpu.launch \
        --nprocs 4 --max-restarts 2 -- script.py --flag ...

On a real multi-host pod each host runs one agent with
``--node-rank``/``--nnodes`` so rank offsets and the coordinator
address line up; workers then hold the hosts' chips via PJRT.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import os
import random
import signal
import socket
import subprocess
import sys
import time

from .obs import aggregate, runtime_gauges, watchtower
from .runtime import failure, native

log = logging.getLogger(__name__)


@dataclasses.dataclass
class LaunchConfig:
    nprocs: int
    max_restarts: int = 0
    heartbeat_timeout_s: float | None = None  # None → exit-code-only watch
    heartbeat_interval_s: float = 1.0
    progress_timeout_s: float | None = None  # step-progress watchdog window
    poll_interval_s: float = 0.2
    kill_grace_s: float = 5.0
    flight_dir: str | None = None  # where workers dump flight rings
    flight_dump_grace_s: float = 2.0  # wait for dumps before the kill
    # restart policy (RestartPolicy): max_restarts per restart_window_s
    # seconds (None → per job lifetime), exponential backoff with
    # jitter between incarnations, fail-fast on repeated identical
    # pre-heartbeat crashes
    restart_window_s: float | None = None
    backoff_base_s: float = 1.0
    backoff_max_s: float = 30.0
    backoff_factor: float = 2.0
    backoff_jitter: float = 0.1
    failfast_repeats: int = 2
    failfast_startup_s: float = 5.0
    restart_seed: int = 0
    nnodes: int = 1
    node_rank: int = 0
    master_addr: str = "127.0.0.1"
    master_port: int | None = None  # None → pick a free port per incarnation
    env: dict[str, str] = dataclasses.field(default_factory=dict)


def worker_env(*, rank: int, local_rank: int | None = None,
               world_size: int = 1, master_addr: str = "127.0.0.1",
               master_port: int | None = None, incarnation: int = 0,
               heartbeat_interval_s: float | None = None,
               progress_timeout_s: float | None = None,
               store_host: str = "127.0.0.1",
               store_port: int | None = None,
               flight_dir: str | None = None,
               extra: dict[str, str] | None = None) -> dict[str, str]:
    """The agent↔worker environment contract, in ONE place: both the
    JAX-native (``PROCESS_ID``/``NUM_PROCESSES``/``COORDINATOR_ADDRESS``)
    and torch-style (``RANK``/``WORLD_SIZE``/``MASTER_*``) rank vars,
    plus the ``TPUNN_*`` heartbeat/restart/flight contract
    (:mod:`runtime.failure`). Used by :class:`ElasticAgent` for training
    gangs and by :class:`serve.procfleet.ProcessFleet` for serving
    replica workers — one contract, two supervisors."""
    env = dict(os.environ)
    if extra:
        env.update(extra)
    env.update(
        RANK=str(rank),
        LOCAL_RANK=str(rank if local_rank is None else local_rank),
        WORLD_SIZE=str(world_size),
        PROCESS_ID=str(rank),
        NUM_PROCESSES=str(world_size),
    )
    if master_port is not None:
        env.update(
            MASTER_ADDR=master_addr,
            MASTER_PORT=str(master_port),
            COORDINATOR_ADDRESS=f"{master_addr}:{master_port}",
        )
    env[failure.ENV_RESTART] = str(incarnation)
    if heartbeat_interval_s is not None:
        env[failure.ENV_HB_INTERVAL] = str(heartbeat_interval_s)
    if progress_timeout_s is not None:
        env[failure.ENV_PROGRESS_WINDOW] = str(progress_timeout_s)
    if flight_dir is not None:
        from pytorch_distributed_nn_tpu.obs import flight as _fl

        env[_fl.ENV_FLIGHT_DIR] = str(flight_dir)
    if store_port is not None:
        env[failure.ENV_STORE_PORT] = str(store_port)
        env[failure.ENV_STORE_HOST] = store_host
    return env


@dataclasses.dataclass
class IncarnationRecord:
    """One gang incarnation's outcome (LaunchResult.incarnations)."""

    reason: str  # "ok" | "crash" | "hang" | "preempt"
    code: int
    duration_s: float


@dataclasses.dataclass
class LaunchResult:
    exit_code: int
    restarts: int  # incarnations actually consumed (0 = clean first run)
    reason: str = "ok"  # "ok" | "crash" | "hang" | "preempt"
    stop_reason: str = ""  # why the agent stopped restarting
    incarnations: list[IncarnationRecord] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class Decision:
    """RestartPolicy verdict after one failed incarnation."""

    action: str  # "restart" | "stop"
    delay_s: float = 0.0
    why: str = ""


class RestartPolicy:
    """Restart governor for the elastic agent (torchrun's fixed
    ``--max-restarts`` counter, hardened for pod reality):

    - **budget window** — at most ``max_restarts`` budget-charged
      restarts per ``window_s`` seconds (sliding; ``None`` = per job
      lifetime). A job that crashes once a day for a month should keep
      restarting; one that crashes 5x in a minute should not.
    - **exponential backoff + jitter** — ``base * factor**(n-1)`` capped
      at ``max_s``, ±``jitter`` fraction from a seeded RNG, so a gang of
      agents doesn't stampede a recovering coordinator/filesystem.
    - **fail-fast** — the same exit code ``failfast_repeats`` times in a
      row *before any heartbeat* (import error, bad flag, missing
      checkpoint dir) is a deterministic startup crash: restarting burns
      budget without hope. With no heartbeat monitor, "pre-heartbeat"
      falls back to ``duration < failfast_startup_s``.
    - **graceful preemption** (exit ``failure.GRACEFUL_EXIT_CODE``) —
      restarts immediately and charges nothing: a preempted worker did
      nothing wrong.

    Process-agnostic on purpose: the elastic agent below governs OS
    processes with it, and the serving fleet (serve/fleet.py) reuses
    it unchanged per replica — thread-backed replicas crash, hang, and
    drain through the same budget/backoff/preempt semantics.

    ``clock`` is injectable for fake-clock tests.
    """

    def __init__(self, *, max_restarts: int,
                 window_s: float | None = None,
                 backoff_base_s: float = 1.0,
                 backoff_max_s: float = 30.0,
                 backoff_factor: float = 2.0,
                 jitter_frac: float = 0.1,
                 failfast_repeats: int = 2,
                 failfast_startup_s: float = 5.0,
                 seed: int = 0,
                 clock=time.monotonic) -> None:
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got "
                             f"{max_restarts}")
        if not 0.0 <= jitter_frac < 1.0:
            raise ValueError(f"jitter_frac must be in [0, 1), got "
                             f"{jitter_frac}")
        self.max_restarts = max_restarts
        self.window_s = window_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.backoff_factor = backoff_factor
        self.jitter_frac = jitter_frac
        self.failfast_repeats = failfast_repeats
        self.failfast_startup_s = failfast_startup_s
        self._clock = clock
        self._rng = random.Random(seed)
        self._grants: list[float] = []  # budget-charged restart times
        self._failures = 0  # consecutive failed incarnations (backoff)
        self._startup_streak = 0  # consecutive same-code startup crashes
        self._startup_code: int | None = None
        self.preempt_restarts = 0
        self.backoff_total_s = 0.0

    def backoff_bounds(self, failures: int) -> tuple[float, float]:
        """[lo, hi] delay for the n-th consecutive failure — the
        testable jitter envelope."""
        raw = min(self.backoff_base_s
                  * self.backoff_factor ** max(failures - 1, 0),
                  self.backoff_max_s)
        return raw * (1.0 - self.jitter_frac), raw * (1.0 + self.jitter_frac)

    def on_exit(self, *, reason: str, code: int, duration_s: float,
                beat_seen: bool | None = None) -> Decision:
        """Classify one finished incarnation; call once per exit."""
        if reason == "ok":
            return Decision("stop", why="ok")
        if reason == "preempt":
            # graceful exit: not a failure — no budget charge, no
            # backoff growth, restart at once
            self._failures = 0
            self._startup_streak = 0
            self.preempt_restarts += 1
            return Decision("restart", 0.0, "graceful preemption exit")
        pre_beat = ((not beat_seen) if beat_seen is not None
                    else duration_s < self.failfast_startup_s)
        if reason == "crash" and pre_beat:
            if self._startup_streak and code == self._startup_code:
                self._startup_streak += 1
            else:
                self._startup_streak = 1
                self._startup_code = code
            if self._startup_streak >= self.failfast_repeats:
                return Decision(
                    "stop",
                    why=(f"failfast: exit code {code} x"
                         f"{self._startup_streak} before first "
                         f"heartbeat (deterministic startup crash)"),
                )
        else:
            self._startup_streak = 0
        now = self._clock()
        if self.window_s is not None:
            self._grants = [t for t in self._grants
                            if now - t < self.window_s]
        if len(self._grants) >= self.max_restarts:
            scope = (f"{self.max_restarts} per {self.window_s}s"
                     if self.window_s is not None
                     else f"{self.max_restarts} per job")
            return Decision("stop",
                            why=f"restart budget exhausted ({scope})")
        self._grants.append(now)
        self._failures += 1
        lo, hi = self.backoff_bounds(self._failures)
        delay = lo + (hi - lo) * self._rng.random()
        self.backoff_total_s += delay
        return Decision("restart", delay,
                        f"backoff {delay:.2f}s (consecutive failure "
                        f"{self._failures})")

    @property
    def budget_restarts(self) -> int:
        return len(self._grants)


def _clamp_code(code: int) -> int:
    """Exit codes a shell can see: signal-killed workers (poll() < 0)
    map to the 128+N convention instead of aliasing the hang sentinel
    or being masked to an arbitrary byte by sys.exit."""
    if code < 0:
        return 128 - code
    return code if 0 < code < 256 else 1


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ElasticAgent:
    """One incarnation loop: spawn gang → watch → (maybe) restart."""

    def __init__(self, argv: list[str], cfg: LaunchConfig) -> None:
        if not argv:
            raise ValueError("no worker command given")
        if cfg.nprocs < 1:
            # An empty gang would vacuously "succeed" in _watch.
            raise ValueError(f"nprocs must be >= 1, got {cfg.nprocs}")
        if (cfg.progress_timeout_s is not None
                and cfg.heartbeat_timeout_s is None):
            raise ValueError(
                "progress_timeout_s needs heartbeat_timeout_s: the "
                "watchdog signals a hang by going silent, and only the "
                "heartbeat monitor listens for silence"
            )
        if (cfg.heartbeat_timeout_s is not None
                and cfg.heartbeat_timeout_s < 2 * cfg.heartbeat_interval_s):
            # A timeout inside the beat period would condemn healthy
            # workers between beats.
            raise ValueError(
                f"heartbeat_timeout_s ({cfg.heartbeat_timeout_s}) must be "
                f">= 2x heartbeat_interval_s ({cfg.heartbeat_interval_s})"
            )
        self.argv = argv
        self.cfg = cfg
        self._procs: list[subprocess.Popen] = []

    # -- gang lifecycle ----------------------------------------------------

    def _spawn(self, incarnation: int, store_port: int | None) -> None:
        cfg = self.cfg
        if cfg.master_port is None and cfg.nnodes > 1:
            # Each node runs its own agent; a per-agent random port would
            # hand every node a different COORDINATOR_ADDRESS.
            raise ValueError("--master-port is required when nnodes > 1")
        port = cfg.master_port or _free_port()
        world = cfg.nprocs * cfg.nnodes
        base = cfg.nprocs * cfg.node_rank
        for local_rank in range(cfg.nprocs):
            rank = base + local_rank
            env = worker_env(
                rank=rank, local_rank=local_rank, world_size=world,
                master_addr=cfg.master_addr, master_port=port,
                incarnation=incarnation,
                heartbeat_interval_s=cfg.heartbeat_interval_s,
                progress_timeout_s=cfg.progress_timeout_s,
                # Workers heartbeat into the store of the agent that
                # spawned them (always this host) — node-local liveness.
                store_port=store_port,
                flight_dir=cfg.flight_dir,
                extra=cfg.env,
            )
            self._procs.append(subprocess.Popen(
                [sys.executable, *self.argv], env=env
            ))

    def _kill_gang(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + self.cfg.kill_grace_s
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.05, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        self._procs.clear()

    # -- one incarnation ---------------------------------------------------

    def _feed_rank_progress(self, monitor,
                            incarnation: int,
                            detector: failure.FailureDetector) -> None:
        """Supervisor-side straggler feed for the watchtower: per-rank
        cumulative step counts come from the aggregate snapshots each
        worker publishes at log cadence (obs/aggregate.py), so no new
        transport is needed. The drift detector compares every rank's
        step rate against the peer median and pages with the lagging
        rank *named*; on a fresh page the agent also asks every worker
        for a flight dump so obs_doctor has rings to attribute
        against."""
        cfg = self.cfg
        base = cfg.nprocs * cfg.node_rank
        try:
            snaps = aggregate.collect_snapshots(
                monitor, list(range(base, base + cfg.nprocs)),
                incarnation=incarnation)
        except OSError:
            return
        steps = {r: s["train_steps_total"] for r, s in snaps.items()
                 if "train_steps_total" in s}
        if len(steps) < 2:
            return
        tower = watchtower.tower()
        before = len(tower.alerts) if tower is not None else 0
        watchtower.on_rank_progress(steps)
        if tower is not None and any(
                a.kind == "straggler_drift" for a in tower.alerts[before:]):
            detector.request_flight_dump("watchtower straggler_drift")

    def _watch(self, detector: failure.FailureDetector | None,
               monitor=None, incarnation: int = 0) -> tuple[str, int]:
        """Poll until the gang succeeds, a worker fails, or a worker
        hangs. Success requires *every* worker to exit 0. Returns
        (reason, exit_code) with reason in {"ok", "crash", "hang",
        "preempt"}."""
        cfg = self.cfg
        base = cfg.nprocs * cfg.node_rank
        while True:
            codes = [p.poll() for p in self._procs]
            bad = [(i, c) for i, c in enumerate(codes) if c not in (None, 0)]
            if bad:
                rank, code = bad[0]
                if code == failure.GRACEFUL_EXIT_CODE:
                    # graceful preemption exit (SIGTERM → final save →
                    # distinct code): not charged to the restart budget
                    log.warning("worker local_rank=%d exited gracefully "
                                "on preemption", rank)
                    return "preempt", _clamp_code(code)
                log.warning("worker local_rank=%d exited %d", rank, code)
                return "crash", _clamp_code(code)
            if all(c == 0 for c in codes):
                return "ok", 0
            if detector is not None:
                alive = {base + i for i, c in enumerate(codes) if c is None}
                stale = detector.stale_ranks(alive)
                # agent-side observability: per-rank last-beat age and
                # missed-beat gauges in the process registry (scraped /
                # snapshotted like any worker metric)
                runtime_gauges.export_detector_gauges(detector)
                if watchtower.enabled() and monitor is not None:
                    self._feed_rank_progress(monitor, incarnation, detector)
                if stale:
                    log.warning("heartbeat lost from ranks %s", stale)
                    # Flight-recorder forensics: ask every worker's
                    # heartbeat thread to dump its ring, and give them
                    # a beat interval or two to do it BEFORE the kill
                    # (the stalled rank's main thread can't dump; its
                    # daemon thread can).
                    if detector.request_flight_dump(
                            f"stale ranks {stale}"):
                        time.sleep(max(cfg.flight_dump_grace_s,
                                       2 * cfg.heartbeat_interval_s))
                    return "hang", 1
            time.sleep(cfg.poll_interval_s)

    def _policy(self) -> RestartPolicy:
        cfg = self.cfg
        return RestartPolicy(
            max_restarts=cfg.max_restarts,
            window_s=cfg.restart_window_s,
            backoff_base_s=cfg.backoff_base_s,
            backoff_max_s=cfg.backoff_max_s,
            backoff_factor=cfg.backoff_factor,
            jitter_frac=cfg.backoff_jitter,
            failfast_repeats=cfg.failfast_repeats,
            failfast_startup_s=cfg.failfast_startup_s,
            seed=cfg.restart_seed,
        )

    def run(self) -> LaunchResult:
        cfg = self.cfg
        # supervisor-side watchtower (TPUNN_WATCH): the agent feeds it
        # cross-rank step progress; workers arm their own instance
        watchtower.maybe_init()
        policy = self._policy()
        history: list[IncarnationRecord] = []
        incarnation = 0
        while True:
            server = None
            monitor = None
            detector = None
            beat_seen: bool | None = None
            t0 = time.monotonic()
            try:
                if cfg.heartbeat_timeout_s is not None:
                    # The store (and the workers' heartbeat threads) only
                    # exist when something will read the beats.
                    try:
                        server = native.StoreServer()
                    except (native.NativeUnavailable, OSError) as e:
                        raise RuntimeError(
                            "heartbeat monitoring requires the native "
                            f"store, which failed to load: {e}"
                        ) from e
                    monitor = native.StoreClient("127.0.0.1", server.port)
                    base = cfg.nprocs * cfg.node_rank
                    detector = failure.FailureDetector(
                        monitor,
                        ranks=list(range(base, base + cfg.nprocs)),
                        incarnation=incarnation,
                        timeout_s=cfg.heartbeat_timeout_s,
                    )
                self._spawn(incarnation,
                            server.port if server is not None else None)
                reason, code = self._watch(detector, monitor, incarnation)
                if detector is not None:
                    # the fail-fast discriminator, read BEFORE the store
                    # goes down with the gang
                    beat_seen = detector.any_beats()
            finally:
                self._kill_gang()
                if monitor is not None:
                    monitor.close()
                if server is not None:
                    server.stop()
            history.append(IncarnationRecord(
                reason=reason, code=code,
                duration_s=time.monotonic() - t0))
            decision = (Decision("stop", why="ok") if reason == "ok"
                        else policy.on_exit(
                            reason=reason, code=code,
                            duration_s=history[-1].duration_s,
                            beat_seen=beat_seen))
            runtime_gauges.export_restart_gauges(
                incarnations=len(history),
                restarts=policy.budget_restarts,
                preempt_restarts=policy.preempt_restarts,
                backoff_seconds_total=policy.backoff_total_s,
                last_exit_code=code,
            )
            if reason == "ok":
                return LaunchResult(exit_code=0, restarts=incarnation,
                                    reason="ok", stop_reason="ok",
                                    incarnations=history)
            if decision.action == "stop":
                log.warning("not restarting: %s", decision.why)
                return LaunchResult(exit_code=code, restarts=incarnation,
                                    reason=reason,
                                    stop_reason=decision.why,
                                    incarnations=history)
            log.warning("restarting gang (incarnation %d → %d): %s",
                        incarnation, incarnation + 1, decision.why)
            if decision.delay_s > 0:
                time.sleep(decision.delay_s)
            incarnation += 1


# signals that must tear the gang down with the agent: SIGTERM (cluster
# kill / preemption), SIGINT (interactive Ctrl-C), SIGHUP (lost
# terminal) — any of them hitting only the agent would orphan workers
_PROPAGATED_SIGNALS = (signal.SIGTERM, signal.SIGINT, signal.SIGHUP)


def launch(argv: list[str], cfg: LaunchConfig) -> LaunchResult:
    """Run ``argv`` (a python script + args) as an ``nprocs`` gang."""
    agent = ElasticAgent(argv, cfg)

    def _propagate(signum, frame):  # propagate an agent kill to the gang
        agent._kill_gang()
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    old: dict[int, object] = {}
    for signum in _PROPAGATED_SIGNALS:
        try:
            old[signum] = signal.signal(signum, _propagate)
        except (ValueError, OSError):  # non-main thread / exotic platform
            pass
    try:
        return agent.run()
    finally:
        for signum, prev in old.items():
            signal.signal(signum, prev)


def main(args: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pytorch_distributed_nn_tpu.launch",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument("--nprocs", type=int, required=True,
                    help="worker processes on this host "
                         "(torchrun --nproc-per-node)")
    ap.add_argument("--max-restarts", type=int, default=0)
    ap.add_argument("--restart-window", type=float, default=None,
                    help="budget window in seconds: at most "
                         "--max-restarts budget-charged restarts per "
                         "this many seconds (default: per job lifetime)")
    ap.add_argument("--backoff-base", type=float, default=1.0,
                    help="first-restart backoff seconds (doubles per "
                         "consecutive failure, jittered)")
    ap.add_argument("--backoff-max", type=float, default=30.0,
                    help="backoff ceiling in seconds")
    ap.add_argument("--heartbeat-timeout", type=float, default=None,
                    help="seconds without a heartbeat before a worker "
                         "counts as hung (default: exit-code watch only)")
    ap.add_argument("--progress-timeout", type=float, default=None,
                    help="seconds without a completed training step "
                         "before a worker stops heartbeating (catches "
                         "deadlocked collectives; needs "
                         "--heartbeat-timeout)")
    ap.add_argument("--flight-dir", default=None,
                    help="directory where workers dump their collective "
                         "flight rings (flight_rank<k>.json) on "
                         "hang/crash; analyze with scripts/obs_doctor.py")
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node-rank", type=int, default=0)
    ap.add_argument("--master-addr", default="127.0.0.1")
    ap.add_argument("--master-port", type=int, default=None)
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="worker script and its args (prefix with --)")
    ns = ap.parse_args(args)
    script = ns.script[1:] if ns.script[:1] == ["--"] else ns.script
    if not script:
        ap.error("missing worker script")
    if ns.progress_timeout is not None and ns.heartbeat_timeout is None:
        ap.error("--progress-timeout requires --heartbeat-timeout")
    logging.basicConfig(level=logging.INFO,
                        format="[tpu-launch] %(levelname)s %(message)s")
    result = launch(script, LaunchConfig(
        nprocs=ns.nprocs,
        max_restarts=ns.max_restarts,
        restart_window_s=ns.restart_window,
        backoff_base_s=ns.backoff_base,
        backoff_max_s=ns.backoff_max,
        heartbeat_timeout_s=ns.heartbeat_timeout,
        progress_timeout_s=ns.progress_timeout,
        flight_dir=ns.flight_dir,
        nnodes=ns.nnodes,
        node_rank=ns.node_rank,
        master_addr=ns.master_addr,
        master_port=ns.master_port,
    ))
    if result.restarts:
        log.info("job finished after %d restart(s): %s", result.restarts,
                 "; ".join(f"[{i}] {r.reason} code={r.code} "
                           f"{r.duration_s:.1f}s"
                           for i, r in enumerate(result.incarnations)))
    if result.stop_reason and result.stop_reason != "ok":
        log.warning("agent stopped: %s", result.stop_reason)
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
