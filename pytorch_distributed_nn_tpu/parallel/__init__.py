"""Parallelism strategies — the heart of the framework, as the strategy
layer is the heart of the reference (SURVEY.md §1). Each strategy builds a
jit-compiled train step over the named mesh; they compose through mesh
axes rather than through wrapper classes."""

from pytorch_distributed_nn_tpu.parallel.api import make_train_step

__all__ = ["make_train_step"]
