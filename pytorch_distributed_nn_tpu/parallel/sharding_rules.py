"""Parameter-layout rules: path + shape → PartitionSpec.

One rule function covers every strategy's layout needs (SURVEY.md §2c):

- **TP** (Megatron-style, pjit-native per arXiv 2204.06514, PAPERS.md):
  name-driven — attention q/k/v shard the heads dim, attention out shards
  the heads dim (row-parallel), MLP in/gate/up shard the hidden dim
  (column-parallel), MLP out/down shard it row-parallel, embeddings and
  LM heads shard the vocab dim. XLA's SPMD partitioner then inserts the
  Megatron all-reduces automatically.
- **ZeRO/FSDP**: shape-driven — after TP assignment, the largest remaining
  divisible dim of any big-enough leaf is sharded over ``fsdp``.

Optimizer state needs no special handling: optax moment trees embed the
parameter paths (``mu/block0/attn/query/kernel``), so the same path rules
apply verbatim — moments land on the same devices as their params (the
weight-update sharding of arXiv 2004.13336).
"""

from __future__ import annotations

import re

import numpy as np
from jax.sharding import PartitionSpec as P

from pytorch_distributed_nn_tpu.runtime.mesh import (
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_TENSOR,
)

# Leaves smaller than this stay replicated under fsdp (a gather of a bias
# costs more than it saves).
MIN_SHARD_ELEMS = 2 ** 14

# (path regex, dim to shard over `tensor`). Paths are '/'-joined param
# paths; optimizer-state paths contain these as suffixes.
TP_RULES: list[tuple[re.Pattern, int]] = [
    (re.compile(r"(query|key|value)/kernel$"), 1),  # (d, H, Dh): heads
    (re.compile(r"attn/out/kernel$"), 0),  # (H, Dh, d): heads (row-par)
    (re.compile(r"(mlp_in|gate_proj|up_proj)/kernel$"), 1),  # (d, ff)
    (re.compile(r"(mlp_in|gate_proj|up_proj)/bias$"), 0),  # (ff,)
    (re.compile(r"(mlp_out|down_proj)/kernel$"), 0),  # (ff, d): row-par
    (re.compile(r"(tok_embed|pos_embed|type_embed)/embedding$"), 0),
    (re.compile(r"(lm_head|mlm_decoder|head)/kernel$"), 1),  # (d, V)
    (re.compile(r"moe/wi$"), 2),  # (E, d, ff): shard ff (column-parallel)
    (re.compile(r"moe/wo$"), 1),  # (E, ff, d): shard ff (row-parallel)
]

# Stacked-expert leaves: leading E dim shards over `expert` (EP row of
# SURVEY.md §2c). The router stays replicated (it is tiny and every token
# needs it).
EP_RULES: list[tuple[re.Pattern, int]] = [
    (re.compile(r"moe/(wi|wo)$"), 0),
]

# Tables whose fsdp shard must ride the vocab dim (tupled with tensor
# when TP is on), never the d_model dim — see the comment in spec_for.
_VOCAB_TABLES: list[tuple[re.Pattern, int]] = [
    (re.compile(r"(tok_embed|pos_embed|type_embed)/embedding$"), 0),
    (re.compile(r"(lm_head|mlm_decoder|head)/kernel$"), 1),
]


def spec_for(path: str, shape: tuple[int, ...], *, tensor: int = 1,
             fsdp: int = 1, expert: int = 1,
             min_elems: int = MIN_SHARD_ELEMS) -> P:
    """The layout rule. ``path`` is the '/'-joined tree path of the leaf
    (params or optimizer state); ``shape`` its shape."""
    ndim = len(shape)
    axes: list = [None] * ndim
    if expert > 1:
        for pattern, dim in EP_RULES:
            if pattern.search(path) and dim < ndim \
                    and shape[dim] % expert == 0:
                axes[dim] = AXIS_EXPERT
                break
    if tensor > 1:
        for pattern, dim in TP_RULES:
            if pattern.search(path) and dim < ndim \
                    and shape[dim] % tensor == 0 and axes[dim] is None:
                axes[dim] = AXIS_TENSOR
                break
    if fsdp > 1 and int(np.prod(shape or (1,))) >= min_elems:
        # Embedding/head tables: co-shard fsdp WITH tensor on the vocab
        # dim instead of sharding d_model. Sharding their d dim forces
        # the SPMD partitioner to reshard activation cotangents from
        # batch-sharding to feature-sharding inside the backward, a
        # transition it can only do by full rematerialization
        # (spmd_partitioner.cc "Involuntary full rematerialization" —
        # VERDICT.md round-1 Weak #2).
        for pattern, dim in _VOCAB_TABLES:
            if (pattern.search(path) and dim < ndim
                    and axes[dim] in (AXIS_TENSOR, None)
                    and shape[dim] % ((tensor if axes[dim] else 1)
                                      * fsdp) == 0):
                axes[dim] = ((AXIS_TENSOR, AXIS_FSDP)
                             if axes[dim] else AXIS_FSDP)
                break
        else:
            candidates = [
                (size, i) for i, size in enumerate(shape)
                if axes[i] is None and size % fsdp == 0
            ]
            if candidates:
                _, best = max(candidates)
                axes[best] = AXIS_FSDP
    if all(a is None for a in axes):
        return P()
    return P(*axes)


def path_str(key_path) -> str:
    """jax.tree_util key path → '/'-joined string."""
    parts = []
    for key in key_path:
        if hasattr(key, "key"):
            parts.append(str(key.key))
        elif hasattr(key, "name"):
            parts.append(str(key.name))
        elif hasattr(key, "idx"):
            parts.append(str(key.idx))
        else:
            parts.append(str(key))
    return "/".join(parts)
