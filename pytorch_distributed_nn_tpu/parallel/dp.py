"""Data parallelism.

Two implementations of the same math, mirroring the reference's two DP
code paths (SURVEY.md §3.1 vs §3.2):

- :func:`make_dp_train_step` — *compiler-sharded* DP, the DDP analogue:
  params replicated, batch sharded over the data axes, one ``jit``; XLA
  derives the gradient all-reduce from the shardings and schedules it
  asynchronously, overlapped with remaining backward compute — the
  compiler-native form of DDP's bucket/overlap Reducer (SURVEY.md §2b).

- :func:`make_dp_train_step_explicit` — *hand-rolled* DP under
  ``shard_map``, the analogue of the reference's pedagogical
  ``average_gradients`` loop: per-device grads, then an explicit
  per-tensor (or bucketed — ops/buckets.py) ``pmean``. Exists for parity,
  for the bucket-size experiments behind the BASELINE bus-bw metric, and
  as the hook point for quantized allreduce.

Both produce bit-identical results to single-device training on the same
global batch (the golden-equivalence oracle, SURVEY.md §4).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_nn_tpu.ops import collectives as cc
from pytorch_distributed_nn_tpu.runtime.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    batch_pspec,
    global_device_put,
)
from pytorch_distributed_nn_tpu.train.state import TrainState

DATA_AXES = (AXIS_DATA, AXIS_FSDP)


def forward(state: TrainState, params, x, *, train: bool,
            apply_kwargs: dict | None = None):
    """Run the model, threading mutable collections (BatchNorm stats) and
    a per-step dropout PRNG. Returns (logits, new_model_state, aux_losses)
    where ``aux_losses`` are scalars sown into the "losses" collection
    (MoE load-balance terms — parallel/expert.py) to be *added to the
    task loss*; they are never carried in model_state.

    ``apply_kwargs`` are forwarded to the model (e.g.
    ``return_hidden=True`` for the chunked-xent path, in which case the
    first return is the trunk hidden, not logits)."""
    variables = {"params": params, **state.model_state}
    extra = apply_kwargs or {}
    # deterministic per-step dropout stream seeded from the TrainState's
    # base key (cfg.seed); under jit-sharding the mask generation
    # partitions with the batch (threefry is partitionable)
    rngs = {"dropout": jax.random.fold_in(state.rng, state.step)}
    if train:
        logits, updated = state.apply_fn(
            variables, x, train=True,
            mutable=list(state.model_state) + ["losses"],
            rngs=rngs, **extra,
        )
        updated = dict(updated)
        aux = jax.tree.leaves(updated.pop("losses", {}))
        return logits, updated, aux
    logits = state.apply_fn(variables, x, train=train, **extra)
    return logits, state.model_state, []


def _loss_and_grads(state, x, y, loss_fn):
    """``loss_fn(out, y)`` by default. A loss_fn carrying the marker
    attributes set by api.make_chunked_loss gets the model output it
    asked for (``loss_fn.apply_kwargs``) plus the live params
    (``loss_fn.needs_params``) — the chunked-xent path needs the head
    kernel to project blockwise."""
    apply_kwargs = getattr(loss_fn, "apply_kwargs", None)
    needs_params = getattr(loss_fn, "needs_params", False)

    def compute(params):
        out, new_model_state, aux = forward(
            state, params, x, train=True, apply_kwargs=apply_kwargs
        )
        loss = (loss_fn(out, y, params) if needs_params
                else loss_fn(out, y))
        for term in aux:  # sown losses (MoE load balance)
            loss = loss + term
        return loss, new_model_state

    (loss, new_model_state), grads = jax.value_and_grad(
        compute, has_aux=True
    )(state.params)
    return loss, new_model_state, grads


def make_dp_train_step(mesh: Mesh, loss_fn: Callable, *, accum: int = 1):
    """Compiler-sharded DP step: ``(step, place_state)``.

    Sharding contract: TrainState replicated over the data axes (TP rules
    still shard over ``tensor`` when that axis is >1), batch sharded over
    data×fsdp. Gradients of a global-batch-mean loss w.r.t. replicated
    params make XLA emit exactly one all-reduce per parameter (fused and
    overlapped by the async-collective scheduler). Implemented as
    ZeRO-stage-0 — DP is the layout special case, not a separate code
    path. ``accum``: gradient-accumulation microbatches (see
    zero.make_zero_train_step).
    """
    from pytorch_distributed_nn_tpu.parallel import zero

    return zero.make_zero_train_step(mesh, loss_fn, stage=0, accum=accum)


def make_dp_train_step_explicit(
    mesh: Mesh,
    loss_fn: Callable,
    *,
    bucket_reduce: Callable | None = None,
    donate: bool = True,
):
    """Hand-rolled DP under shard_map (the reference's §3.2 path).

    ``bucket_reduce(grads_tree) -> grads_tree`` replaces the default
    per-tensor pmean when given — that's where the DDP-style bucket
    controller (ops/buckets.py) or quantized allreduce plugs in. It runs
    *inside* shard_map, so it may use any named-axis collective.
    """
    replicated = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, batch_pspec())

    if bucket_reduce is None:
        def bucket_reduce(grads, *, seed=0):
            return cc.tree_all_reduce_mean(grads, DATA_AXES)

    reduce_grads = bucket_reduce

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), batch_pspec(), batch_pspec()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def step(state: TrainState, x, y):
        # Decorrelate dropout masks across devices (single-device golden
        # equivalence for dropout>0 holds only for the compiler-sharded
        # path, where one global mask exists).
        dev = cc.axis_index(AXIS_DATA) * cc.axis_size(AXIS_FSDP) \
            + cc.axis_index(AXIS_FSDP)
        # fwd-only view: the per-device fold must not escape into the
        # (replicated) output state
        fwd_state = state.replace(rng=jax.random.fold_in(state.rng, dev))
        # Per-device microloss on the local shard; mean of per-device
        # means == global mean because shards are equal-sized. (For
        # token-weighted losses like masked_lm_xent this reproduces torch
        # DDP's per-rank-denominator semantics — reference parity — not
        # the exact global mean the compiler-sharded path computes.)
        loss, new_model_state, grads = _loss_and_grads(
            fwd_state, x, y, loss_fn
        )
        grads = reduce_grads(grads, seed=state.step)
        loss = cc.all_reduce_mean(loss, DATA_AXES)
        # model_state (BN stats) must agree across replicas: average like
        # grads (SyncBN semantics — torch DDP leaves them local, which
        # diverges; syncing is strictly more correct).
        new_model_state = cc.tree_all_reduce_mean(
            new_model_state, DATA_AXES
        ) if new_model_state else new_model_state
        new_state = state.apply_gradients(grads).replace(
            model_state=new_model_state
        )
        return new_state, {"loss": loss}

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Initial parameter broadcast — the reference's rank-0 ``broadcast``
    at DDP construction (SURVEY.md §3.1). SPMD form: place every leaf
    with a fully-replicated sharding."""
    return global_device_put(
        state, jax.tree.map(lambda _: NamedSharding(mesh, P()), state)
    )
