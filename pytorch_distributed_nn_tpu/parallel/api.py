"""Strategy dispatch: config → jit-compiled train step.

The reference selects a communication strategy by running a different
trainer script (SURVEY.md §1 Entrypoints row); here the strategy is a
config field and every strategy exposes the same contract:

    step(state, x, y) -> (state, metrics)      # jit-compiled over mesh

with TrainState sharded per the strategy (replicated for DP, parameter-
sharded for ZeRO, stage-sharded for pipeline).
"""

from __future__ import annotations

import logging
from typing import Callable

from jax.sharding import Mesh

from pytorch_distributed_nn_tpu.config import TrainConfig
from pytorch_distributed_nn_tpu.train.state import TrainState


def make_chunked_loss(chunk: int) -> Callable:
    """LM loss that never materializes (B, T, V) logits: the model
    returns trunk hidden (``apply_kwargs``), the head kernel is pulled
    from the live params (``needs_params``), and
    losses.chunked_lm_xent projects + cross-entropies per T-chunk
    (rematerialized in backward). See dp._loss_and_grads for the
    marker-attribute contract."""
    from pytorch_distributed_nn_tpu.train.losses import chunked_lm_xent

    def loss_fn(hidden, targets, params):
        kernel = params["lm_head"]["kernel"]
        return chunked_lm_xent(hidden, kernel, targets, chunk=chunk)

    loss_fn.needs_params = True
    loss_fn.apply_kwargs = {"return_hidden": True}
    return loss_fn


def make_train_step(
    cfg: TrainConfig, mesh: Mesh, loss_fn: Callable, model=None
) -> tuple[Callable, Callable[[TrainState], TrainState]]:
    """Returns ``(step_fn, place_state_fn)``: the compiled step and the
    function that lays the freshly-initialised TrainState out on the mesh
    (replication broadcast, ZeRO sharding, or stage split)."""
    from pytorch_distributed_nn_tpu.parallel import dp
    from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ

    strategy = cfg.parallel.strategy
    token_datasets = ("lm_synthetic", "token_file")
    if mesh.shape.get(AXIS_SEQ, 1) > 1:
        if cfg.data.dataset not in token_datasets:
            raise ValueError(
                "mesh.seq > 1 shards the sequence dim of (B, T) token "
                f"batches; dataset {cfg.data.dataset!r} has no sequence "
                "dim to shard"
            )
        if strategy not in ("single", "dp", "zero"):
            raise ValueError(
                "mesh.seq > 1 needs the compiler-sharded step (single/"
                f"dp/zero): ring attention's nested shard_map cannot "
                f"live inside strategy {strategy!r}"
            )
        if cfg.data.seq_len % mesh.shape[AXIS_SEQ]:
            # the loader would silently fall back to batch-only
            # sharding while zero's jit demands seq-sharded batches
            raise ValueError(
                f"seq_len {cfg.data.seq_len} not divisible by mesh.seq "
                f"{mesh.shape[AXIS_SEQ]}"
            )
        if cfg.model.extra.get("attn_impl") not in ("ring", "ulysses"):
            logging.getLogger(__name__).warning(
                "mesh.seq=%d but model.extra.attn_impl is not 'ring'/"
                "'ulysses': XLA will all-gather the sequence dim around "
                "attention instead of running the sequence-parallel "
                "schedule — correct but slow",
                mesh.shape[AXIS_SEQ],
            )
    if cfg.xent_chunk:
        if strategy not in ("single", "dp", "dp_explicit", "zero"):
            raise ValueError(
                f"xent_chunk is not supported under strategy "
                f"{strategy!r} (needs the shared dp/zero step)"
            )
        if cfg.data.dataset not in token_datasets:
            raise ValueError(
                "xent_chunk is a causal-LM loss option (datasets "
                f"{token_datasets}), got {cfg.data.dataset!r}"
            )
        if cfg.data.seq_len > cfg.xent_chunk:
            if cfg.data.seq_len % cfg.xent_chunk:
                raise ValueError(
                    f"seq_len {cfg.data.seq_len} not divisible by "
                    f"xent_chunk {cfg.xent_chunk} — the dense fallback "
                    "would defeat the memory bound"
                )
            if cfg.label_smoothing:
                raise ValueError(
                    "label_smoothing is not supported with xent_chunk "
                    "(the chunked loss computes plain nll blockwise)"
                )
            loss_fn = make_chunked_loss(cfg.xent_chunk)
        # else: the whole sequence fits in one chunk — the dense loss
        # (which does support label_smoothing) is already within the
        # chunked memory bound (scaled benches and dryruns shrink T
        # without editing xent_chunk)
    accum = cfg.parallel.grad_accum
    if accum < 1:
        raise ValueError(f"parallel.grad_accum must be >= 1, got {accum}")
    if accum > 1:
        if strategy not in ("single", "dp", "zero"):
            raise ValueError(
                f"grad_accum needs the compiler-sharded step (single/dp/"
                f"zero), got strategy {strategy!r} (pipeline microbatches "
                "its own schedule via parallel.microbatches)"
            )
        if cfg.data.batch_size % accum:
            raise ValueError(
                f"batch_size {cfg.data.batch_size} not divisible by "
                f"grad_accum {accum}"
            )
    if strategy in ("single", "dp"):
        if cfg.parallel.quantized_allreduce:
            logging.getLogger(__name__).warning(
                "quantized_allreduce requires strategy='dp_explicit' "
                "(the compiler-sharded 'dp' path owns its own collectives) "
                "— ignoring"
            )
        return dp.make_dp_train_step(mesh, loss_fn, accum=accum)
    if strategy == "dp_explicit":
        quant = cfg.parallel.quantized_allreduce
        if quant.lower() in ("true", "1", "yes", "on"):  # legacy bool flag
            quant = "bf16"
        bucket_mb = cfg.parallel.bucket_mb
        if bucket_mb <= 0 and quant:
            # quantization rides the bucket path; one giant bucket keeps
            # it active when bucketing is "off"
            bucket_mb = 1e9
        bucket_reduce = None
        if bucket_mb > 0:
            from pytorch_distributed_nn_tpu.ops.buckets import (
                make_bucket_reduce,
            )

            bucket_reduce = make_bucket_reduce(
                bucket_mb=bucket_mb,
                quantized=quant or False,
            )
        step = dp.make_dp_train_step_explicit(
            mesh, loss_fn, bucket_reduce=bucket_reduce
        )
        return step, lambda s: dp.replicate_state(s, mesh)
    if strategy == "zero":
        from pytorch_distributed_nn_tpu.parallel import zero

        return zero.make_zero_train_step(
            mesh, loss_fn, stage=cfg.parallel.zero_stage, accum=accum
        )
    if strategy == "pipeline":
        from pytorch_distributed_nn_tpu.parallel import pipeline

        if model is None:
            raise ValueError("pipeline strategy needs the model instance")
        return pipeline.make_pipeline_train_step(cfg, mesh, loss_fn, model)
    if strategy == "ps":
        raise ValueError(
            "the async parameter-server strategy is process-level, not a "
            "jit step — run scripts/train_ps.py (see parallel/ps.py)"
        )
    raise ValueError(f"unknown strategy {strategy!r}")
