"""Sequence / context parallelism for long sequences.

Absent from the reference (SURVEY.md §2c/§5 "Long-context" rows) but
first-class here per the task mandate. Two schemes over the ``seq`` mesh
axis, both exact (not approximations):

- :func:`ring_attention` — context parallelism: Q stays put, KV blocks
  rotate around the ICI ring via ``ppermute`` while a numerically-stable
  online-softmax accumulates (flash-attention math, blockwise over
  devices). O(T/s) memory per device; comm fully overlappable with the
  per-block matmuls. ``impl='pallas'`` fuses each block update into the
  ops/pallas/ring_attention kernel (the TPU path — scores never touch
  HBM; backward recomputes through the jnp schedule via custom_vjp);
  ``impl='xla'`` is the jnp reference and the CPU test path.

- :func:`ulysses_attention` — head-scatter: two ``all_to_all``s reshard
  seq↔heads around an ordinary full-sequence attention, so each device
  handles all T positions for H/s heads. Cheaper comm for moderate T;
  requires heads % seq-degree == 0.

Both run inside ``shard_map`` with activations sharded (B, T/s, H, D) on
the sequence dim.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_nn_tpu.ops import collectives as cc
from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ

_NEG_INF = -1e30


def ring_attention(q, k, v, *, axis: str = AXIS_SEQ, causal: bool = True,
                   impl: str = "auto"):
    """Exact blockwise attention with rotating KV. q,k,v: local shards
    (B, Tl, H, D) of a (B, T, H, D) sequence-sharded tensor; returns the
    local (B, Tl, H, D) output shard.

    impl: 'xla' (jnp blockwise math), 'pallas' (fused block kernel, TPU),
    'pallas_interpret' (the Pallas kernel under the interpreter — CPU
    correctness runs), or 'auto' (pallas on TPU, xla elsewhere).
    """
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"kv heads {k.shape[2]} must divide q heads {q.shape[2]}"
        )
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return _ring_attention_xla(q, k, v, axis=axis, causal=causal)
    if impl in ("pallas", "pallas_interpret"):
        return _ring_attention_fused(
            q, k, v, axis, causal, impl == "pallas_interpret"
        )
    raise ValueError(f"unknown ring attention impl {impl!r}")


def _ring_attention_xla(q, k, v, *, axis: str = AXIS_SEQ,
                        causal: bool = True):
    """jnp reference schedule — autodiff-friendly; also the recompute
    path for the fused kernel's backward."""
    s = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"kv heads {Hkv} must divide q heads {H}")
    # GQA: the ring rotates the GROUPED (Hkv) shards — expanding before
    # the ring would multiply every ppermute's ICI bytes by H/Hkv; each
    # visiting block is expanded locally at use instead.
    q_per_kv = H // Hkv
    scale = D ** -0.5
    qf = q.astype(jnp.float32)

    def expand(x):
        return jnp.repeat(x, q_per_kv, axis=2) if q_per_kv > 1 else x

    # global positions of my query rows
    q_pos = idx * Tl + lax.broadcasted_iota(jnp.int32, (Tl, 1), 0)

    def block_contrib(k_blk, v_blk, src_block, m, l, acc):
        k_blk, v_blk = expand(k_blk), expand(v_blk)
        logits = jnp.einsum(
            "bthd,bshd->bhts", qf, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            k_pos = src_block * Tl + lax.broadcasted_iota(
                jnp.int32, (1, Tl), 1
            )
            mask = q_pos >= k_pos  # (Tl, Tl) global causal
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1, keepdims=True)
        # corr: (B, H, Tq, 1) → (B, Tq, H, 1) to rescale acc (B, Tq, H, D)
        corr_t = corr.transpose(0, 2, 1, 3)
        acc_new = acc * corr_t + jnp.einsum(
            "bhts,bshd->bthd", p, v_blk.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        src_block = (idx - i) % s  # whose KV block we hold this round
        m, l, acc = block_contrib(k_blk, v_blk, src_block, m, l, acc)
        # rotate KV to the right neighbour for the next round
        k_blk = cc.shift_right(k_blk, axis)
        v_blk = cc.shift_right(v_blk, axis)
        return (k_blk, v_blk, m, l, acc), None

    m0 = jnp.full((B, H, Tl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    # fresh zeros are unvarying over the mesh; the scan carry becomes
    # device-varying after one block update, so mark the initials
    # varying up front or check_vma rejects the carry type change
    m0, l0, acc0 = (lax.pvary(t, axis) for t in (m0, l0, acc0))
    # s-1 rotate-after-use rounds in the scan, then the last held block
    # outside it: the final rotation's output is never read, so don't
    # pay its 2 ppermutes of full KV shards.
    (k, v, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(s - 1)
    )
    m, l, acc = block_contrib(k, v, (idx - (s - 1)) % s, m, l, acc)
    # l: (B, H, Tl, 1) → (B, Tl, H, 1)
    denom = l.transpose(0, 2, 1, 3)
    out = acc / jnp.maximum(denom, 1e-30)
    return out.astype(q.dtype)


def _ring_fused_impl(q, k, v, axis: str, causal: bool, interpret: bool):
    """Forward ring schedule with the fused Pallas block kernel
    (ops/pallas/ring_attention): same math as :func:`_ring_attention_xla`
    but each block update runs in one kernel, (BH, Tl, D) layout."""
    from pytorch_distributed_nn_tpu.ops.pallas.ring_attention import (
        STAT_LANES,
        ring_block_update,
    )

    s = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    q_per_kv = H // Hkv

    def to_bh(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, Tl, D)

    def expand_bh(x):  # (B*Hkv, Tl, D) → (B*H, Tl, D), local only
        if q_per_kv == 1:
            return x
        return jnp.repeat(x, q_per_kv, axis=0)

    # the ring carries GROUPED KV shards (see _ring_attention_xla);
    # expansion happens locally per visiting block
    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    m0 = jnp.full((B * H, Tl, STAT_LANES), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B * H, Tl, STAT_LANES), jnp.float32)
    acc0 = jnp.zeros((B * H, Tl, D), jnp.float32)
    # see _ring_attention_xla: initials must be device-varying for the
    # scan carry to type-check under check_vma
    m0, l0, acc0 = (lax.pvary(t, axis) for t in (m0, l0, acc0))

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        src_block = (idx - i) % s
        offs = jnp.stack([idx * Tl, src_block * Tl]).astype(jnp.int32)
        m, l, acc = ring_block_update(
            qb, expand_bh(k_blk), expand_bh(v_blk), m, l, acc, offs,
            causal=causal, interpret=interpret,
        )
        k_blk = cc.shift_right(k_blk, axis)
        v_blk = cc.shift_right(v_blk, axis)
        return (k_blk, v_blk, m, l, acc), None

    # As in _ring_attention_xla: last block handled outside the scan so
    # the never-read final rotation is not issued.
    (kb, vb, m, l, acc), _ = lax.scan(
        step, (kb, vb, m0, l0, acc0), jnp.arange(s - 1)
    )
    last = s - 1
    offs = jnp.stack(
        [idx * Tl, ((idx - last) % s) * Tl]
    ).astype(jnp.int32)
    m, l, acc = ring_block_update(
        qb, expand_bh(kb), expand_bh(vb), m, l, acc, offs,
        causal=causal, interpret=interpret,
    )
    out = acc / jnp.maximum(l[..., 0:1], 1e-30)
    return out.reshape(B, H, Tl, D).transpose(0, 2, 1, 3).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention_fused(q, k, v, axis, causal, interpret):
    return _ring_fused_impl(q, k, v, axis, causal, interpret)


def _ring_fused_fwd(q, k, v, axis, causal, interpret):
    return _ring_fused_impl(q, k, v, axis, causal, interpret), (q, k, v)


def _ring_fused_bwd(axis, causal, interpret, res, g):
    # flash-style recompute: rerun the (differentiable) jnp schedule and
    # pull its VJP — no (T, T) scores or per-block residuals ever stored
    q, k, v = res
    _, vjp = jax.vjp(
        lambda a, b, c: _ring_attention_xla(a, b, c, axis=axis,
                                            causal=causal),
        q, k, v,
    )
    return vjp(g.astype(q.dtype))


_ring_attention_fused.defvjp(_ring_fused_fwd, _ring_fused_bwd)


def ulysses_attention(q, k, v, *, axis: str = AXIS_SEQ,
                      causal: bool = True, impl: str = "auto"):
    """All-to-all head-scatter attention (DeepSpeed-Ulysses scheme,
    SURVEY.md §2c). Local shards (B, Tl, H, D) → full-seq per-head-group
    attention → back."""
    from pytorch_distributed_nn_tpu.nn.attention import (
        dot_product_attention,
    )

    s = lax.axis_size(axis)
    H = q.shape[2]
    Hkv = k.shape[2]
    if H % s or Hkv % s:
        raise ValueError(
            f"ulysses needs heads divisible by seq degree: {H}/{Hkv} vs {s}"
        )
    # (B, Tl, H, D) → (B, T, H/s, D): gather seq, scatter heads
    q = cc.all_to_all(q, axis, split_axis=2, concat_axis=1)
    k = cc.all_to_all(k, axis, split_axis=2, concat_axis=1)
    v = cc.all_to_all(v, axis, split_axis=2, concat_axis=1)
    out = dot_product_attention(q, k, v, causal=causal, impl=impl)
    # back: (B, T, H/s, D) → (B, Tl, H, D)
    return cc.all_to_all(out, axis, split_axis=1, concat_axis=2)
