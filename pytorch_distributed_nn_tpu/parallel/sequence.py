"""Sequence / context parallelism for long sequences.

Absent from the reference (SURVEY.md §2c/§5 "Long-context" rows) but
first-class here per the task mandate. Two schemes over the ``seq`` mesh
axis, both exact (not approximations):

- :func:`ring_attention` — context parallelism: Q stays put, KV blocks
  rotate around the ICI ring via ``ppermute`` while a numerically-stable
  online-softmax accumulates (flash-attention math, blockwise over
  devices). O(T/s) memory per device; comm fully overlappable with the
  per-block matmuls. The Pallas fused kernel (ops/pallas/ring_attention)
  shares this schedule; this jnp version is its reference and the CPU
  test path.

- :func:`ulysses_attention` — head-scatter: two ``all_to_all``s reshard
  seq↔heads around an ordinary full-sequence attention, so each device
  handles all T positions for H/s heads. Cheaper comm for moderate T;
  requires heads % seq-degree == 0.

Both run inside ``shard_map`` with activations sharded (B, T/s, H, D) on
the sequence dim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_nn_tpu.ops import collectives as cc
from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ

_NEG_INF = -1e30


def ring_attention(q, k, v, *, axis: str = AXIS_SEQ, causal: bool = True):
    """Exact blockwise attention with rotating KV. q,k,v: local shards
    (B, Tl, H, D) of a (B, T, H, D) sequence-sharded tensor; returns the
    local (B, Tl, H, D) output shard."""
    s = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    if H != Hkv:  # grouped-query: expand kv once, locally
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    scale = D ** -0.5
    qf = q.astype(jnp.float32)

    # global positions of my query rows
    q_pos = idx * Tl + lax.broadcasted_iota(jnp.int32, (Tl, 1), 0)

    def block_contrib(k_blk, v_blk, src_block, m, l, acc):
        logits = jnp.einsum(
            "bthd,bshd->bhts", qf, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            k_pos = src_block * Tl + lax.broadcasted_iota(
                jnp.int32, (1, Tl), 1
            )
            mask = q_pos >= k_pos  # (Tl, Tl) global causal
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1, keepdims=True)
        # corr: (B, H, Tq, 1) → (B, Tq, H, 1) to rescale acc (B, Tq, H, D)
        corr_t = corr.transpose(0, 2, 1, 3)
        acc_new = acc * corr_t + jnp.einsum(
            "bhts,bshd->bthd", p, v_blk.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        src_block = (idx - i) % s  # whose KV block we hold this round
        m, l, acc = block_contrib(k_blk, v_blk, src_block, m, l, acc)
        # rotate KV to the right neighbour for the next round
        k_blk = cc.shift_right(k_blk, axis)
        v_blk = cc.shift_right(v_blk, axis)
        return (k_blk, v_blk, m, l, acc), None

    m0 = jnp.full((B, H, Tl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    (k, v, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(s)
    )
    # l: (B, H, Tl, 1) → (B, Tl, H, 1)
    denom = l.transpose(0, 2, 1, 3)
    out = acc / jnp.maximum(denom, 1e-30)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis: str = AXIS_SEQ,
                      causal: bool = True, impl: str = "xla"):
    """All-to-all head-scatter attention (DeepSpeed-Ulysses scheme,
    SURVEY.md §2c). Local shards (B, Tl, H, D) → full-seq per-head-group
    attention → back."""
    from pytorch_distributed_nn_tpu.nn.attention import (
        dot_product_attention,
    )

    s = lax.axis_size(axis)
    H = q.shape[2]
    Hkv = k.shape[2]
    if H % s or Hkv % s:
        raise ValueError(
            f"ulysses needs heads divisible by seq degree: {H}/{Hkv} vs {s}"
        )
    # (B, Tl, H, D) → (B, T, H/s, D): gather seq, scatter heads
    q = cc.all_to_all(q, axis, split_axis=2, concat_axis=1)
    k = cc.all_to_all(k, axis, split_axis=2, concat_axis=1)
    v = cc.all_to_all(v, axis, split_axis=2, concat_axis=1)
    out = dot_product_attention(q, k, v, causal=causal, impl=impl)
    # back: (B, T, H/s, D) → (B, Tl, H, D)
    return cc.all_to_all(out, axis, split_axis=1, concat_axis=2)
