"""Sequence / context parallelism for long sequences.

Absent from the reference (SURVEY.md §2c/§5 "Long-context" rows) but
first-class here per the task mandate. Two schemes over the ``seq`` mesh
axis, both exact (not approximations):

- :func:`ring_attention` — context parallelism: Q stays put, KV blocks
  rotate around the ICI ring via ``ppermute`` while a numerically-stable
  online-softmax accumulates (flash-attention math, blockwise over
  devices). O(T/s) memory per device; comm fully overlappable with the
  per-block matmuls. ``impl='pallas'`` fuses each block update into the
  ops/pallas/ring_attention kernel, and the backward runs the flash
  two-pass Pallas kernels per ring step with f32 dk/dv accumulators
  riding the ring — scores never touch HBM in either direction;
  ``impl='xla'`` is the jnp reference and the CPU test path.

- :func:`ulysses_attention` — head-scatter: two ``all_to_all``s reshard
  seq↔heads around an ordinary full-sequence attention, so each device
  handles all T positions for H/s heads. Cheaper comm for moderate T;
  requires heads % seq-degree == 0.

Both run inside ``shard_map`` with activations sharded (B, T/s, H, D) on
the sequence dim.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_nn_tpu.ops import collectives as cc
from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ

_NEG_INF = -1e30


def ring_attention(q, k, v, *, axis: str = AXIS_SEQ, causal: bool = True,
                   impl: str = "auto"):
    """Exact blockwise attention with rotating KV. q,k,v: local shards
    (B, Tl, H, D) of a (B, T, H, D) sequence-sharded tensor; returns the
    local (B, Tl, H, D) output shard.

    impl: 'xla' (jnp blockwise math), 'pallas' (fused block kernel, TPU),
    'pallas_interpret' (the Pallas kernel under the interpreter — CPU
    correctness runs), or 'auto' (pallas on TPU, xla elsewhere).
    """
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"kv heads {k.shape[2]} must divide q heads {q.shape[2]}"
        )
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return _ring_attention_xla(q, k, v, axis=axis, causal=causal)
    if impl in ("pallas", "pallas_interpret"):
        return _ring_attention_fused(
            q, k, v, axis, causal, impl == "pallas_interpret"
        )
    raise ValueError(f"unknown ring attention impl {impl!r}")


def _ring_attention_xla(q, k, v, *, axis: str = AXIS_SEQ,
                        causal: bool = True):
    """jnp reference schedule — autodiff-friendly; also the recompute
    path for the fused kernel's backward."""
    s = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"kv heads {Hkv} must divide q heads {H}")
    # GQA: the ring rotates the GROUPED (Hkv) shards — expanding before
    # the ring would multiply every ppermute's ICI bytes by H/Hkv; each
    # visiting block is expanded locally at use instead.
    q_per_kv = H // Hkv
    scale = D ** -0.5
    qf = q.astype(jnp.float32)

    def expand(x):
        return jnp.repeat(x, q_per_kv, axis=2) if q_per_kv > 1 else x

    # global positions of my query rows
    q_pos = idx * Tl + lax.broadcasted_iota(jnp.int32, (Tl, 1), 0)

    def block_contrib(k_blk, v_blk, src_block, m, l, acc):
        k_blk, v_blk = expand(k_blk), expand(v_blk)
        logits = jnp.einsum(
            "bthd,bshd->bhts", qf, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            k_pos = src_block * Tl + lax.broadcasted_iota(
                jnp.int32, (1, Tl), 1
            )
            mask = q_pos >= k_pos  # (Tl, Tl) global causal
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1, keepdims=True)
        # corr: (B, H, Tq, 1) → (B, Tq, H, 1) to rescale acc (B, Tq, H, D)
        corr_t = corr.transpose(0, 2, 1, 3)
        acc_new = acc * corr_t + jnp.einsum(
            "bhts,bshd->bthd", p, v_blk.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        src_block = (idx - i) % s  # whose KV block we hold this round
        m, l, acc = block_contrib(k_blk, v_blk, src_block, m, l, acc)
        # rotate KV to the right neighbour for the next round
        k_blk = cc.shift_right(k_blk, axis)
        v_blk = cc.shift_right(v_blk, axis)
        return (k_blk, v_blk, m, l, acc), None

    m0 = jnp.full((B, H, Tl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    # fresh zeros are unvarying over the mesh; the scan carry becomes
    # device-varying after one block update, so mark the initials
    # varying up front or check_vma rejects the carry type change
    m0, l0, acc0 = (lax.pcast(t, axis, to='varying') for t in (m0, l0, acc0))
    # s-1 rotate-after-use rounds in the scan, then the last held block
    # outside it: the final rotation's output is never read, so don't
    # pay its 2 ppermutes of full KV shards.
    (k, v, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(s - 1)
    )
    m, l, acc = block_contrib(k, v, (idx - (s - 1)) % s, m, l, acc)
    # l: (B, H, Tl, 1) → (B, Tl, H, 1)
    denom = l.transpose(0, 2, 1, 3)
    out = acc / jnp.maximum(denom, 1e-30)
    return out.astype(q.dtype)


def _ring_fused_impl(q, k, v, axis: str, causal: bool, interpret: bool):
    """Forward ring schedule with the fused Pallas block kernel
    (ops/pallas/ring_attention): same math as :func:`_ring_attention_xla`
    but each block update runs in one kernel, (BH, Tl, D) layout.
    Returns (out, lse) — the per-row logsumexp is the softmax stat the
    Pallas ring backward replays p from."""
    from pytorch_distributed_nn_tpu.ops.pallas.ring_attention import (
        STAT_LANES,
        ring_block_update,
    )

    s = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    q_per_kv = H // Hkv

    def to_bh(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, Tl, D)

    def expand_bh(x):  # (B*Hkv, Tl, D) → (B*H, Tl, D), local only
        if q_per_kv == 1:
            return x
        return jnp.repeat(x, q_per_kv, axis=0)

    # the ring carries GROUPED KV shards (see _ring_attention_xla);
    # expansion happens locally per visiting block
    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    m0 = jnp.full((B * H, Tl, STAT_LANES), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B * H, Tl, STAT_LANES), jnp.float32)
    acc0 = jnp.zeros((B * H, Tl, D), jnp.float32)
    # see _ring_attention_xla: initials must be device-varying for the
    # scan carry to type-check under check_vma
    m0, l0, acc0 = (lax.pcast(t, axis, to='varying') for t in (m0, l0, acc0))

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        src_block = (idx - i) % s
        offs = jnp.stack([idx * Tl, src_block * Tl]).astype(jnp.int32)
        m, l, acc = ring_block_update(
            qb, expand_bh(k_blk), expand_bh(v_blk), m, l, acc, offs,
            causal=causal, interpret=interpret,
        )
        k_blk = cc.shift_right(k_blk, axis)
        v_blk = cc.shift_right(v_blk, axis)
        return (k_blk, v_blk, m, l, acc), None

    # As in _ring_attention_xla: last block handled outside the scan so
    # the never-read final rotation is not issued.
    (kb, vb, m, l, acc), _ = lax.scan(
        step, (kb, vb, m0, l0, acc0), jnp.arange(s - 1)
    )
    last = s - 1
    offs = jnp.stack(
        [idx * Tl, ((idx - last) % s) * Tl]
    ).astype(jnp.int32)
    m, l, acc = ring_block_update(
        qb, expand_bh(kb), expand_bh(vb), m, l, acc, offs,
        causal=causal, interpret=interpret,
    )
    l0c = jnp.maximum(l[..., 0:1], 1e-30)
    out = acc / l0c
    lse = m[..., 0] + jnp.log(l0c[..., 0])  # (BH, Tl) f32
    out = out.reshape(B, H, Tl, D).transpose(0, 2, 1, 3).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention_fused(q, k, v, axis, causal, interpret):
    return _ring_fused_impl(q, k, v, axis, causal, interpret)[0]


def _ring_fused_fwd(q, k, v, axis, causal, interpret):
    out, lse = _ring_fused_impl(q, k, v, axis, causal, interpret)
    return out, (q, k, v, out, lse)


def _ring_fused_bwd(axis, causal, interpret, res, g):
    """Pallas ring backward: dk/dv accumulators ride the KV ring.

    Every ring step pairs the local Q shard with the visiting KV shard;
    under global causality that pair is one of exactly three flavors —
    the diagonal (src == idx: ordinary causal self-attention geometry),
    the past (src < idx: dense, no mask), or the future (src > idx:
    zero gradient). The first two are precisely what the flash
    two-pass backward kernels already compute, with p replayed from the
    forward's saved lse — so each step dispatches those kernels instead
    of re-running the jnp schedule, and no (Tl, Tl) score block ever
    reaches HBM in either direction (VERDICT.md round-1 Weak #3).

    Gradients accumulate in f32: dq stays resident with Q; dk/dv travel
    one hop behind their KV block and take a final ppermute home.
    """
    q, k, v, out, lse = res
    from pytorch_distributed_nn_tpu.ops.pallas.flash_attention import (
        _flash_bwd_pallas,
        _pick_block,
    )

    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    on_tpu = jax.default_backend() == "tpu"
    bq = _pick_block(Tl, min(512, Tl))
    bk = _pick_block(Tl, min(512, Tl))
    if bq is None or bk is None or not (on_tpu or interpret):
        # no viable block tiling (tiny shards) or CPU without interpret:
        # recompute through the differentiable jnp schedule
        _, vjp = jax.vjp(
            lambda a, b, c: _ring_attention_xla(a, b, c, axis=axis,
                                                causal=causal),
            q, k, v,
        )
        return vjp(g.astype(q.dtype))

    s = lax.axis_size(axis)
    idx = lax.axis_index(axis)

    def to_bh(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, Tl, D)

    qb, gb, outb = to_bh(q), to_bh(g.astype(q.dtype)), to_bh(out)
    kb, vb = to_bh(k), to_bh(v)  # grouped (B*Hkv, Tl, D) — never expanded
    delta = jnp.sum(outb.astype(jnp.float32) * gb.astype(jnp.float32), -1)
    nq = Tl // bq
    lse_r = lse.reshape(B * H, nq, bq)
    delta_r = delta.reshape(B * H, nq, bq)
    interp = bool(interpret and not on_tpu)

    def pair_bwd(kv, pair_causal):
        return _flash_bwd_pallas(
            qb, kv[0], kv[1], gb, lse_r, delta_r, causal=pair_causal,
            block_q=bq, block_k=bk, out_dtype=jnp.float32,
            interpret=interp,
        )

    def contrib(k_blk, v_blk, src):
        if not causal:
            return pair_bwd((k_blk, v_blk), False)

        def future(kv):
            zq = jnp.zeros((B * H, Tl, D), jnp.float32)
            zkv = jnp.zeros((B * Hkv, Tl, D), jnp.float32)
            return tuple(lax.pcast(t, axis, to='varying') for t in (zq, zkv, zkv))

        return lax.cond(
            src == idx,
            lambda kv: pair_bwd(kv, True),
            lambda kv: lax.cond(src < idx,
                                lambda kv2: pair_bwd(kv2, False),
                                future, kv),
            (k_blk, v_blk),
        )

    def step(carry, i):
        k_blk, v_blk, dk, dv, dq = carry
        src = (idx - i) % s
        dqc, dkc, dvc = contrib(k_blk, v_blk, src)
        dq, dk, dv = dq + dqc, dk + dkc, dv + dvc
        k_blk = cc.shift_right(k_blk, axis)
        v_blk = cc.shift_right(v_blk, axis)
        dk = cc.shift_right(dk, axis)  # accumulators follow their block
        dv = cc.shift_right(dv, axis)
        return (k_blk, v_blk, dk, dv, dq), None

    dq0 = jnp.zeros((B * H, Tl, D), jnp.float32)
    dk0 = jnp.zeros((B * Hkv, Tl, D), jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    dq0, dk0, dv0 = (lax.pcast(t, axis, to='varying') for t in (dq0, dk0, dv0))
    (kb, vb, dk, dv, dq), _ = lax.scan(
        step, (kb, vb, dk0, dv0, dq0), jnp.arange(s - 1)
    )
    # last round outside the scan: KV needs no further rotation, but the
    # visiting block's accumulators are one hop from home
    dqc, dkc, dvc = contrib(kb, vb, (idx - (s - 1)) % s)
    dq = dq + dqc
    dk = cc.shift_right(dk + dkc, axis)
    dv = cc.shift_right(dv + dvc, axis)

    def from_bh(x, h, dtype):
        return x.reshape(B, h, Tl, D).transpose(0, 2, 1, 3).astype(dtype)

    return (from_bh(dq, H, q.dtype), from_bh(dk, Hkv, k.dtype),
            from_bh(dv, Hkv, v.dtype))


_ring_attention_fused.defvjp(_ring_fused_fwd, _ring_fused_bwd)


def ulysses_attention(q, k, v, *, axis: str = AXIS_SEQ,
                      causal: bool = True, impl: str = "auto"):
    """All-to-all head-scatter attention (DeepSpeed-Ulysses scheme,
    SURVEY.md §2c). Local shards (B, Tl, H, D) → full-seq per-head-group
    attention → back."""
    from pytorch_distributed_nn_tpu.nn.attention import (
        dot_product_attention,
    )

    s = lax.axis_size(axis)
    H = q.shape[2]
    Hkv = k.shape[2]
    if H % s or Hkv % s:
        raise ValueError(
            f"ulysses needs heads divisible by seq degree: {H}/{Hkv} vs {s}"
        )
    # (B, Tl, H, D) → (B, T, H/s, D): gather seq, scatter heads
    q = cc.all_to_all(q, axis, split_axis=2, concat_axis=1)
    k = cc.all_to_all(k, axis, split_axis=2, concat_axis=1)
    v = cc.all_to_all(v, axis, split_axis=2, concat_axis=1)
    # inside this shard_map the seq axis is manual (H already divided by
    # s) but the batch dim is still the global trace size over the auto
    # data/fsdp axes — so the 'auto' occupancy rule must divide rows by
    # the NON-seq mesh factor only, not the full device_count (which
    # would double-count s) and not 1 (which would overcount occupancy
    # by the data*fsdp factor on a pod)
    out = dot_product_attention(
        q, k, v, causal=causal, impl=impl,
        device_count=max(jax.device_count() // s, 1))
    # back: (B, T, H/s, D) → (B, Tl, H, D)
    return cc.all_to_all(out, axis, split_axis=1, concat_axis=2)
