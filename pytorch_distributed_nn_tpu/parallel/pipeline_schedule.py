"""Pipeline schedules as static per-tick tables.

The reference hand-schedules its pipeline with blocking send/recv pairs
per rank (SURVEY.md §3.3). In an SPMD world every stage executes the
same traced program, so a schedule is DATA, not control flow: a table
``(ticks, stages)`` saying which microbatch each stage's forward and
backward units process at each global tick (or NO_OP). The tick body
masks its (always-traced) units with the table entries, and the
cross-stage ``ppermute``s run unconditionally — collectives never sit
inside divergent control flow.

Two schedules:

- ``gpipe`` — all forwards, then (via AD transpose) all backwards;
  built directly in ``parallel/pipeline.py``. In-flight activations
  grow with the microbatch count M.
- ``1f1b`` (PipeDream-flush) — built here in closed form:

      fwd[t, s] = t - s              (while 0 <= t - s < M)
      bwd[t, s] = t - (2S - 1 - s)   (while in range)

  Stage s runs its f-th forward at tick s + f and its b-th backward at
  tick 2S - 1 - s + b. With one-tick message latency both dependency
  chains are tight (producer always exactly one tick ahead), so the
  steady state runs one forward AND one backward every tick with zero
  relay gaps: M + 2S - 1 total ticks. In-flight activations are
  bounded by 2(S - s) - 1 <= 2S - 1 per stage — the stage DEPTH, not
  the microbatch count, which is the entire point of the schedule
  (VERDICT.md round-1 Missing #4).

Tables are built in plain Python at trace time (S and M are static)
and closed over by the jitted step; device-side cost is a gather per
tick.
"""

from __future__ import annotations

import dataclasses

import numpy as np

NO_OP = -1  # table entry: no microbatch scheduled for this unit


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Static pipeline schedule. ``fwd``/``bwd`` are (ticks, stages)
    int32; entry [t, s] is the microbatch stage s processes at tick t
    for that unit, or NO_OP."""

    n_stages: int
    n_micro: int
    fwd: np.ndarray
    bwd: np.ndarray
    max_in_flight: int  # activation ring-buffer depth any stage needs

    @property
    def n_ticks(self) -> int:
        return self.fwd.shape[0]


def one_f_one_b(n_stages: int, n_micro: int) -> Schedule:
    """The closed-form PipeDream-flush table (module docstring)."""
    S, M = n_stages, n_micro
    if S < 1 or M < 1:
        raise ValueError(f"need n_stages >= 1, n_micro >= 1; got {S}, {M}")
    n_ticks = M + 2 * S - 1
    t = np.arange(n_ticks)[:, None]
    s = np.arange(S)[None, :]
    fwd = t - s
    bwd = t - (2 * S - 1 - s)
    fwd = np.where((fwd >= 0) & (fwd < M), fwd, NO_OP).astype(np.int32)
    bwd = np.where((bwd >= 0) & (bwd < M), bwd, NO_OP).astype(np.int32)
    # stage s holds microbatch f from fwd tick s+f until bwd tick
    # 2S-1-s+f: at most 2(S-s)-1 in flight; stage 0 peaks
    max_in_flight = min(M, 2 * S - 1)
    return Schedule(n_stages=S, n_micro=M, fwd=fwd, bwd=bwd,
                    max_in_flight=max_in_flight)
