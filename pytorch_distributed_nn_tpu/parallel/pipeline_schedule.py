"""Pipeline schedules as static per-tick tables.

The reference hand-schedules its pipeline with blocking send/recv pairs
per rank (SURVEY.md §3.3). In an SPMD world every stage executes the
same traced program, so a schedule is DATA, not control flow: a table
``(ticks, stages)`` saying which microbatch each stage's forward and
backward units process at each global tick (or NO_OP). The tick body
masks its (always-traced) units with the table entries, and the
cross-stage ``ppermute``s run unconditionally — collectives never sit
inside divergent control flow.

Two schedules:

- ``gpipe`` — all forwards, then (via AD transpose) all backwards;
  built directly in ``parallel/pipeline.py``. In-flight activations
  grow with the microbatch count M.
- ``1f1b`` (PipeDream-flush) — built here in closed form:

      fwd[t, s] = t - s              (while 0 <= t - s < M)
      bwd[t, s] = t - (2S - 1 - s)   (while in range)

  Stage s runs its f-th forward at tick s + f and its b-th backward at
  tick 2S - 1 - s + b. With one-tick message latency both dependency
  chains are tight (producer always exactly one tick ahead), so the
  steady state runs one forward AND one backward every tick with zero
  relay gaps: M + 2S - 1 total ticks. In-flight activations are
  bounded by 2(S - s) - 1 <= 2S - 1 per stage — the stage DEPTH, not
  the microbatch count, which is the entire point of the schedule
  (VERDICT.md round-1 Missing #4).

Tables are built in plain Python at trace time (S and M are static)
and closed over by the jitted step; device-side cost is a gather per
tick.
"""

from __future__ import annotations

import dataclasses

import numpy as np

NO_OP = -1  # table entry: no microbatch scheduled for this unit


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Static pipeline schedule. ``fwd``/``bwd`` are (ticks, stages)
    int32; entry [t, s] is the microbatch stage s processes at tick t
    for that unit, or NO_OP."""

    n_stages: int
    n_micro: int
    fwd: np.ndarray
    bwd: np.ndarray
    max_in_flight: int  # activation ring-buffer depth any stage needs

    @property
    def n_ticks(self) -> int:
        return self.fwd.shape[0]


@dataclasses.dataclass(frozen=True)
class InterleavedSchedule:
    """Static interleaved (virtual-chunk) 1F1B schedule.

    ``S`` devices each hold ``v`` chunks; virtual stage ``k`` lives on
    device ``k % S`` as its chunk ``k // S`` (round-robin — the
    ``k % S == S-1 -> device 0`` wrap edge is where the interleaving
    lives, so both ppermutes are FULL rings). All tables are
    ``(ticks, S)`` int32 with NO_OP for dead slots:

    - ``fwd_chunk``/``fwd_mb`` — which (local chunk, microbatch) the
      forward unit runs; ``bwd_chunk``/``bwd_mb`` likewise;
    - ``act_write``/``act_read`` — activation-buffer slot the forward
      saves its input to / the backward re-linearizes from;
    - ``fin_write``/``fin_read`` — fwd-inbox slot the arriving
      ppermute message lands in / the forward unit consumes from
      (unlike plain 1F1B, grouped warmup makes consume tick > arrival
      tick, so messages queue; depths are schedule-static);
    - ``bin_write``/``bin_read`` — same for backward cotangents.

    Slot lifetimes honor the traced tick-body order: inbox writes
    happen BEFORE unit reads (same-tick passthrough), the backward's
    act read happens BEFORE the forward's act write (tight reuse).
    """

    n_stages: int
    n_chunks: int
    n_micro: int
    fwd_chunk: np.ndarray
    fwd_mb: np.ndarray
    bwd_chunk: np.ndarray
    bwd_mb: np.ndarray
    act_write: np.ndarray
    act_read: np.ndarray
    fin_write: np.ndarray
    fin_read: np.ndarray
    bin_write: np.ndarray
    bin_read: np.ndarray
    act_depth: int
    fin_depth: int
    bin_depth: int

    @property
    def n_ticks(self) -> int:
        return self.fwd_chunk.shape[0]


class _SlotAllocator:
    """Greedy interval slot assignment. ``free_at_read=True`` frees a
    slot for same-tick rewrites (act buffer: read-before-write);
    ``False`` keeps it busy through the read tick (inboxes:
    write-before-read)."""

    def __init__(self, free_at_read: bool) -> None:
        self._free_at_read = free_at_read
        self._busy: list[tuple[int, int]] = []  # per slot: (start, end)

    def alloc(self, start: int, end: int) -> int:
        for slot, (_, prev_end) in enumerate(self._busy):
            limit = prev_end if self._free_at_read else prev_end + 1
            if start >= limit:
                self._busy[slot] = (start, end)
                return slot
        self._busy.append((start, end))
        return len(self._busy) - 1

    @property
    def depth(self) -> int:
        return len(self._busy)


def interleaved_1f1b(n_stages: int, n_chunks: int,
                     n_micro: int) -> InterleavedSchedule:
    """Build the interleaved schedule by simulating Megatron's grouped
    unit order under the one-tick ppermute latency.

    Per device ``d`` the unit order is Megatron's
    (``forward_backward_pipelining_with_interleaving``): ``w`` warmup
    forwards with ``w = 2(S-d-1) + (v-1)S``, then strict 1F1B pairs,
    then cooldown backwards. The i-th forward processes chunk
    ``(i % Sv) // S`` of microbatch ``(i // Sv)*S + i % S`` (groups of
    S microbatches sweep the chunks in order); backwards sweep chunks
    in reverse. A depth-first greedy order was tried in round 2 and
    REVERTED — it schedules worse than plain 1F1B (docs/design.md).

    The simulation walks ticks; each device executes the prefix of its
    remaining unit list whose dependencies (producer ran at an earlier
    tick) are met, at most one forward + one backward per tick, in
    list order (blocking-recv semantics). The tables then get slot
    assignments for every message/activation lifetime. M must divide
    by S (Megatron's own constraint — partial groups stall the ring).
    """
    S, v, M = n_stages, n_chunks, n_micro
    if S < 1 or v < 1 or M < 1:
        raise ValueError(f"need S, v, M >= 1; got {S}, {v}, {M}")
    if M % S:
        raise ValueError(
            f"interleaved 1F1B needs microbatches divisible by stages "
            f"(Megatron group structure); got M={M}, S={S}"
        )
    Sv = S * v
    n = v * M  # units of each kind per device

    def fwd_unit(i: int) -> tuple[int, int]:
        g, r = divmod(i, Sv)
        return r // S, g * S + r % S  # (chunk, microbatch)

    def bwd_unit(i: int) -> tuple[int, int]:
        g, r = divmod(i, Sv)
        return v - 1 - r // S, g * S + r % S

    units: list[list[tuple[str, int, int]]] = []
    for d in range(S):
        w = min(2 * (S - d - 1) + (v - 1) * S, n)
        order = [("F", *fwd_unit(i)) for i in range(w)]
        for f, b in zip(range(w, n), range(n)):
            order.append(("F", *fwd_unit(f)))
            order.append(("B", *bwd_unit(b)))
        done_b = max(n - w, 0)
        order += [("B", *bwd_unit(i)) for i in range(done_b, n)]
        assert len(order) == 2 * n
        units.append(order)

    fwd_done: dict[tuple[int, int], int] = {}  # (virtual stage, mb) -> tick
    bwd_done: dict[tuple[int, int], int] = {}
    ptr = [0] * S
    rows_fc, rows_fm, rows_bc, rows_bm = [], [], [], []
    t = 0
    max_ticks = 4 * (2 * n + 2 * S * v) + 16  # deadlock tripwire
    while any(p < 2 * n for p in ptr):
        if t > max_ticks:
            raise RuntimeError(
                f"interleaved schedule deadlocked (S={S}, v={v}, M={M})"
            )
        row_fc, row_fm = [NO_OP] * S, [NO_OP] * S
        row_bc, row_bm = [NO_OP] * S, [NO_OP] * S
        for d in range(S):
            did = {"F": False, "B": False}
            while ptr[d] < 2 * n:
                typ, j, m = units[d][ptr[d]]
                if did[typ]:
                    break
                k = j * S + d
                if typ == "F":
                    ready = k == 0 or fwd_done.get((k - 1, m), t) < t
                else:
                    # every backward re-linearizes from its own saved
                    # forward input, so own-F must be a strict tick
                    # earlier (act read precedes act write in-body);
                    # non-last stages also need the cotangent
                    ready = fwd_done.get((k, m), t) < t and (
                        k == Sv - 1 or bwd_done.get((k + 1, m), t) < t
                    )
                if not ready:
                    break
                if typ == "F":
                    row_fc[d], row_fm[d] = j, m
                    fwd_done[(k, m)] = t
                else:
                    row_bc[d], row_bm[d] = j, m
                    bwd_done[(k, m)] = t
                did[typ] = True
                ptr[d] += 1
        rows_fc.append(row_fc)
        rows_fm.append(row_fm)
        rows_bc.append(row_bc)
        rows_bm.append(row_bm)
        t += 1

    T = len(rows_fc)
    fwd_chunk = np.asarray(rows_fc, np.int32)
    fwd_mb = np.asarray(rows_fm, np.int32)
    bwd_chunk = np.asarray(rows_bc, np.int32)
    bwd_mb = np.asarray(rows_bm, np.int32)

    # ---- slot assignment post-pass (all lifetimes are now known) ----
    act_write = np.full((T, S), NO_OP, np.int32)
    act_read = np.full((T, S), NO_OP, np.int32)
    fin_write = np.full((T, S), NO_OP, np.int32)
    fin_read = np.full((T, S), NO_OP, np.int32)
    bin_write = np.full((T, S), NO_OP, np.int32)
    bin_read = np.full((T, S), NO_OP, np.int32)
    act_depth = fin_depth = bin_depth = 1
    for d in range(S):
        acts = _SlotAllocator(free_at_read=True)
        fins = _SlotAllocator(free_at_read=False)
        bins_ = _SlotAllocator(free_at_read=False)
        # chronological allocation per device: walk ticks, allocate at
        # each lifetime's start
        for t in range(T):
            # arriving fwd message: sent by (d-1)%S's forward at t-1
            # for virtual stage k-1 -> consumed by this device's F of
            # (k, m); garbage (dead producer / last-stage output) is
            # dropped (stays NO_OP)
            p = (d - 1) % S
            if t > 0 and fwd_chunk[t - 1, p] != NO_OP:
                kp = fwd_chunk[t - 1, p] * S + p
                m = int(fwd_mb[t - 1, p])
                if kp < Sv - 1:
                    t_cons = fwd_done[(kp + 1, m)]
                    slot = fins.alloc(t, t_cons)
                    fin_write[t, d] = slot
                    fin_read[t_cons, d] = slot
            # arriving bwd cotangent: sent by (d+1)%S's backward at t-1
            # for virtual stage k -> consumed by this device's B of
            # (k-1, m)
            p = (d + 1) % S
            if t > 0 and bwd_chunk[t - 1, p] != NO_OP:
                kp = bwd_chunk[t - 1, p] * S + p
                m = int(bwd_mb[t - 1, p])
                if kp > 0:
                    t_cons = bwd_done[(kp - 1, m)]
                    slot = bins_.alloc(t, t_cons)
                    bin_write[t, d] = slot
                    bin_read[t_cons, d] = slot
            # saved forward input: written by F at t, read by the same
            # (k, m)'s B on this device
            if fwd_chunk[t, d] != NO_OP:
                k = fwd_chunk[t, d] * S + d
                m = int(fwd_mb[t, d])
                t_b = bwd_done[(k, m)]
                slot = acts.alloc(t, t_b)
                act_write[t, d] = slot
                act_read[t_b, d] = slot
        act_depth = max(act_depth, acts.depth)
        fin_depth = max(fin_depth, fins.depth)
        bin_depth = max(bin_depth, bins_.depth)

    return InterleavedSchedule(
        n_stages=S, n_chunks=v, n_micro=M,
        fwd_chunk=fwd_chunk, fwd_mb=fwd_mb,
        bwd_chunk=bwd_chunk, bwd_mb=bwd_mb,
        act_write=act_write, act_read=act_read,
        fin_write=fin_write, fin_read=fin_read,
        bin_write=bin_write, bin_read=bin_read,
        act_depth=act_depth, fin_depth=fin_depth, bin_depth=bin_depth,
    )


def one_f_one_b(n_stages: int, n_micro: int) -> Schedule:
    """The closed-form PipeDream-flush table (module docstring)."""
    S, M = n_stages, n_micro
    if S < 1 or M < 1:
        raise ValueError(f"need n_stages >= 1, n_micro >= 1; got {S}, {M}")
    n_ticks = M + 2 * S - 1
    t = np.arange(n_ticks)[:, None]
    s = np.arange(S)[None, :]
    fwd = t - s
    bwd = t - (2 * S - 1 - s)
    fwd = np.where((fwd >= 0) & (fwd < M), fwd, NO_OP).astype(np.int32)
    bwd = np.where((bwd >= 0) & (bwd < M), bwd, NO_OP).astype(np.int32)
    # stage s holds microbatch f from fwd tick s+f until bwd tick
    # 2S-1-s+f: at most 2(S-s)-1 in flight; stage 0 peaks
    max_in_flight = min(M, 2 * S - 1)
    return Schedule(n_stages=S, n_micro=M, fwd=fwd, bwd=bwd,
                    max_in_flight=max_in_flight)
