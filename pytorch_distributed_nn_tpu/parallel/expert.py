"""Expert parallelism (MoE) — the EP row of SURVEY.md §2c.

The reference has no MoE support; the task mandates the complete
parallelism inventory, so expert parallelism is first-class here. The
design is the GShard/Switch capacity-based formulation, which is the
TPU-idiomatic one:

- **Routing as einsums, not gather/scatter.** Token→expert assignment is
  expressed with dense one-hot ``dispatch``/``combine`` tensors and
  ``einsum`` contractions. Every op is a static-shape matmul — it lands
  on the MXU and XLA can fuse/partition it; there is no data-dependent
  control flow anywhere (SURVEY's "no dynamic shapes under jit" rule).
- **EP as a layout, not a protocol.** Expert weights are stacked
  ``(E, d, ff)`` and sharded over the ``expert`` mesh axis by
  :mod:`~pytorch_distributed_nn_tpu.parallel.sharding_rules`; tokens stay
  sharded over the data axes. XLA's SPMD partitioner then inserts the
  token all-to-all (dispatch) and its reverse (combine) over ICI — the
  same way the ZeRO strategy gets its all-gather/reduce-scatter for free
  (parallel/zero.py). The explicit ``shard_map`` form of the dispatch is
  :func:`ep_dispatch` / :func:`ep_combine`, the pedagogical analogue of
  ``dp_explicit``.
- **Capacity, not queues.** Each expert processes a fixed ``capacity``
  of tokens per step; overflow tokens are dropped (their combine weight
  is zero, so they pass through the residual unchanged) — the standard
  static-shape trade the Switch/GShard papers make.

The auxiliary load-balance loss is sown into the ``"losses"`` collection;
the shared train-step path (parallel/dp.py ``forward``) collects and adds
it to the task loss.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.ops import collectives as cc
from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_EXPERT


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Routing:
    """Result of :func:`top_k_routing` for one group of N tokens."""

    dispatch: jnp.ndarray  # (N, E, C) 0/1 — token n → slot c of expert e
    combine: jnp.ndarray  # (N, E, C) float — gate weights for the return trip
    aux_loss: jnp.ndarray  # scalar load-balance loss (Switch formulation)
    fraction_dropped: jnp.ndarray  # scalar, tokens over capacity


def expert_capacity(num_tokens: int, num_experts: int, k: int,
                    capacity_factor: float) -> int:
    """Static per-expert slot count: ceil(k·N/E · factor), floored at 1."""
    return max(1, math.ceil(num_tokens * k * capacity_factor / num_experts))


def top_k_routing(router_logits: jnp.ndarray, *, k: int,
                  capacity: int) -> Routing:
    """Capacity-based top-k routing (GShard §3.2 scheme, vectorised).

    ``router_logits``: (N, E) float32. Tokens claim expert slots in token
    order (position-in-expert via cumulative sum); a token whose chosen
    expert is already at capacity is dropped for that expert. Gates are
    the softmax probabilities of the chosen experts, renormalised over
    the k choices (Mixtral convention) *before* capacity dropping.
    """
    N, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # (N, k) each
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9
    )

    # one-hot expert choice per (choice, token): (k, N, E)
    choice_mask = jax.nn.one_hot(expert_idx.T, E, dtype=jnp.float32)

    # Position of each (choice, token) in its expert's queue. Choices are
    # ranked choice-major then token-major: all first choices claim slots
    # before any second choice (GShard's priority rule), so within one
    # choice level positions are a per-token cumsum, offset by every
    # earlier level's total claim count.
    pos_within = jnp.cumsum(choice_mask, axis=1) - choice_mask  # (k, N, E)
    prior_counts = jnp.cumsum(choice_mask.sum(axis=1), axis=0) \
        - choice_mask.sum(axis=1)  # (k, E): claims from earlier levels
    position = pos_within + prior_counts[:, None, :]  # (k, N, E)
    position = (position * choice_mask).sum(-1)  # (k, N) scalar slot idx

    fits = position < capacity  # (k, N)
    kept = fits.T * (gate_vals > 0)  # (N, k)

    # combine[n, e, c] = gate weight of token n at slot c of expert e
    slot_onehot = jax.nn.one_hot(position.T.astype(jnp.int32), capacity,
                                 dtype=jnp.float32)  # (N, k, C)
    expert_onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)
    combine = jnp.einsum(
        "nk,nke,nkc->nec",
        gate_vals * kept.astype(jnp.float32), expert_onehot, slot_onehot,
    )
    dispatch = (combine > 0.0).astype(router_logits.dtype)

    # Switch load-balance loss: E · Σ_e f_e·P_e, where f_e is the fraction
    # of (token, choice) assignments routed to e and P_e the mean router
    # probability. Minimised (=1) at uniform routing.
    f = choice_mask.sum(axis=(0, 1)) / (N * k)  # fraction of assignments
    p = probs.mean(axis=0)  # (E,)
    aux = E * jnp.sum(f * p)

    dropped = 1.0 - kept.sum() / jnp.asarray(N * k, jnp.float32)
    return Routing(dispatch=dispatch, combine=combine.astype(
        router_logits.dtype), aux_loss=aux, fraction_dropped=dropped)


class MoEMLP(nn.Module):
    """Mixture-of-experts FFN block (drop-in for a dense MLP).

    Expert weights are stacked on a leading E dim — ``wi (E, d, ff)``,
    ``wo (E, ff, d)`` — which the layout rules shard over the ``expert``
    mesh axis (sharding_rules.EP_RULES). All compute is batched einsum.

    Routing is **grouped** (GShard §3.1): tokens are split into groups of
    at most ``group_size`` (never crossing a sequence boundary) and each
    group is routed independently with capacity ``ceil(k·g·cf/E)``. The
    dispatch/combine tensors are then (G, g, E, C) — O(N·g·k·cf) memory
    instead of the O(N²·k·cf) a single global group would cost, which is
    what keeps batch 32 × seq 1024 runnable on a 16 GB chip.
    """

    num_experts: int = 8
    mlp_dim: int = 3072
    k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    group_size: int = 1024  # max tokens per routing group
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, S, d = x.shape
        E = self.num_experts
        g = min(self.group_size, S)
        if S % g:
            raise ValueError(
                f"seq_len {S} not divisible by routing group size {g}"
            )
        G = B * (S // g)
        tokens = x.reshape(G, g, d)

        # Router in fp32: small matmul, numerically load-bearing.
        router_logits = nn.Dense(
            E, use_bias=False, dtype=jnp.float32,
            param_dtype=self.param_dtype, name="router",
        )(tokens.astype(jnp.float32))  # (G, g, E)
        C = expert_capacity(g, E, self.k, self.capacity_factor)
        routing = jax.vmap(
            partial(top_k_routing, k=self.k, capacity=C)
        )(router_logits)  # fields batched over G

        wi = self.param(
            "wi", nn.initializers.lecun_normal(batch_axis=(0,)),
            (E, d, self.mlp_dim), self.param_dtype,
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(batch_axis=(0,)),
            (E, self.mlp_dim, d), self.param_dtype,
        )

        # dispatch: (G,g,E,C)×(G,g,d) → (E, G·C, d). Under EP sharding
        # this einsum is where XLA inserts the token all-to-all.
        expert_in = jnp.einsum(
            "gnec,gnd->egcd", routing.dispatch.astype(self.dtype),
            tokens.astype(self.dtype),
        ).reshape(E, G * C, d)
        h = jnp.einsum("esd,edf->esf", expert_in, wi.astype(self.dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum(
            "esf,efd->esd", h, wo.astype(self.dtype)
        ).reshape(E, G, C, d)
        out = jnp.einsum(
            "gnec,egcd->gnd", routing.combine.astype(self.dtype), expert_out
        )

        # Collected by parallel/dp.forward into the train loss; a no-op
        # when the collection isn't mutable (eval / non-MoE callers).
        # Per-step drop diagnostics live on the Routing value
        # (fraction_dropped) for direct-layer users; they are not sown.
        self.sow("losses", "moe_aux",
                 self.aux_loss_weight * routing.aux_loss.mean(),
                 reduce_fn=lambda a, b: a + b, init_fn=lambda: jnp.float32(0))
        return out.reshape(B, S, d)


# ---------------------------------------------------------------------------
# Explicit shard_map EP transport (pedagogical parity with dp_explicit):
# the hand-rolled all-to-all the compiler path does implicitly.
# ---------------------------------------------------------------------------

def ep_dispatch(expert_in, *, axis: str = AXIS_EXPERT):
    """(E, C, d) with E global → (E/n, n·C, d) local expert view.

    Inside ``shard_map`` each device holds its tokens' contributions to
    *all* E experts; this all-to-all re-partitions so each device holds
    *its* E/n experts' slots from all n peers — ``dist.all_to_all`` in
    the reference's vocabulary (SURVEY.md §2c EP row).
    """
    n = cc.axis_size(axis)
    E, C, d = expert_in.shape
    if E % n:
        raise ValueError(f"experts {E} not divisible by axis size {n}")
    out = cc.all_to_all(expert_in, axis, split_axis=0, concat_axis=0)
    # (E, C, d) → rows grouped as n blocks of E/n experts: reorder to
    # (E/n, n·C, d) so each local expert sees one contiguous slot buffer.
    return out.reshape(n, E // n, C, d).transpose(1, 0, 2, 3) \
        .reshape(E // n, n * C, d)


def ep_combine(expert_out, *, axis: str = AXIS_EXPERT):
    """Inverse of :func:`ep_dispatch`: (E/n, n·C, d) → (E, C, d)."""
    n = cc.axis_size(axis)
    El, nC, d = expert_out.shape
    C = nC // n
    x = expert_out.reshape(El, n, C, d).transpose(1, 0, 2, 3) \
        .reshape(n * El, C, d)
    return cc.all_to_all(x, axis, split_axis=0, concat_axis=0)
