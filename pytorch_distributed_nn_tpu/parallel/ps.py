"""Asynchronous parameter-server data parallelism.

The reference's async strategy: rank 0 holds the parameters; workers
``dist.send`` gradients to it and ``dist.recv`` fresh parameters back,
with no step synchronization — classic async SGD with stale gradients
(SURVEY.md §2a "Parameter-server / async trainer" row, §2c "Async /
parameter-server DP").

Async PS is deliberately NOT an SPMD program (XLA lockstep is the
antithesis of asynchrony), so the TPU-native design runs it at the
*process* level: the server applies updates host-side while each worker
drives its own accelerator (or CPU) through a jit-compiled grad step.
Transport is the framework's native rendezvous store
(:mod:`runtime.native`, the c10d-TCPStore equivalent) — the same
send/recv capability the reference gets from torch p2p:

- server: ``grads`` arrive as a totally-ordered ticket queue
  (store ADD gives the ticket; blocking GET drains it); each grad is
  applied immediately and ``params/v{N}`` is republished;
- workers: pull the freshest params (version counter), compute a grad
  on their own batch shard, push it with their ticket — never waiting
  for other workers. Staleness is bounded only by worker speed, exactly
  the reference's semantics.
"""

from __future__ import annotations

import io
import logging
import time
from typing import Any, Callable

import jax
import numpy as np

from pytorch_distributed_nn_tpu.runtime.native import StoreClient

log = logging.getLogger(__name__)

_PARAMS_VERSION = "ps/params/version"
_PARAMS_KEY = "ps/params/v{v}"
_GRAD_TICKET = "ps/grads/ticket"
_GRAD_KEY = "ps/grads/{t}"
_STOP_KEY = "ps/stop"


def tree_to_bytes(tree: Any) -> bytes:
    leaves, treedef = jax.tree.flatten(tree)
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(leaf) for leaf in leaves])
    del treedef  # structure is carried by the template on the other side
    return buf.getvalue()


def tree_from_bytes(data: bytes, template: Any) -> Any:
    leaves, treedef = jax.tree.flatten(template)
    with np.load(io.BytesIO(data)) as z:
        loaded = [z[f"arr_{i}"] for i in range(len(leaves))]
    return jax.tree.unflatten(treedef, loaded)


class ParameterServer:
    """Rank 0 of the reference's PS strategy: owns params + optimizer,
    applies each incoming (stale) gradient, republishes params."""

    def __init__(self, store: StoreClient, params: Any, tx) -> None:
        self.store = store
        self.params = params
        self.tx = tx
        self.opt_state = tx.init(params)
        self.version = 0
        self.applied = 0
        self._publish()

    def _publish(self) -> None:
        self.store.set(_PARAMS_KEY.format(v=self.version),
                       tree_to_bytes(self.params))
        self.store.set(_PARAMS_VERSION, str(self.version).encode())

    def apply_one(self, grad_bytes: bytes) -> None:
        import optax

        grads = tree_from_bytes(grad_bytes, self.params)
        updates, self.opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        self.params = optax.apply_updates(self.params, updates)
        self.version += 1
        self.applied += 1
        self._publish()

    def serve(self, total_grads: int, *, timeout_ms: int = 120_000) -> Any:
        """Drain the ticket queue until ``total_grads`` gradients have
        been applied; returns the final params."""
        next_ticket = 1
        while self.applied < total_grads:
            data = self.store.get(_GRAD_KEY.format(t=next_ticket),
                                  timeout_ms=timeout_ms)
            self.apply_one(data)
            self.store.delete(_GRAD_KEY.format(t=next_ticket))
            next_ticket += 1
        self.store.set(_STOP_KEY, b"1")
        return self.params


class PSWorker:
    """One async worker: pull freshest params, grad on own shard, push.

    ``max_staleness`` bounds how many tickets a worker may run ahead of
    the server's applied count (stale-synchronous-parallel): unbounded
    asynchrony lets fast workers push a burst of gradients all computed
    at the initial params, which diverges; SSP keeps the reference's
    async semantics with a convergence guarantee. ``None`` = fully async.
    """

    def __init__(self, store: StoreClient, grad_fn: Callable,
                 params_template: Any, *,
                 max_staleness: int | None = 8) -> None:
        self.store = store
        self.grad_fn = grad_fn  # (params, x, y) -> grads  (jit-compiled)
        self.template = params_template
        self.max_staleness = max_staleness
        self._version_seen = -1
        self._params = None
        self._last_ticket = 0

    def pull(self) -> Any:
        v = int(self.store.get(_PARAMS_VERSION).decode())
        if v != self._version_seen:
            data = self.store.get(_PARAMS_KEY.format(v=v))
            self._params = tree_from_bytes(data, self.template)
            self._version_seen = v
        return self._params

    def step(self, x, y) -> int:
        """One async step; returns the ticket this grad got."""
        if self.max_staleness is not None:
            # SSP gate: wait until the server has applied to within
            # max_staleness of our last pushed ticket
            target = self._last_ticket - self.max_staleness
            while (target > 0 and
                   int(self.store.get(_PARAMS_VERSION).decode()) < target):
                time.sleep(0.002)
        params = self.pull()
        grads = self.grad_fn(params, x, y)
        grads = jax.device_get(grads)
        ticket = self.store.add(_GRAD_TICKET, 1)
        self.store.set(_GRAD_KEY.format(t=ticket), tree_to_bytes(grads))
        self._last_ticket = ticket
        return ticket

    def run(self, batches, *, poll_stop_every: int = 4) -> int:
        """Push gradients for ``batches`` until exhausted or the server
        says stop; returns how many grads this worker contributed."""
        pushed = 0
        for i, (x, y) in enumerate(batches):
            if i % poll_stop_every == 0 and self.store.check(_STOP_KEY):
                break
            self.step(x, y)
            pushed += 1
        return pushed


def run_ps_local(params, tx, grad_fn, worker_batches,
                 *, server_port: int = 0) -> tuple[Any, int]:
    """Single-process reference harness: threads play the server and
    workers (the multi-process form just runs the same classes from
    different OS processes against one StoreServer)."""
    import threading

    from pytorch_distributed_nn_tpu.runtime.native import StoreServer

    total = sum(len(b) for b in worker_batches)
    with StoreServer(server_port) as srv:
        server = ParameterServer(StoreClient(port=srv.port), params, tx)
        result: dict = {}

        def serve():
            result["params"] = server.serve(total)

        threads = [threading.Thread(target=serve)]
        for batches in worker_batches:
            worker = PSWorker(StoreClient(port=srv.port), grad_fn, params)
            threads.append(threading.Thread(target=worker.run,
                                            args=(batches,)))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        log.info("ps: %d grads in %.3fs", server.applied,
                 time.perf_counter() - t0)
    return result["params"], server.applied
