"""Pipeline parallelism — BASELINE.json config 4: "Transformer-LM
pipeline-parallel (torch.distributed send/recv p2p)".

The reference moves activations between stage ranks with blocking
``dist.send``/``dist.recv`` and hand-schedules the backward pass
(SURVEY.md §3.3). TPU-native design (SURVEY.md §7 hard part (b)):

- the block stack is *stacked* into per-stage parameter groups — every
  leaf gains a leading ``(n_stages, layers_per_stage, ...)`` dim, sharded
  over the ``pipe`` mesh axis;
- one ``shard_map`` over ``pipe`` runs the GPipe fill-drain schedule as a
  ``lax.scan`` over ticks; stage s's output reaches stage s+1 via
  ``lax.ppermute`` over the ICI ring — the send/recv pair as one
  collective;
- the *backward* pipeline comes from AD: transposing the scan reverses
  the tick order and transposes each ppermute edge s→s+1 into s+1→s,
  which is exactly the reference's hand-written reverse send/recv chain;
- embedding and head are cheap and stay *outside* the shard_map,
  replicated over ``pipe`` and sharded over batch like any DP compute, so
  pipeline composes with data parallelism on the same mesh.

Bubble accounting matches GPipe: S+M-1 ticks for M microbatches over S
stages; every stage computes on every tick (fill/drain ticks process
garbage that is masked out of the output slots and contributes zero
gradient).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_nn_tpu.config import TrainConfig
from pytorch_distributed_nn_tpu.ops import collectives as cc
from pytorch_distributed_nn_tpu.runtime.mesh import (
    AXIS_PIPE,
    batch_pspec,
    global_device_put,
)
from pytorch_distributed_nn_tpu.train.state import TrainState


@dataclasses.dataclass
class StagePartition:
    """How to split one model family into (embed | blocks | head).

    ``block`` returns ``(y, aux)``: aux is the scalar sum of the
    block's sown "losses" collection (MoE load-balance terms; exactly
    0.0 for dense blocks), which the schedules thread into the training
    objective.

    Mixed dense/MoE stacks (``moe_every = e > 1``): homogeneous
    (S, K, ...) stacking can't hold heterogeneous layer trees, so the
    stage params become TWO homogeneous stacks — ``{"dense", "moe"}``
    subtrees — applied in (e-1 dense, 1 MoE) groups of ``period`` by
    :func:`_stage_apply`. ``block`` then applies a DENSE layer and
    ``moe_block`` the MoE layer closing each group."""

    block_names: list[str]  # ordered param-tree keys of the block stack
    embed: Callable  # (params, tokens) -> activations
    block: Callable  # (one_block_params, x, *, train, rng) -> (x, aux)
    head: Callable  # (params, x) -> logits
    moe_block: Callable | None = None  # MoE layer flavor (mixed stacks)
    period: int = 1  # layers per dense+MoE group (moe_every)

    def split_names(self) -> tuple[list[str], list[str]]:
        """(dense, moe) block names in layer order (mixed stacks)."""
        e = self.period
        dense = [n for i, n in enumerate(self.block_names)
                 if i % e != e - 1]
        moe = [n for i, n in enumerate(self.block_names)
               if i % e == e - 1]
        return dense, moe


def _aux_block(block_mod):
    def block(p, x, *, train=True, rng=None):
        rngs = None if rng is None else {"dropout": rng}
        y, updates = block_mod.apply({"params": p}, x, train=train,
                                     rngs=rngs, mutable=["losses"])
        aux = sum(
            (leaf.astype(jnp.float32).sum()
             for leaf in jax.tree.leaves(updates.get("losses", {}))),
            jnp.zeros((), jnp.float32),
        )
        return y, aux

    return block


def partition_for(model) -> StagePartition:
    """Build the stage partition for a supported model family by
    re-instantiating its leaf modules (no duplicated math)."""
    from pytorch_distributed_nn_tpu.models.llama import Llama, LlamaBlock, RMSNorm
    from pytorch_distributed_nn_tpu.models.transformer_lm import (
        DecoderBlock,
        TransformerLM,
    )

    from pytorch_distributed_nn_tpu.models.moe_lm import MoETransformerLM

    if isinstance(model, TransformerLM):
        # MoE cadence: derived from the model's own layer_ffn hook (the
        # single source of truth for which layers are MoE), validated
        # against the periodic pattern split_names/_stage_apply_mixed
        # assume — a changed convention fails HERE, loudly, not as an
        # opaque stacking mismatch. moe_every=1 keeps ONE homogeneous
        # stack (every block is MoE); e>1 splits into dense + MoE
        # stacks applied in period-e groups (see StagePartition).
        period = 1
        moe_block = None
        ffn = None
        if isinstance(model, MoETransformerLM):
            mask = [model.layer_ffn(i) is not None
                    for i in range(model.num_layers)]
            e = model.moe_every
            if mask != [(i % e == e - 1)
                        for i in range(model.num_layers)]:
                raise ValueError(
                    f"layer_ffn MoE placement {mask} is not the "
                    f"(e-1 dense, 1 MoE) period-{e} pattern the mixed "
                    f"stage stacking assumes — update "
                    f"StagePartition.split_names/_stage_apply_mixed "
                    f"alongside the model convention"
                )
            if e == 1:
                ffn = model.layer_ffn(0)
            else:
                period = e
                moe_mod = DecoderBlock(
                    **model.block_kwargs(),
                    ffn=model.layer_ffn(mask.index(True)),
                )
                moe_block = _aux_block(moe_mod)
        block_mod = DecoderBlock(**model.block_kwargs(), ffn=ffn)
        tok = nn.Embed(model.vocab_size, model.d_model,
                       param_dtype=model.param_dtype)
        pos = nn.Embed(model.max_len, model.d_model,
                       param_dtype=model.param_dtype)
        ln_f = nn.LayerNorm(dtype=model.dtype,
                            param_dtype=model.param_dtype)
        lm_head = nn.Dense(model.vocab_size, use_bias=False,
                           dtype=jnp.float32,
                           param_dtype=model.param_dtype)

        def embed(params, tokens):
            T = tokens.shape[1]
            x = tok.apply({"params": params["tok_embed"]}, tokens)
            x = x + pos.apply({"params": params["pos_embed"]},
                              jnp.arange(T)[None])
            return x.astype(model.dtype)

        def head(params, x):
            x = ln_f.apply({"params": params["ln_f"]}, x)
            return lm_head.apply({"params": params["lm_head"]}, x)

        names = [f"block{i}" for i in range(model.num_layers)]
        return StagePartition(names, embed, _aux_block(block_mod), head,
                              moe_block=moe_block,
                              period=period if moe_block else 1)

    if isinstance(model, Llama):
        block_mod = LlamaBlock(
            num_heads=model.num_heads, num_kv_heads=model.num_kv_heads,
            mlp_dim=model.mlp_dim, rope_theta=model.rope_theta,
            attn_impl=model.attn_impl, dtype=model.dtype,
            param_dtype=model.param_dtype,
        )
        tok = nn.Embed(model.vocab_size, model.d_model,
                       param_dtype=model.param_dtype)
        norm = RMSNorm(dtype=model.dtype, param_dtype=model.param_dtype)
        lm_head = nn.Dense(model.vocab_size, use_bias=False,
                           dtype=jnp.float32,
                           param_dtype=model.param_dtype)

        def embed(params, tokens):
            x = tok.apply({"params": params["tok_embed"]}, tokens)
            return x.astype(model.dtype)

        def head(params, x):
            x = norm.apply({"params": params["final_norm"]}, x)
            return lm_head.apply({"params": params["lm_head"]}, x)

        names = [f"layer{i}" for i in range(model.num_layers)]
        return StagePartition(names, embed, _aux_block(block_mod), head)

    raise ValueError(
        f"pipeline parallelism supports TransformerLM/Llama, got "
        f"{type(model).__name__}"
    )


def stack_stage_params(params: dict, part: StagePartition,
                       n_stages: int, n_chunks: int = 1,
                       chunked: bool | None = None) -> dict:
    """Restack flat per-block params into a stacked stage tree plus the
    non-block remainder. Keeps single-device init bit-identical to the
    unpipelined model (golden-equivalence oracle).

    ``n_chunks == 1`` (gpipe/1f1b): leaves are (S, K, ...) — stage s
    holds blocks [sK, (s+1)K). ``n_chunks > 1`` (interleaved): leaves
    are (S, v, Kc, ...) with [d, j] = virtual stage ``j*S + d``'s Kc
    blocks — the device-major permutation round-robining virtual
    stages over devices (docs/design.md interleaving notes)."""
    L = len(part.block_names)
    S, v = n_stages, n_chunks
    if chunked is None:
        chunked = v > 1  # the interleaved step forces chunked at v=1
    if L % (S * v):
        raise ValueError(
            f"{L} blocks not divisible by {S} stages x {v} chunks"
        )
    rest = {k: p for k, p in params.items() if k not in part.block_names}
    if part.period > 1:
        K = L // (S * v)
        if K % part.period:
            raise ValueError(
                f"each pipeline stage/chunk holds {K} layers — not "
                f"divisible by moe_every={part.period}, so stages "
                f"would split a dense+MoE group; choose stages/chunks "
                f"aligned to whole groups"
            )
        dense_names, moe_names = part.split_names()
        stages = {
            "dense": _stack_subset(params, dense_names, S, v, chunked),
            "moe": _stack_subset(params, moe_names, S, v, chunked),
        }
        return {"stages": stages, "rest": rest}
    return {"stages": _stack_subset(params, part.block_names, S, v,
                                    chunked),
            "rest": rest}


def _stack_subset(params: dict, names: list[str], S: int, v: int,
                  chunked: bool):
    """Stack ``names``'s (homogeneous) block trees into (S, n/S, ...)
    or, chunked, device-major (S, v, n/(Sv), ...) — index [d, j] is
    virtual stage j*S + d (subsets inherit the layout because name
    filtering preserves layer order and every stage contributes a
    contiguous run)."""
    blocks = [params[name] for name in names]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    n = len(names)
    if not chunked:
        return jax.tree.map(
            lambda x: x.reshape((S, n // S) + x.shape[1:]), stacked
        )
    per = n // (S * v)
    return jax.tree.map(
        lambda x: jnp.moveaxis(
            x.reshape((v, S, per) + x.shape[1:]), 0, 1
        ),
        stacked,
    )


def unstack_stage_params(params: dict, part: StagePartition,
                         n_chunks: int = 1,
                         chunked: bool | None = None) -> dict:
    """Inverse of :func:`stack_stage_params` (for checkpoint export):
    inverts the device-major permutation for chunked layouts and
    re-interleaves mixed dense/MoE stacks."""
    stacked = params["stages"]
    if chunked is None:
        chunked = n_chunks > 1

    def unflatten(tree):
        if not chunked:
            return jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), tree
            )
        return jax.tree.map(
            lambda x: jnp.moveaxis(x, 1, 0).reshape(
                (-1,) + x.shape[3:]
            ),
            tree,
        )

    out = dict(params["rest"])
    if part.period > 1:
        dense_names, moe_names = part.split_names()
        dflat = unflatten(stacked["dense"])
        mflat = unflatten(stacked["moe"])
        for i, name in enumerate(dense_names):
            out[name] = jax.tree.map(lambda x: x[i], dflat)
        for i, name in enumerate(moe_names):
            out[name] = jax.tree.map(lambda x: x[i], mflat)
        return out
    flat = unflatten(stacked)
    for i, name in enumerate(part.block_names):
        out[name] = jax.tree.map(lambda x: x[i], flat)
    return out


def restore_unstacked_params(cfg, checkpoint_dir: str):
    """Restore a pipeline checkpoint (STACKED stage params) and return
    the flat per-block tree on host, or None when no checkpoint exists.

    Builds the stacked template from a fresh init — no pipeline mesh is
    needed (restore places to the template's single-device layout), so
    this works on hosts with fewer devices than ``cfg.mesh.pipe``. The
    shared mechanism behind ``scripts/eval.py`` (evaluate a pipeline
    run under dp) and checkpoint export."""
    from pytorch_distributed_nn_tpu.data import get_dataset
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from pytorch_distributed_nn_tpu.train.optim import make_optimizer
    from pytorch_distributed_nn_tpu.train.state import TrainState

    mgr = CheckpointManager(checkpoint_dir, async_save=False)
    try:
        if mgr.latest_step() is None:
            return None
        model = get_model(cfg.model)
        # full data args: a pipeline run trained on token_file/array_file
        # must be restorable too (the init batch only provides shapes,
        # but file datasets refuse to construct without their path)
        ds = get_dataset(cfg.data.dataset, seed=cfg.seed, batch_size=1,
                         seq_len=cfg.data.seq_len,
                         vocab_size=cfg.data.vocab_size,
                         path=cfg.data.path,
                         token_dtype=cfg.data.token_dtype,
                         sample=cfg.data.sample,
                         image_size=cfg.data.image_size)
        x0, _ = ds.batch(0)
        flat = model.init(jax.random.key(cfg.seed), jnp.asarray(x0),
                          train=False)["params"]
        part = partition_for(model)
        interleaved = cfg.parallel.pipeline_schedule == "interleaved"
        n_chunks = (max(cfg.parallel.pipe_chunks, 1)
                    if interleaved else 1)
        stacked = stack_stage_params(flat, part, max(cfg.mesh.pipe, 1),
                                     n_chunks=n_chunks,
                                     chunked=interleaved)
        template = TrainState.create(
            apply_fn=model.apply, params=stacked,
            tx=make_optimizer(cfg.optim, total_steps=max(cfg.steps, 1)),
            rng=jax.random.key(cfg.seed + 1),
        )
        state, _ = mgr.restore(template)
        return unstack_stage_params(jax.device_get(state.params), part,
                                    n_chunks=n_chunks,
                                    chunked=interleaved)
    finally:
        mgr.close()


def _stage_apply(part: StagePartition, stage_params, x, *,
                 train: bool = True, rng=None):
    """Run this device's K blocks sequentially (scan over the stacked
    leading dim); returns (y, aux) with aux the summed sown losses of
    the K blocks. ``rng`` (dropout): folded per layer so every block
    draws a distinct mask — callers fold in microbatch and stage first,
    making the stream deterministic for backward recompute."""
    if part.period > 1:
        return _stage_apply_mixed(part, stage_params, x, train=train,
                                  rng=rng)
    K = jax.tree.leaves(stage_params)[0].shape[0]

    if rng is None:
        def body(carry, p):
            h, aux = carry
            h, a = part.block(p, h, train=train)
            return (h, aux + a), None

        (out, aux), _ = lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), stage_params
        )
    else:
        def body(carry, xs):
            h, aux = carry
            p, i = xs
            h, a = part.block(p, h, train=train,
                              rng=jax.random.fold_in(rng, i))
            return (h, aux + a), None

        (out, aux), _ = lax.scan(
            body, (x, jnp.zeros((), jnp.float32)),
            (stage_params, jnp.arange(K)),
        )
    return out, aux


def _stage_apply_mixed(part: StagePartition, stage_params, x, *,
                       train: bool, rng=None):
    """Mixed dense/MoE stage (``moe_every = e > 1``): the stage holds
    two homogeneous stacks — dense (K(e-1)/e, ...) and moe (K/e, ...)
    — applied as a scan over K/e groups of (e-1 dense, 1 MoE) layers.
    ``rng`` folds the ORIGINAL in-stage layer index (j*e + i), keeping
    the dropout-mask convention identical to the homogeneous path."""
    e = part.period
    dense, moe = stage_params["dense"], stage_params["moe"]
    g = jax.tree.leaves(moe)[0].shape[0]
    dense = jax.tree.map(
        lambda p: p.reshape((g, e - 1) + p.shape[1:]), dense
    )

    def group(carry, xs):
        h, aux = carry
        dp, mp, j = xs

        def lay(c, xs2):
            h2, a2 = c
            p, i = xs2
            r = (None if rng is None
                 else jax.random.fold_in(rng, j * e + i))
            h2, a = part.block(p, h2, train=train, rng=r)
            return (h2, a2 + a), None

        (h, aux), _ = lax.scan(lay, (h, aux),
                               (dp, jnp.arange(e - 1)))
        r = (None if rng is None
             else jax.random.fold_in(rng, j * e + e - 1))
        h, a = part.moe_block(mp, h, train=train, rng=r)
        return (h, aux + a), None

    (out, aux), _ = lax.scan(
        group, (x, jnp.zeros((), jnp.float32)),
        (dense, moe, jnp.arange(g)),
    )
    return out, aux


_DATA_SPEC = batch_pspec()  # P(('data','fsdp')) — mesh.py owns this
_X_MB_SPEC = P(None, *_DATA_SPEC)  # (M, mb, ...)
_STAGE_SPEC = P(AXIS_PIPE)
# With TP on, the pipeline's shard_maps are MANUAL over these axes
# only; the `tensor` axis stays AUTO so the SPMD partitioner runs
# Megatron TP inside each stage (per the stage params' sharding —
# _stage_sharding) with no hand-written collectives in the tick body.
_MANUAL_AXES = frozenset({AXIS_PIPE, "data", "fsdp"})


def _is_partial_manual(mesh: Mesh) -> bool:
    """True when the pipeline shard_maps leave axes to the compiler
    (TP/EP inside stages)."""
    return (mesh.shape.get("tensor", 1) > 1
            or mesh.shape.get("expert", 1) > 1)


def _wire_dtype(mesh: Mesh, dtype):
    """Dtype for the pipeline's cross-stage output-broadcast psum.

    XLA *CPU*'s AllReducePromotion pass crashes on bf16 all-reduces
    under partial-manual lowering ('Invalid binary instruction opcode
    copy'), so CPU-device meshes promote the wire to f32. TPU lowers
    bf16 all-reduces natively — gate on the platform of the mesh's own
    devices (not the process default backend: a CPU mesh in a
    TPU-attached process must still promote) so real runs don't pay 2x
    ICI bytes for a CPU-only bug (VERDICT r2 Weak #3;
    tests/test_pipeline.py asserts both arms). Revisit if the crash
    ever reproduces on TPU."""
    platform = mesh.devices.flat[0].platform
    if _is_partial_manual(mesh) and platform == "cpu":
        return jnp.float32
    return dtype


def _pipeline_axis_names(mesh: Mesh) -> frozenset:
    """Manual axes for the pipeline shard_maps: fully manual unless
    TP/EP is on (see _is_partial_manual) — keep the standard path
    unperturbed."""
    if _is_partial_manual(mesh):
        return _MANUAL_AXES & set(mesh.axis_names)
    return frozenset(mesh.axis_names)


def _stage_sharding(mesh: Mesh, path: str, shape,
                    lead: int = 2) -> NamedSharding:
    """Sharding for one STACKED stage leaf — (S, K, *param_shape) for
    gpipe/1f1b (``lead=2``), (S, v, Kc, *param_shape) for interleaved
    (``lead=3``): stages over ``pipe``, and the within-stage dims
    TP/EP-sharded by the same name-driven rules every other strategy
    uses (sharding_rules.spec_for, dims shifted by the stacking
    dims)."""
    from pytorch_distributed_nn_tpu.parallel.sharding_rules import (
        spec_for,
    )

    inner = spec_for(path, tuple(shape[lead:]),
                     tensor=mesh.shape.get("tensor", 1),
                     expert=mesh.shape.get("expert", 1))
    return NamedSharding(mesh, P(AXIS_PIPE, *([None] * (lead - 1)),
                                 *inner))


def _pipelined_forward(part: StagePartition, mesh: Mesh, S: int, M: int,
                       *, train: bool, with_rng: bool = False):
    """The GPipe fill-drain FORWARD as a shard_map over ``pipe``:
    (stage_params, x_mb (M, mb, T, D)[, rng]) -> (last-stage outputs
    broadcast to every stage for the replicated head, mean
    per-microbatch aux loss). Differentiable (the AD transpose is the
    reverse fill-drain) and reused verbatim by the forward-only
    pipeline eval path (train=False, aux ignored).

    ``with_rng`` (dropout): the tick folds (rng, live microbatch,
    stage, data shard) — the SAME stream convention as 1F1B's
    ``mb_rng`` — so both schedules draw bit-identical masks and their
    loss curves agree exactly (the cross-schedule dropout golden in
    tests/test_pipeline.py). AD saves the mask-relevant residuals like
    any other; fill/drain ticks draw garbage masks for garbage compute
    that never reaches the objective."""
    fwd_edges = [(i, i + 1) for i in range(S - 1)]  # no wraparound

    def pipelined_blocks(stage_params, x_mb, rng=None):
        stage_params = jax.tree.map(lambda p: p.squeeze(0), stage_params)
        idx = lax.axis_index(AXIS_PIPE)
        mb_shape = x_mb.shape[1:]
        buf = jnp.zeros(mb_shape, x_mb.dtype)
        outputs = jnp.zeros_like(x_mb)
        aux0 = jnp.zeros((), jnp.float32)

        def tick(carry, t):
            buf, outputs, aux_sum = carry
            feed = x_mb[jnp.clip(t, 0, M - 1)]
            x_in = jnp.where(idx == 0, feed, buf)
            if rng is not None:
                m_live = jnp.clip(t - idx, 0, M - 1)
                r = jax.random.fold_in(
                    jax.random.fold_in(rng, m_live), idx
                )
                r = jax.random.fold_in(
                    r, lax.axis_index(("data", "fsdp"))
                )
            else:
                r = None
            y, aux = _stage_apply(part, stage_params, x_in, train=train,
                                  rng=r)
            # cc.ppermute = lax.ppermute + CommRecorder/flight record
            sent = cc.ppermute(y, AXIS_PIPE, fwd_edges)
            # fill/drain ticks compute garbage — their aux terms must
            # not reach the objective (stage s is live for t in
            # [s, s + M))
            live = jnp.logical_and(t >= idx, t < idx + M)
            aux_sum = aux_sum + jnp.where(live, aux, 0.0)
            out_t = t - (S - 1)
            write = jnp.logical_and(idx == S - 1, out_t >= 0)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(out_t, 0, M - 1), 0
                ),
                lambda o: o,
                outputs,
            )
            return (sent, outputs, aux_sum), None

        (_, outputs, aux_sum), _ = lax.scan(
            tick, (buf, outputs, aux0), jnp.arange(M + S - 1)
        )
        # everyone needs the last stage's outputs for the (replicated)
        # head: broadcast by masked psum over pipe, at the backend-gated
        # wire dtype (see _wire_dtype).
        wire = _wire_dtype(mesh, x_mb.dtype)
        outputs = lax.psum(
            jnp.where(idx == S - 1, outputs.astype(wire),
                      jnp.zeros(outputs.shape, wire)),
            AXIS_PIPE,
        ).astype(x_mb.dtype)
        # aux: sum over this device's M live ticks and all stages, then
        # batch-mean across the data shards; /M makes it the mean of
        # per-microbatch sums — identical semantics to the dense path's
        # full-batch forward (routing groups never span microbatches)
        aux = lax.pmean(lax.psum(aux_sum, AXIS_PIPE),
                        ("data", "fsdp")) / M
        return outputs, aux

    in_specs = ((_STAGE_SPEC, _X_MB_SPEC, P()) if with_rng
                else (_STAGE_SPEC, _X_MB_SPEC))
    return jax.shard_map(
        pipelined_blocks,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(_X_MB_SPEC, P()),
        axis_names=_pipeline_axis_names(mesh),
        check_vma=False,
    )


def _state_placement(mesh: Mesh, part: StagePartition, S: int, step,
                     n_chunks: int = 1, chunked: bool | None = None):
    """(step_dispatch, place_state) for a pipeline step function:
    stacks the flat params ((S, K, ...) or, for interleaved,
    (S, v, Kc, ...)), shards stages over ``pipe``, replicates the rest,
    jits with donation."""
    from pytorch_distributed_nn_tpu.parallel.sharding_rules import (
        path_str,
    )

    if chunked is None:
        chunked = n_chunks > 1
    lead = 3 if chunked else 2
    replicated = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, _DATA_SPEC)

    def _opt_shardings(opt_state):
        # optimizer moments mirror param shapes AND paths (optax trees
        # embed the param path), so stacked (S, K, ...) leaves get the
        # same pipe x TP layout as their params
        def spec_of(kp, x):
            if hasattr(x, "ndim") and x.ndim >= lead and x.shape[0] == S:
                return _stage_sharding(mesh, path_str(kp), x.shape,
                                       lead=lead)
            return replicated

        return jax.tree_util.tree_map_with_path(spec_of, opt_state)

    def shardings_of(state):
        stage_sh = jax.tree_util.tree_map_with_path(
            lambda kp, x: _stage_sharding(mesh, path_str(kp), x.shape,
                                          lead=lead),
            state.params["stages"],
        )
        param_sh = {"stages": stage_sh,
                    "rest": jax.tree.map(lambda _: replicated,
                                         state.params["rest"])}
        return state.replace(
            step=replicated,
            rng=replicated,
            params=param_sh,
            model_state=jax.tree.map(lambda _: replicated,
                                     state.model_state),
            opt_state=_opt_shardings(state.opt_state),
        )

    compiled: dict = {}

    def place_state(state: TrainState) -> TrainState:
        stacked_params = stack_stage_params(state.params, part, S,
                                            n_chunks=n_chunks,
                                            chunked=chunked)
        state = TrainState.create(
            apply_fn=state.apply_fn, params=stacked_params, tx=state.tx,
            model_state=state.model_state, rng=state.rng,
        )
        sh = shardings_of(state)
        placed = global_device_put(state, sh)
        compiled["step"] = jax.jit(
            step,
            in_shardings=(sh, batch_sh, batch_sh),
            out_shardings=(sh, replicated),
            donate_argnums=(0,),
        )
        return placed

    def step_dispatch(state, x, y):
        if "step" not in compiled:
            raise RuntimeError("call place_state before stepping")
        return compiled["step"](state, x, y)

    def jitted():
        """The underlying jax.jit step (for AOT lowering /
        memory_analysis — scripts/validate_pp_layout.py); available
        after place_state."""
        if "step" not in compiled:
            raise RuntimeError("call place_state before jitted()")
        return compiled["step"]

    step_dispatch.jitted = jitted
    return step_dispatch, place_state


def make_pipeline_train_step(cfg: TrainConfig, mesh: Mesh,
                             loss_fn: Callable, model):
    S = mesh.shape[AXIS_PIPE]
    M = max(cfg.parallel.microbatches, 1)
    if S < 2:
        raise ValueError("pipeline strategy needs mesh.pipe >= 2")
    schedule = cfg.parallel.pipeline_schedule
    if cfg.parallel.pipe_chunks > 1 and schedule != "interleaved":
        raise ValueError(
            f"parallel.pipe_chunks={cfg.parallel.pipe_chunks} only "
            f"takes effect with pipeline_schedule='interleaved' (got "
            f"{schedule!r}) — refusing to silently train un-interleaved"
        )
    if schedule == "1f1b":
        return _make_1f1b_step(cfg, mesh, loss_fn, model, S, M)
    if schedule == "interleaved":
        return _make_interleaved_step(cfg, mesh, loss_fn, model, S, M)
    if schedule != "gpipe":
        raise ValueError(
            f"unknown pipeline_schedule {schedule!r}; have 'gpipe' "
            "(AD-transposed fill-drain), '1f1b' (PipeDream-flush, "
            "manual backward, depth-bounded activation memory), and "
            "'interleaved' (Megatron virtual chunks, ~1/v bubble)"
        )
    part = partition_for(model)
    use_dropout = bool(getattr(model, "dropout", 0.0))
    sharded_pipeline = _pipelined_forward(part, mesh, S, M, train=True,
                                          with_rng=use_dropout)

    def step(state: TrainState, tokens, targets):
        B = tokens.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        rng = (jax.random.fold_in(state.rng, state.step)
               if use_dropout else None)

        def compute(params):
            h = part.embed(params["rest"], tokens)  # (B, T, D)
            h_mb = h.reshape((M, B // M) + h.shape[1:])
            if use_dropout:
                h_mb, aux = sharded_pipeline(params["stages"], h_mb, rng)
            else:
                h_mb, aux = sharded_pipeline(params["stages"], h_mb)
            h = h_mb.reshape((B,) + h_mb.shape[2:])
            logits = part.head(params["rest"], h)
            return loss_fn(logits, targets) + aux

        loss, grads = jax.value_and_grad(compute)(state.params)
        new_state = state.apply_gradients(grads)
        return new_state, {"loss": loss}

    return _state_placement(mesh, part, S, step)


def _microbatch_weights(mesh: Mesh, tgt_mb, M: int):
    """Masked-loss weighting shared by the manual-backward schedules
    (ADVICE r2): loss_fn returns a mean over VALID positions
    (losses.valid_mask: targets >= 0), and the mean of per-microbatch
    means equals the global batch mean only when every microbatch holds
    the same valid count. Weight each microbatch's data loss by its
    share of the GLOBAL valid count (all microbatches, all data
    shards). Unmasked losses see weights of exactly 1.0 (x/x == 1.0 in
    f32), leaving the dense-path goldens unchanged; max(., 1) keeps an
    all-ignored batch at 0 loss (masked_lm_xent's own guard), not
    0/0 = NaN. Call INSIDE the pipeline shard_map."""
    from pytorch_distributed_nn_tpu.train.losses import valid_mask

    n_valid = jnp.sum(
        valid_mask(tgt_mb), axis=tuple(range(1, tgt_mb.ndim))
    ).astype(jnp.float32)  # (M,) per data shard
    d_shards = mesh.shape["data"] * mesh.shape["fsdp"]
    return (n_valid * (d_shards * M)
            / jnp.maximum(lax.psum(n_valid.sum(), ("data", "fsdp")),
                          1.0))


def _finalize_shard_values(sg, rg, loss_sum):
    """Shared tail of the manual-backward tick loops: everything in the
    scan carry is PER DATA SHARD (the whole loss/backward runs inside
    shard_map, unlike gpipe where jit-level SPMD averages the batch
    axes automatically), so take the data-axis mean explicitly. Stage
    grads then live with their stage (out spec: pipe-sharded, the
    [None] re-adds the pipe dim); rest grads were accumulated on the
    embed- and head-owning devices only — the pipe-sum replicates them
    like the params they update."""
    data_axes = ("data", "fsdp")
    sg = jax.tree.map(lambda g: lax.pmean(g, data_axes)[None], sg)
    rg = jax.tree.map(
        lambda g: lax.pmean(lax.psum(g, AXIS_PIPE), data_axes), rg
    )
    loss = lax.pmean(lax.psum(loss_sum, AXIS_PIPE), data_axes)
    return sg, rg, loss


def _microbatched_step(sharded, M: int):
    """Shared outer step for the manual-backward schedules: split the
    batch into M microbatches, fold the step into the rng, run the
    sharded tick loop, apply gradients."""

    def step(state: TrainState, tokens, targets):
        B = tokens.shape[0]
        if B % M:
            raise ValueError(
                f"batch {B} not divisible by {M} microbatches"
            )
        tok_mb = tokens.reshape((M, B // M) + tokens.shape[1:])
        tgt_mb = targets.reshape((M, B // M) + targets.shape[1:])
        rng = jax.random.fold_in(state.rng, state.step)
        sg, rg, loss = sharded(state.params["stages"],
                               state.params["rest"], tok_mb, tgt_mb,
                               rng)
        new_state = state.apply_gradients({"stages": sg, "rest": rg})
        return new_state, {"loss": loss}

    return step


def _make_1f1b_step(cfg: TrainConfig, mesh: Mesh, loss_fn: Callable,
                    model, S: int, M: int):
    """The 1F1B (PipeDream-flush) pipeline step: manual backward.

    GPipe above lets AD transpose the forward scan, which forces the
    scan to save residuals for every in-flight tick — activation memory
    grows with the microbatch count M. Here the backward is explicit:
    the tick body holds a ring buffer of at most ``2S - 1`` saved stage
    INPUTS, and a backward unit re-linearizes its stage from the saved
    input (``jax.vjp``) at the tick the schedule dictates — per-stage
    recompute, exactly one extra forward, O(S) activation memory
    (pipeline_schedule.py has the schedule math).

    Structure per tick (all stages run the same traced body):
    - forward unit: consume previous tick's ppermute (stage 0: embed
      the scheduled token microbatch), save the input, send the output
      right. Masked by the fwd table.
    - backward unit: three device-varying flavors via ``lax.switch`` —
      stage 0 differentiates (blocks∘embed) and accumulates embed
      grads; middle stages differentiate blocks against the received
      cotangent; the last stage differentiates (loss∘head∘blocks)
      from its saved input (no received cotangent — the loss grad is
      born here). Cotangents are sent left. Masked by the bwd table.
    - both ppermutes run unconditionally — collectives never sit in
      divergent control flow; the tables guarantee sender/receiver
      liveness matches.

    Dropout: each microbatch/stage/layer
    folds a deterministic rng, so the backward's recompute sees the
    identical masks its forward drew.
    """
    from pytorch_distributed_nn_tpu.parallel.pipeline_schedule import (
        NO_OP,
        one_f_one_b,
    )

    part = partition_for(model)
    sched = one_f_one_b(S, M)
    depth = sched.max_in_flight
    fwd_tbl = jnp.asarray(sched.fwd)  # (N, S) int32
    bwd_tbl = jnp.asarray(sched.bwd)
    n_ticks = sched.n_ticks
    fwd_edges = [(i, i + 1) for i in range(S - 1)]
    bwd_edges = [(i + 1, i) for i in range(S - 1)]
    use_dropout = bool(getattr(model, "dropout", 0.0))

    def body(stage_params, rest_params, tok_mb, tgt_mb, rng):
        """Inside shard_map. stage_params local (1, K, ...); tok_mb
        (M, mb, T) int tokens; tgt_mb the matching targets; rng the
        per-step dropout key (unused when the model has no dropout)."""
        sp = jax.tree.map(lambda p: p.squeeze(0), stage_params)
        idx = lax.axis_index(AXIS_PIPE)
        probe = part.embed(rest_params, tok_mb[0])  # shape/dtype probe
        mb_shape, act_dtype = probe.shape, probe.dtype
        mb_w = _microbatch_weights(mesh, tgt_mb, M)

        def mb_rng(b):
            if not use_dropout:
                return None
            # decorrelate over (step-folded base rng, microbatch, stage,
            # data shard); _stage_apply folds the in-stage layer index.
            # Without the shard fold every data-parallel shard would
            # draw identical masks for corresponding activations —
            # correlated regularization relative to the dense path's
            # per-example masks (ADVICE r2).
            r = jax.random.fold_in(jax.random.fold_in(rng, b), idx)
            return jax.random.fold_in(
                r, lax.axis_index(("data", "fsdp"))
            )

        def stage_fwd(sp_, x, b):
            return _stage_apply(part, sp_, x, train=True, rng=mb_rng(b))

        def tick(carry, t):
            recv_f, recv_b, act, sg, rg, loss_sum = carry
            f_mb = fwd_tbl[t, idx]
            b_mb = bwd_tbl[t, idx]
            f_idx = jnp.clip(f_mb, 0, M - 1)
            b_idx = jnp.clip(b_mb, 0, M - 1)
            # Read the backward's saved input BEFORE the forward unit
            # writes: at stage 0 in steady state f - b == depth, so
            # this tick's forward lands in exactly the slot the
            # backward needs (ring reuse is tight by construction).
            x_saved = act[b_idx % depth]

            # ---- forward unit (dead warmup/drain ticks skip the
            # stage compute entirely — local cond, no collectives) ----
            def fwd_unit(_):
                x_in = lax.cond(
                    idx == 0,
                    lambda: part.embed(rest_params, tok_mb[f_idx])
                    .astype(act_dtype),
                    lambda: recv_f,
                )
                slot = f_idx % depth
                act_new = lax.dynamic_update_index_in_dim(
                    act, x_in, slot, 0
                )
                # the last stage's forward output feeds nobody (its
                # backward re-linearizes from the saved input): skip.
                # aux is discarded here — every (mb, stage) pair gets
                # exactly one backward, which recomputes and counts it.
                y = lax.cond(
                    idx == S - 1,
                    lambda: jnp.zeros(mb_shape, act_dtype),
                    lambda: stage_fwd(sp, x_in, f_idx)[0]
                    .astype(act_dtype),
                )
                return act_new, y

            act, y = lax.cond(
                f_mb != NO_OP, fwd_unit,
                lambda _: (act, jnp.zeros(mb_shape, act_dtype)), None,
            )

            # ---- backward unit (three flavors; dead ticks skip both
            # the vjp and the dense grad-tree accumulate) -------------
            def bwd_unit(_):
                # each flavor's objective includes the stage's own aux
                # terms (sown MoE losses, /M like the data loss), so
                # their gradients flow through the same vjp and the
                # summed lv values reproduce the dense path's objective
                def bwd_first(_):
                    def f(sp_, rp_):
                        x0 = part.embed(rp_, tok_mb[b_idx]) \
                            .astype(act_dtype)
                        y, aux = stage_fwd(sp_, x0, b_idx)
                        return y.astype(act_dtype), aux / M

                    (_, auxv), vjp = jax.vjp(f, sp, rest_params)
                    dsp, drp = vjp((recv_b, jnp.ones((), jnp.float32)))
                    return (auxv, dsp, drp,
                            jnp.zeros(mb_shape, act_dtype))

                def bwd_mid(_):
                    def f(sp_, x):
                        y, aux = stage_fwd(sp_, x, b_idx)
                        return y.astype(act_dtype), aux / M

                    (_, auxv), vjp = jax.vjp(f, sp, x_saved)
                    dsp, dx = vjp((recv_b, jnp.ones((), jnp.float32)))
                    zeros_rest = jax.tree.map(jnp.zeros_like, rest_params)
                    return auxv, dsp, zeros_rest, dx

                def bwd_last(_):
                    tgt = tgt_mb[b_idx]

                    def f(sp_, rp_, x):
                        yl, aux = stage_fwd(sp_, x, b_idx)
                        logits = part.head(rp_, yl)
                        # valid-count-weighted mean of per-mb means ==
                        # global batch mean even under masking (mb_w)
                        return ((loss_fn(logits, tgt) * mb_w[b_idx]
                                 + aux) / M).astype(jnp.float32)

                    lv, vjp = jax.vjp(f, sp, rest_params, x_saved)
                    dsp, drp, dx = vjp(jnp.ones((), jnp.float32))
                    return lv, dsp, drp, dx

                branch = jnp.where(idx == 0, 0,
                                   jnp.where(idx == S - 1, 2, 1))
                lv, dsp, drp, dx = lax.switch(
                    branch, (bwd_first, bwd_mid, bwd_last), None
                )
                sg_new = jax.tree.map(jnp.add, sg, dsp)
                rg_new = jax.tree.map(jnp.add, rg, drp)
                return sg_new, rg_new, loss_sum + lv, dx

            sg, rg, loss_sum, dx = lax.cond(
                b_mb != NO_OP, bwd_unit,
                lambda _: (sg, rg, loss_sum,
                           jnp.zeros(mb_shape, act_dtype)), None,
            )

            # ---- unconditional sends -------------------------------
            recv_f = cc.ppermute(y, AXIS_PIPE, fwd_edges)
            recv_b = cc.ppermute(dx, AXIS_PIPE, bwd_edges)
            return (recv_f, recv_b, act, sg, rg, loss_sum), None

        zeros_act = jnp.zeros(mb_shape, act_dtype)
        init = (
            zeros_act,
            zeros_act,
            jnp.zeros((depth,) + mb_shape, act_dtype),
            jax.tree.map(jnp.zeros_like, sp),
            jax.tree.map(jnp.zeros_like, rest_params),
            jnp.zeros((), jnp.float32),
        )
        init = jax.tree.map(
            lambda x: lax.pcast(x, AXIS_PIPE, to="varying"), init
        )
        (_, _, _, sg, rg, loss_sum), _ = lax.scan(
            tick, init, jnp.arange(n_ticks)
        )
        return _finalize_shard_values(sg, rg, loss_sum)

    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(_STAGE_SPEC, P(), _X_MB_SPEC, _X_MB_SPEC, P()),
        out_specs=(_STAGE_SPEC, P(), P()),
        axis_names=_pipeline_axis_names(mesh),
        check_vma=False,
    )

    return _state_placement(mesh, part, S, _microbatched_step(sharded, M))


def _make_interleaved_step(cfg: TrainConfig, mesh: Mesh,
                           loss_fn: Callable, model, S: int, M: int):
    """Interleaved (virtual-chunk) 1F1B: Megatron's schedule on the
    table-driven SPMD machinery (SURVEY.md §7(b); VERDICT r2 Missing
    #4; worked design in docs/design.md).

    Each device holds ``v = parallel.pipe_chunks`` chunks of
    ``L/(S v)`` layers; virtual stage ``k`` is chunk ``k // S`` on
    device ``k % S``, so consecutive virtual stages are consecutive
    devices and the ``k % S == S-1 -> device 0`` wrap rides a FULL-ring
    ppermute (the non-interleaved schedules' rings have no wrap edge).
    Relative to 1F1B the bubble drops to ~1/v (measured in
    tests/test_pipeline_schedule.py under the max-live-unit cost
    model) for v× more in-flight activations and per-tick ring hops.

    Differences from :func:`_make_1f1b_step`'s tick body:
    - the schedule tables carry (chunk, microbatch) pairs, and the
      grouped warmup means messages can wait — arriving ppermute
      payloads land in schedule-static inbox slots
      (pipeline_schedule.interleaved_1f1b allocates them) instead of
      a single register;
    - stage params gain a leading chunk dim (v, Kc, ...); units slice
      their chunk dynamically and backward grads accumulate into the
      chunk's slot (read-modify-write dynamic update);
    - the three backward flavors become CHUNK-conditional: embed-grad
      at virtual stage 0, loss∘head at Sv-1 — both live on fixed
      devices but fixed (device, chunk) pairs, so the lax.switch
      branch index folds the chunk table in.

    TP/EP compose exactly as under 1f1b: the tensor/expert axes stay
    AUTO in the shard_map (partial-manual lowering) and the SPMD
    partitioner runs Megatron TP / expert sharding inside each chunk
    (goldens: tests/test_pipeline.py pipe x TP x interleaved and
    pipe x EP x interleaved).
    """
    from pytorch_distributed_nn_tpu.parallel.pipeline_schedule import (
        NO_OP,
        interleaved_1f1b,
    )

    v = max(cfg.parallel.pipe_chunks, 1)
    part = partition_for(model)
    L = len(part.block_names)
    if L % (S * v):
        raise ValueError(
            f"{L} layers not divisible by {S} stages x {v} chunks"
        )
    sched = interleaved_1f1b(S, v, M)
    Sv = S * v
    n_ticks = sched.n_ticks
    ACT, FIN, BIN = sched.act_depth, sched.fin_depth, sched.bin_depth
    fwd_c = jnp.asarray(sched.fwd_chunk)
    fwd_m = jnp.asarray(sched.fwd_mb)
    bwd_c = jnp.asarray(sched.bwd_chunk)
    bwd_m = jnp.asarray(sched.bwd_mb)
    act_w_t = jnp.asarray(sched.act_write)
    act_r_t = jnp.asarray(sched.act_read)
    fin_w_t = jnp.asarray(sched.fin_write)
    fin_r_t = jnp.asarray(sched.fin_read)
    bin_w_t = jnp.asarray(sched.bin_write)
    bin_r_t = jnp.asarray(sched.bin_read)
    ring_fwd = [(i, (i + 1) % S) for i in range(S)]
    ring_bwd = [(i, (i - 1) % S) for i in range(S)]
    use_dropout = bool(getattr(model, "dropout", 0.0))

    def body(stage_params, rest_params, tok_mb, tgt_mb, rng):
        sp = jax.tree.map(lambda p: p.squeeze(0), stage_params)
        idx = lax.axis_index(AXIS_PIPE)
        probe = part.embed(rest_params, tok_mb[0])
        mb_shape, act_dtype = probe.shape, probe.dtype
        mb_w = _microbatch_weights(mesh, tgt_mb, M)

        def chunk_params(j):
            return jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(p, j, 0,
                                                   keepdims=False),
                sp,
            )

        def mb_rng(m, k):
            if not use_dropout:
                return None
            # decorrelate over (step rng, microbatch, VIRTUAL stage,
            # data shard); _stage_apply folds the in-chunk layer index
            r = jax.random.fold_in(jax.random.fold_in(rng, m), k)
            return jax.random.fold_in(
                r, lax.axis_index(("data", "fsdp"))
            )

        def chunk_fwd(cp, x, m, k):
            return _stage_apply(part, cp, x, train=True,
                                rng=mb_rng(m, k))

        def tick(carry, t):
            recv_f, recv_b, fin, binb, act, sg, rg, loss_sum = carry
            fj, fm = fwd_c[t, idx], fwd_m[t, idx]
            bj, bm = bwd_c[t, idx], bwd_m[t, idx]
            fk = fj * S + idx  # virtual stage of the forward unit
            bk = bj * S + idx
            fm_i = jnp.clip(fm, 0, M - 1)
            bm_i = jnp.clip(bm, 0, M - 1)

            # ---- 1) arriving messages land in their inbox slots
            # BEFORE any unit reads (same-tick passthrough is legal;
            # garbage arrivals have NO_OP write slots and are dropped)
            fin_w = fin_w_t[t, idx]
            fin = lax.cond(
                fin_w != NO_OP,
                lambda b: lax.dynamic_update_index_in_dim(
                    b, recv_f, jnp.clip(fin_w, 0, FIN - 1), 0
                ),
                lambda b: b,
                fin,
            )
            bin_w = bin_w_t[t, idx]
            binb = lax.cond(
                bin_w != NO_OP,
                lambda b: lax.dynamic_update_index_in_dim(
                    b, recv_b, jnp.clip(bin_w, 0, BIN - 1), 0
                ),
                lambda b: b,
                binb,
            )

            # ---- 2) backward's saved input: read BEFORE the forward
            # unit writes (the allocator frees act slots at-read)
            x_saved = act[jnp.clip(act_r_t[t, idx], 0, ACT - 1)]
            cot_in = binb[jnp.clip(bin_r_t[t, idx], 0, BIN - 1)]

            # ---- 3) forward unit ------------------------------------
            def fwd_unit(act):
                x_in = lax.cond(
                    fk == 0,
                    lambda: part.embed(rest_params, tok_mb[fm_i])
                    .astype(act_dtype),
                    lambda: fin[jnp.clip(fin_r_t[t, idx], 0, FIN - 1)],
                )
                act = lax.dynamic_update_index_in_dim(
                    act, x_in, jnp.clip(act_w_t[t, idx], 0, ACT - 1), 0
                )
                # the LAST virtual stage's output feeds nobody (its
                # backward re-linearizes from the saved input): skip
                y = lax.cond(
                    fk == Sv - 1,
                    lambda: jnp.zeros(mb_shape, act_dtype),
                    lambda: chunk_fwd(
                        chunk_params(jnp.clip(fj, 0, v - 1)),
                        x_in, fm_i, fk,
                    )[0].astype(act_dtype),
                )
                return act, y

            act, y = lax.cond(
                fj != NO_OP, fwd_unit,
                lambda a: (a, jnp.zeros(mb_shape, act_dtype)), act,
            )

            # ---- 4) backward unit: flavors by VIRTUAL stage ---------
            def bwd_unit(_):
                cp = chunk_params(jnp.clip(bj, 0, v - 1))

                def bwd_first(_):
                    def f(cp_, rp_):
                        x0 = part.embed(rp_, tok_mb[bm_i]) \
                            .astype(act_dtype)
                        yb, aux = chunk_fwd(cp_, x0, bm_i, bk)
                        return yb.astype(act_dtype), aux / M

                    (_, auxv), vjp = jax.vjp(f, cp, rest_params)
                    dcp, drp = vjp((cot_in, jnp.ones((), jnp.float32)))
                    return (auxv, dcp, drp,
                            jnp.zeros(mb_shape, act_dtype))

                def bwd_mid(_):
                    def f(cp_, x):
                        yb, aux = chunk_fwd(cp_, x, bm_i, bk)
                        return yb.astype(act_dtype), aux / M

                    (_, auxv), vjp = jax.vjp(f, cp, x_saved)
                    dcp, dx = vjp((cot_in, jnp.ones((), jnp.float32)))
                    zeros_rest = jax.tree.map(jnp.zeros_like,
                                              rest_params)
                    return auxv, dcp, zeros_rest, dx

                def bwd_last(_):
                    tgt = tgt_mb[bm_i]

                    def f(cp_, rp_, x):
                        yb, aux = chunk_fwd(cp_, x, bm_i, bk)
                        logits = part.head(rp_, yb)
                        return ((loss_fn(logits, tgt) * mb_w[bm_i]
                                 + aux) / M).astype(jnp.float32)

                    lv, vjp = jax.vjp(f, cp, rest_params, x_saved)
                    dcp, drp, dx = vjp(jnp.ones((), jnp.float32))
                    return lv, dcp, drp, dx

                branch = jnp.where(bk == 0, 0,
                                   jnp.where(bk == Sv - 1, 2, 1))
                lv, dcp, drp, dx = lax.switch(
                    branch, (bwd_first, bwd_mid, bwd_last), None
                )

                # accumulate this chunk's grads into its slot
                bj_i = jnp.clip(bj, 0, v - 1)

                def acc_add(a, g):
                    cur = lax.dynamic_index_in_dim(a, bj_i, 0,
                                                   keepdims=False)
                    return lax.dynamic_update_index_in_dim(
                        a, cur + g, bj_i, 0
                    )

                sg_new = jax.tree.map(acc_add, sg, dcp)
                rg_new = jax.tree.map(jnp.add, rg, drp)
                return sg_new, rg_new, loss_sum + lv, dx

            sg, rg, loss_sum, dx = lax.cond(
                bj != NO_OP, bwd_unit,
                lambda _: (sg, rg, loss_sum,
                           jnp.zeros(mb_shape, act_dtype)), None,
            )

            # ---- 5) unconditional FULL-ring sends -------------------
            recv_f = cc.ppermute(y, AXIS_PIPE, ring_fwd)
            recv_b = cc.ppermute(dx, AXIS_PIPE, ring_bwd)
            return (recv_f, recv_b, fin, binb, act, sg, rg,
                    loss_sum), None

        zeros_act = jnp.zeros(mb_shape, act_dtype)
        init = (
            zeros_act,
            zeros_act,
            jnp.zeros((FIN,) + mb_shape, act_dtype),
            jnp.zeros((BIN,) + mb_shape, act_dtype),
            jnp.zeros((ACT,) + mb_shape, act_dtype),
            jax.tree.map(jnp.zeros_like, sp),
            jax.tree.map(jnp.zeros_like, rest_params),
            jnp.zeros((), jnp.float32),
        )
        init = jax.tree.map(
            lambda x: lax.pcast(x, AXIS_PIPE, to="varying"), init
        )
        (_, _, _, _, _, sg, rg, loss_sum), _ = lax.scan(
            tick, init, jnp.arange(n_ticks)
        )
        return _finalize_shard_values(sg, rg, loss_sum)

    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(_STAGE_SPEC, P(), _X_MB_SPEC, _X_MB_SPEC, P()),
        out_specs=(_STAGE_SPEC, P(), P()),
        axis_names=_pipeline_axis_names(mesh),
        check_vma=False,
    )

    return _state_placement(mesh, part, S, _microbatched_step(sharded, M),
                            n_chunks=v, chunked=True)


def make_pipeline_eval_step(cfg: TrainConfig, mesh: Mesh,
                            loss_fn: Callable, model):
    """Forward-only pipelined evaluation on STACKED stage params: the
    fill-drain forward with train=False, then head + loss + masked
    accuracy — lifting round 1's 'evaluate with strategy=dp on
    unstacked params instead' restriction.

    Interleaved-trained states carry (S, v, Kc, ...) chunked stages;
    eval regroups them to the fill-drain (S, L/S, ...) layout inside
    the jitted step (a per-batch pipe-axis reshuffle — eval is not the
    perf path, and the regroup keeps ONE forward schedule to test)."""
    S = mesh.shape[AXIS_PIPE]
    M = max(cfg.parallel.microbatches, 1)
    chunked = cfg.parallel.pipeline_schedule == "interleaved"
    part = partition_for(model)
    fwd = _pipelined_forward(part, mesh, S, M, train=False)

    def regroup(leaf):
        # (S, v, Kc, ...) -> contiguous (S, v*Kc, ...): invert the
        # device-major chunk permutation (stack_stage_params)
        rest_shape = leaf.shape[3:]
        flat = jnp.moveaxis(leaf, 1, 0).reshape((-1,) + rest_shape)
        return flat.reshape((S, leaf.shape[1] * leaf.shape[2])
                            + rest_shape)

    def eval_step(state: TrainState, x, y):
        B = x.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        params = state.params
        if chunked:
            params = {"stages": jax.tree.map(regroup, params["stages"]),
                      "rest": params["rest"]}
        h = part.embed(params["rest"], x)
        h_mb = h.reshape((M, B // M) + h.shape[1:])
        h_mb, _ = fwd(params["stages"], h_mb)  # eval reports data loss
        h = h_mb.reshape((B,) + h_mb.shape[2:])
        logits = part.head(params["rest"], h)
        loss = loss_fn(logits, y)
        valid = y >= 0
        hit = jnp.logical_and(logits.argmax(-1) == y, valid)
        acc = hit.sum() / jnp.maximum(valid.sum(), 1)
        return loss.astype(jnp.float32), acc.astype(jnp.float32)

    return jax.jit(eval_step)
