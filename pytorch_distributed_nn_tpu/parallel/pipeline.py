"""Pipeline parallelism — BASELINE.json config 4: "Transformer-LM
pipeline-parallel (torch.distributed send/recv p2p)".

The reference moves activations between stage ranks with blocking
``dist.send``/``dist.recv`` and hand-schedules the backward pass
(SURVEY.md §3.3). TPU-native design (SURVEY.md §7 hard part (b)):

- the block stack is *stacked* into per-stage parameter groups — every
  leaf gains a leading ``(n_stages, layers_per_stage, ...)`` dim, sharded
  over the ``pipe`` mesh axis;
- one ``shard_map`` over ``pipe`` runs the GPipe fill-drain schedule as a
  ``lax.scan`` over ticks; stage s's output reaches stage s+1 via
  ``lax.ppermute`` over the ICI ring — the send/recv pair as one
  collective;
- the *backward* pipeline comes from AD: transposing the scan reverses
  the tick order and transposes each ppermute edge s→s+1 into s+1→s,
  which is exactly the reference's hand-written reverse send/recv chain;
- embedding and head are cheap and stay *outside* the shard_map,
  replicated over ``pipe`` and sharded over batch like any DP compute, so
  pipeline composes with data parallelism on the same mesh.

Bubble accounting matches GPipe: S+M-1 ticks for M microbatches over S
stages; every stage computes on every tick (fill/drain ticks process
garbage that is masked out of the output slots and contributes zero
gradient).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_nn_tpu.config import TrainConfig
from pytorch_distributed_nn_tpu.runtime.mesh import (
    AXIS_PIPE,
    batch_pspec,
    global_device_put,
)
from pytorch_distributed_nn_tpu.train.state import TrainState


@dataclasses.dataclass
class StagePartition:
    """How to split one model family into (embed | blocks | head)."""

    block_names: list[str]  # ordered param-tree keys of the block stack
    embed: Callable  # (params, tokens) -> activations
    block: Callable  # (one_block_params, x) -> x
    head: Callable  # (params, x) -> logits


def partition_for(model) -> StagePartition:
    """Build the stage partition for a supported model family by
    re-instantiating its leaf modules (no duplicated math)."""
    from pytorch_distributed_nn_tpu.models.llama import Llama, LlamaBlock, RMSNorm
    from pytorch_distributed_nn_tpu.models.transformer_lm import (
        DecoderBlock,
        TransformerLM,
    )

    from pytorch_distributed_nn_tpu.models.moe_lm import MoETransformerLM

    if isinstance(model, MoETransformerLM):
        # MoE blocks carry an expert-parallel FFN the dense DecoderBlock
        # rebuild below can't represent; reject clearly rather than fail
        # deep inside Flax param matching.
        raise ValueError(
            "pipeline strategy does not support MoE models yet; use the "
            "expert-parallel mesh (strategy='dp' + expert axis) instead"
        )
    if isinstance(model, TransformerLM):
        block_mod = DecoderBlock(**model.block_kwargs())
        tok = nn.Embed(model.vocab_size, model.d_model,
                       param_dtype=model.param_dtype)
        pos = nn.Embed(model.max_len, model.d_model,
                       param_dtype=model.param_dtype)
        ln_f = nn.LayerNorm(dtype=model.dtype,
                            param_dtype=model.param_dtype)
        lm_head = nn.Dense(model.vocab_size, use_bias=False,
                           dtype=jnp.float32,
                           param_dtype=model.param_dtype)

        def embed(params, tokens):
            T = tokens.shape[1]
            x = tok.apply({"params": params["tok_embed"]}, tokens)
            x = x + pos.apply({"params": params["pos_embed"]},
                              jnp.arange(T)[None])
            return x.astype(model.dtype)

        def block(p, x):
            return block_mod.apply({"params": p}, x, train=True)

        def head(params, x):
            x = ln_f.apply({"params": params["ln_f"]}, x)
            return lm_head.apply({"params": params["lm_head"]}, x)

        names = [f"block{i}" for i in range(model.num_layers)]
        return StagePartition(names, embed, block, head)

    if isinstance(model, Llama):
        block_mod = LlamaBlock(
            num_heads=model.num_heads, num_kv_heads=model.num_kv_heads,
            mlp_dim=model.mlp_dim, rope_theta=model.rope_theta,
            attn_impl=model.attn_impl, dtype=model.dtype,
            param_dtype=model.param_dtype,
        )
        tok = nn.Embed(model.vocab_size, model.d_model,
                       param_dtype=model.param_dtype)
        norm = RMSNorm(dtype=model.dtype, param_dtype=model.param_dtype)
        lm_head = nn.Dense(model.vocab_size, use_bias=False,
                           dtype=jnp.float32,
                           param_dtype=model.param_dtype)

        def embed(params, tokens):
            x = tok.apply({"params": params["tok_embed"]}, tokens)
            return x.astype(model.dtype)

        def block(p, x):
            return block_mod.apply({"params": p}, x, train=True)

        def head(params, x):
            x = norm.apply({"params": params["final_norm"]}, x)
            return lm_head.apply({"params": params["lm_head"]}, x)

        names = [f"layer{i}" for i in range(model.num_layers)]
        return StagePartition(names, embed, block, head)

    raise ValueError(
        f"pipeline parallelism supports TransformerLM/Llama, got "
        f"{type(model).__name__}"
    )


def stack_stage_params(params: dict, part: StagePartition,
                       n_stages: int) -> dict:
    """Restack flat per-block params into a stacked (S, K, ...) tree plus
    the non-block remainder. Keeps single-device init bit-identical to the
    unpipelined model (golden-equivalence oracle)."""
    L = len(part.block_names)
    if L % n_stages:
        raise ValueError(f"{L} blocks not divisible by {n_stages} stages")
    blocks = [params[name] for name in part.block_names]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    # (L, ...) -> (S, K, ...)
    stacked = jax.tree.map(
        lambda x: x.reshape((n_stages, L // n_stages) + x.shape[1:]),
        stacked,
    )
    rest = {k: v for k, v in params.items() if k not in part.block_names}
    return {"stages": stacked, "rest": rest}


def unstack_stage_params(params: dict, part: StagePartition) -> dict:
    """Inverse of :func:`stack_stage_params` (for checkpoint export)."""
    stacked = params["stages"]
    flat = jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[2:]), stacked
    )
    out = dict(params["rest"])
    for i, name in enumerate(part.block_names):
        out[name] = jax.tree.map(lambda x: x[i], flat)
    return out


def restore_unstacked_params(cfg, checkpoint_dir: str):
    """Restore a pipeline checkpoint (STACKED stage params) and return
    the flat per-block tree on host, or None when no checkpoint exists.

    Builds the stacked template from a fresh init — no pipeline mesh is
    needed (restore places to the template's single-device layout), so
    this works on hosts with fewer devices than ``cfg.mesh.pipe``. The
    shared mechanism behind ``scripts/eval.py`` (evaluate a pipeline
    run under dp) and checkpoint export."""
    from pytorch_distributed_nn_tpu.data import get_dataset
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from pytorch_distributed_nn_tpu.train.optim import make_optimizer
    from pytorch_distributed_nn_tpu.train.state import TrainState

    mgr = CheckpointManager(checkpoint_dir, async_save=False)
    try:
        if mgr.latest_step() is None:
            return None
        model = get_model(cfg.model)
        # full data args: a pipeline run trained on token_file/array_file
        # must be restorable too (the init batch only provides shapes,
        # but file datasets refuse to construct without their path)
        ds = get_dataset(cfg.data.dataset, seed=cfg.seed, batch_size=1,
                         seq_len=cfg.data.seq_len,
                         vocab_size=cfg.data.vocab_size,
                         path=cfg.data.path,
                         token_dtype=cfg.data.token_dtype,
                         sample=cfg.data.sample)
        x0, _ = ds.batch(0)
        flat = model.init(jax.random.key(cfg.seed), jnp.asarray(x0),
                          train=False)["params"]
        part = partition_for(model)
        stacked = stack_stage_params(flat, part, max(cfg.mesh.pipe, 1))
        template = TrainState.create(
            apply_fn=model.apply, params=stacked,
            tx=make_optimizer(cfg.optim, total_steps=max(cfg.steps, 1)),
            rng=jax.random.key(cfg.seed + 1),
        )
        state, _ = mgr.restore(template)
        return unstack_stage_params(jax.device_get(state.params), part)
    finally:
        mgr.close()


def _stage_apply(part: StagePartition, stage_params, x):
    """Run this device's K blocks sequentially (scan over the stacked
    leading dim)."""
    def body(h, p):
        return part.block(p, h), None

    out, _ = lax.scan(body, x, stage_params)
    return out


def make_pipeline_train_step(cfg: TrainConfig, mesh: Mesh,
                             loss_fn: Callable, model):
    S = mesh.shape[AXIS_PIPE]
    M = max(cfg.parallel.microbatches, 1)
    if S < 2:
        raise ValueError("pipeline strategy needs mesh.pipe >= 2")
    if cfg.parallel.pipeline_schedule != "gpipe":
        raise ValueError(
            f"unknown pipeline_schedule "
            f"{cfg.parallel.pipeline_schedule!r}; only 'gpipe' exists "
            "(the backward fill-drain is AD-derived from the forward scan)"
        )
    if getattr(model, "dropout", 0.0):
        raise ValueError(
            "pipeline strategy does not support dropout yet; set "
            "model dropout to 0"
        )
    part = partition_for(model)

    fwd_edges = [(i, i + 1) for i in range(S - 1)]  # no wraparound

    def pipelined_blocks(stage_params, x_mb):
        """Inside shard_map over `pipe` (and the data axes). stage_params:
        local (1, K, ...) tree — squeeze the pipe dim; x_mb: (M, mb, T, D)
        local batch shard."""
        stage_params = jax.tree.map(lambda p: p.squeeze(0), stage_params)
        idx = lax.axis_index(AXIS_PIPE)
        mb_shape = x_mb.shape[1:]
        buf = jnp.zeros(mb_shape, x_mb.dtype)
        outputs = jnp.zeros_like(x_mb)

        def tick(carry, t):
            buf, outputs = carry
            feed = x_mb[jnp.clip(t, 0, M - 1)]
            x_in = jnp.where(idx == 0, feed, buf)
            y = _stage_apply(part, stage_params, x_in)
            sent = lax.ppermute(y, AXIS_PIPE, fwd_edges)
            out_t = t - (S - 1)
            write = jnp.logical_and(idx == S - 1, out_t >= 0)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(out_t, 0, M - 1), 0
                ),
                lambda o: o,
                outputs,
            )
            return (sent, outputs), None

        (_, outputs), _ = lax.scan(
            tick, (buf, outputs), jnp.arange(M + S - 1)
        )
        # everyone needs the last stage's outputs for the (replicated)
        # head: broadcast by masked psum over pipe
        outputs = lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)),
            AXIS_PIPE,
        )
        return outputs

    data_spec = batch_pspec()  # P(('data','fsdp'))
    x_mb_spec = P(None, ("data", "fsdp"))  # (M, mb, T, D)
    stage_spec = P(AXIS_PIPE)

    sharded_pipeline = jax.shard_map(
        pipelined_blocks,
        mesh=mesh,
        in_specs=(stage_spec, x_mb_spec),
        out_specs=x_mb_spec,
        check_vma=False,
    )

    def step(state: TrainState, tokens, targets):
        B = tokens.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")

        def compute(params):
            h = part.embed(params["rest"], tokens)  # (B, T, D)
            h_mb = h.reshape((M, B // M) + h.shape[1:])
            h_mb = sharded_pipeline(params["stages"], h_mb)
            h = h_mb.reshape((B,) + h_mb.shape[2:])
            logits = part.head(params["rest"], h)
            return loss_fn(logits, targets)

        loss, grads = jax.value_and_grad(compute)(state.params)
        new_state = state.apply_gradients(grads)
        return new_state, {"loss": loss}

    replicated = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, data_spec)

    def shardings_of(state):
        # stages sharded over pipe (leading dim); everything else
        # replicated
        stage_sh = jax.tree.map(
            lambda _: NamedSharding(mesh, stage_spec),
            state.params["stages"],
        )
        param_sh = {"stages": stage_sh,
                    "rest": jax.tree.map(lambda _: replicated,
                                         state.params["rest"])}
        return state.replace(
            step=replicated,
            rng=replicated,
            params=param_sh,
            model_state=jax.tree.map(lambda _: replicated,
                                     state.model_state),
            opt_state=_opt_shardings(state.opt_state, mesh),
        )

    def _opt_shardings(opt_state, mesh):
        # optimizer moments mirror param shapes: shard any leaf whose
        # leading dims match the stacked (S, K, ...) pattern
        def spec_of(x):
            if hasattr(x, "ndim") and x.ndim >= 2 and x.shape[0] == S:
                return NamedSharding(mesh, stage_spec)
            return replicated

        return jax.tree.map(spec_of, opt_state)

    compiled: dict = {}

    def place_state(state: TrainState) -> TrainState:
        stacked_params = stack_stage_params(state.params, part, S)
        state = TrainState.create(
            apply_fn=state.apply_fn, params=stacked_params, tx=state.tx,
            model_state=state.model_state, rng=state.rng,
        )
        sh = shardings_of(state)
        placed = global_device_put(state, sh)
        compiled["step"] = jax.jit(
            step,
            in_shardings=(sh, batch_sh, batch_sh),
            out_shardings=(sh, replicated),
            donate_argnums=(0,),
        )
        return placed

    def step_dispatch(state, x, y):
        if "step" not in compiled:
            raise RuntimeError("call place_state before stepping")
        return compiled["step"](state, x, y)

    return step_dispatch, place_state
