"""Sharded data parallelism (ZeRO / FSDP) — BASELINE.json config 5:
"allgather params + reduce-scatter grads" — and the shared compiler-
sharded step used by plain DP and tensor parallelism.

The reference implements sharded DP imperatively: gather each layer's
shards before use, reduce-scatter gradients after backward, local shard
optimizer step (SURVEY.md §3.4). TPU-native design is *declarative*:
parameters and optimizer state are laid out per
:mod:`~pytorch_distributed_nn_tpu.parallel.sharding_rules`, the train
step is the ordinary DP step, and XLA's SPMD partitioner inserts exactly
those all-gathers (scheduled ahead of first use) and reduce-scatters (on
the gradient sum) — plus the weight-update sharding of arXiv 2004.13336
(PAPERS.md): the optimizer update runs on the 1/n shard each device owns.

Stages (ParallelConfig.zero_stage):
- 0: nothing sharded over ``fsdp`` — plain DP layout (used by the 'dp'
  strategy; tensor-parallel rules still apply when mesh.tensor > 1);
- 1: optimizer state sharded, params replicated (ZeRO-1);
- 3: params + optimizer state sharded (ZeRO-3/FSDP). (ZeRO-2 is
  meaningless under XLA: gradients never materialise unsharded unless
  the schedule wants them to.)
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_nn_tpu.parallel import dp
from pytorch_distributed_nn_tpu.parallel.sharding_rules import (
    path_str,
    spec_for,
)
from pytorch_distributed_nn_tpu.runtime.mesh import (
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_TENSOR,
    batch_pspec,
    global_device_put,
)
from pytorch_distributed_nn_tpu.train.state import TrainState


def state_shardings(state: TrainState, mesh: Mesh, *, stage: int = 3):
    """NamedSharding for every TrainState leaf via the layout rules.

    Optimizer-state paths embed the parameter paths (optax moment trees
    mirror the params tree), so TP/fsdp rules hit them identically and
    moments land with their params.
    """
    tensor = mesh.shape[AXIS_TENSOR]
    fsdp = mesh.shape[AXIS_FSDP]
    expert = mesh.shape[AXIS_EXPERT]

    def shard_tree(tree, *, use_fsdp: bool):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: NamedSharding(
                mesh,
                spec_for(path_str(kp), tuple(x.shape), tensor=tensor,
                         fsdp=fsdp if use_fsdp else 1, expert=expert),
            ),
            tree,
        )

    return state.replace(
        step=NamedSharding(mesh, P()),
        rng=NamedSharding(mesh, P()),
        params=shard_tree(state.params, use_fsdp=stage >= 3),
        model_state=shard_tree(state.model_state, use_fsdp=False),
        opt_state=shard_tree(state.opt_state, use_fsdp=stage >= 1),
    )


def make_zero_train_step(mesh: Mesh, loss_fn: Callable, *, stage: int = 3):
    """Returns (step, place_state). The step body is identical to DP —
    sharded DP is purely a layout change (SURVEY.md §3.4 'expressed
    declaratively as shardings')."""
    if stage not in (0, 1, 3):
        raise ValueError(f"zero_stage must be 0, 1 or 3, got {stage}")
    from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ

    # under sequence parallelism the (B, T) token batches arrive
    # seq-sharded from the loader; the jit contract must match or the
    # compiler would reshard (all-gathering the sequence) at entry
    seq = mesh.shape.get(AXIS_SEQ, 1)
    batch_sh = NamedSharding(
        mesh, batch_pspec(AXIS_SEQ) if seq > 1 else batch_pspec()
    )

    def step(state: TrainState, x, y):
        loss, new_model_state, grads = dp._loss_and_grads(
            state, x, y, loss_fn
        )
        new_state = state.apply_gradients(grads).replace(
            model_state=new_model_state
        )
        return new_state, {"loss": loss}

    compiled: dict = {}

    def place_state(state: TrainState) -> TrainState:
        shardings = state_shardings(state, mesh, stage=stage)
        placed = global_device_put(state, shardings)
        compiled["step"] = jax.jit(
            step,
            in_shardings=(shardings, batch_sh, batch_sh),
            out_shardings=(shardings, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        )
        return placed

    def step_dispatch(state, x, y):
        if "step" not in compiled:
            raise RuntimeError("call place_state before stepping")
        return compiled["step"](state, x, y)

    return step_dispatch, place_state
