"""Sharded data parallelism (ZeRO / FSDP) — BASELINE.json config 5:
"allgather params + reduce-scatter grads" — and the shared compiler-
sharded step used by plain DP and tensor parallelism.

The reference implements sharded DP imperatively: gather each layer's
shards before use, reduce-scatter gradients after backward, local shard
optimizer step (SURVEY.md §3.4). TPU-native design is *declarative*:
parameters and optimizer state are laid out per
:mod:`~pytorch_distributed_nn_tpu.parallel.sharding_rules`, the train
step is the ordinary DP step, and XLA's SPMD partitioner inserts exactly
those all-gathers (scheduled ahead of first use) and reduce-scatters (on
the gradient sum) — plus the weight-update sharding of arXiv 2004.13336
(PAPERS.md): the optimizer update runs on the 1/n shard each device owns.

Stages (ParallelConfig.zero_stage):
- 0: nothing sharded over ``fsdp`` — plain DP layout (used by the 'dp'
  strategy; tensor-parallel rules still apply when mesh.tensor > 1);
- 1: optimizer state sharded, params replicated (ZeRO-1);
- 3: params + optimizer state sharded (ZeRO-3/FSDP). (ZeRO-2 is
  meaningless under XLA: gradients never materialise unsharded unless
  the schedule wants them to.)
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_nn_tpu.parallel import dp
from pytorch_distributed_nn_tpu.parallel.sharding_rules import (
    path_str,
    spec_for,
)
from pytorch_distributed_nn_tpu.runtime.mesh import (
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_TENSOR,
    batch_pspec,
    global_device_put,
)
from pytorch_distributed_nn_tpu.train.state import TrainState


def state_shardings(state: TrainState, mesh: Mesh, *, stage: int = 3):
    """NamedSharding for every TrainState leaf via the layout rules.

    Optimizer-state paths embed the parameter paths (optax moment trees
    mirror the params tree), so TP/fsdp rules hit them identically and
    moments land with their params.
    """
    tensor = mesh.shape[AXIS_TENSOR]
    fsdp = mesh.shape[AXIS_FSDP]
    expert = mesh.shape[AXIS_EXPERT]

    def shard_tree(tree, *, use_fsdp: bool):
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: NamedSharding(
                mesh,
                spec_for(path_str(kp), tuple(x.shape), tensor=tensor,
                         fsdp=fsdp if use_fsdp else 1, expert=expert),
            ),
            tree,
        )

    return state.replace(
        step=NamedSharding(mesh, P()),
        rng=NamedSharding(mesh, P()),
        params=shard_tree(state.params, use_fsdp=stage >= 3),
        model_state=shard_tree(state.model_state, use_fsdp=False),
        opt_state=shard_tree(state.opt_state, use_fsdp=stage >= 1),
    )


def _split_microbatches(x, accum: int, n_shards: int, micro_sh):
    """(B, ...) → (accum, B/accum, ...) for the accumulation scan.

    The averaged gradient is invariant to which examples form a
    microbatch (mean of equal-sized microbatch-mean grads == global
    mean), so the split is chosen for *layout*: each of the ``n_shards``
    devices contributes the a-th sub-block of its local batch shard to
    microbatch a, making the reshape purely local — no resharding
    collective at step entry. Falls back to contiguous chunks (same
    math, one input reshard) when B doesn't divide that way.
    """
    B = x.shape[0]
    if B % accum:
        raise ValueError(
            f"global batch {B} not divisible by grad_accum {accum}"
        )
    rest = x.shape[1:]
    if B % (accum * n_shards) == 0:
        m = x.reshape(n_shards, accum, B // (accum * n_shards), *rest)
        m = jnp.moveaxis(m, 1, 0).reshape(accum, B // accum, *rest)
    else:
        m = x.reshape(accum, B // accum, *rest)
    return jax.lax.with_sharding_constraint(m, micro_sh)


def _build_step(mesh: Mesh, loss_fn: Callable, *, stage: int,
                accum: int):
    """The zero/DP step function plus its batch shardings — shared by
    the runtime path (:func:`make_zero_train_step`) and the AOT layout
    validation path (:func:`lower_zero_train_step`)."""
    if stage not in (0, 1, 3):
        raise ValueError(f"zero_stage must be 0, 1 or 3, got {stage}")
    if accum < 1:
        raise ValueError(f"accum must be >= 1, got {accum}")
    from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ, data_axis_size

    # under sequence parallelism the (B, T) token batches arrive
    # seq-sharded from the loader; the jit contract must match or the
    # compiler would reshard (all-gathering the sequence) at entry
    seq = mesh.shape.get(AXIS_SEQ, 1)
    batch_spec = batch_pspec(AXIS_SEQ) if seq > 1 else batch_pspec()
    batch_sh = NamedSharding(mesh, batch_spec)
    micro_sh = NamedSharding(mesh, P(None, *batch_spec))
    n_shards = data_axis_size(mesh)

    def step(state: TrainState, x, y):
        loss, new_model_state, grads = dp._loss_and_grads(
            state, x, y, loss_fn
        )
        new_state = state.apply_gradients(grads).replace(
            model_state=new_model_state
        )
        return new_state, {"loss": loss}

    def step_accum(state: TrainState, x, y):
        mx = _split_microbatches(x, accum, n_shards, micro_sh)
        my = _split_microbatches(y, accum, n_shards, micro_sh)

        def body(carry, inp):
            model_state, gsum, lsum = carry
            i, bx, by = inp
            # decorrelate the per-microbatch dropout stream (forward
            # folds state.step on top, decorrelating across steps)
            fwd_state = state.replace(
                model_state=model_state,
                rng=jax.random.fold_in(state.rng, i),
            )
            loss, new_model_state, grads = dp._loss_and_grads(
                fwd_state, bx, by, loss_fn
            )
            gsum = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), gsum, grads
            )
            return (new_model_state, gsum, lsum + loss), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        )
        (new_model_state, gsum, lsum), _ = jax.lax.scan(
            body,
            (state.model_state, zeros, jnp.zeros((), jnp.float32)),
            (jnp.arange(accum), mx, my),
        )
        grads = jax.tree.map(
            lambda a, p: (a / accum).astype(p.dtype), gsum, state.params
        )
        new_state = state.apply_gradients(grads).replace(
            model_state=new_model_state
        )
        return new_state, {"loss": lsum / accum}

    return (step_accum if accum > 1 else step), batch_sh


def _jit_step(step, shardings, batch_sh, mesh):
    return jax.jit(
        step,
        in_shardings=(shardings, batch_sh, batch_sh),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def make_zero_train_step(mesh: Mesh, loss_fn: Callable, *, stage: int = 3,
                         accum: int = 1):
    """Returns (step, place_state). The step body is identical to DP —
    sharded DP is purely a layout change (SURVEY.md §3.4 'expressed
    declaratively as shardings').

    ``accum > 1`` runs gradient accumulation: the global batch is split
    into ``accum`` microbatches scanned sequentially (``lax.scan``),
    per-microbatch grads summed in f32, one optimizer step on the mean.
    Peak activation memory drops ~accum×. For deterministic stateless
    models the gradient is the same global-batch mean the accum=1 step
    computes; dropout models re-draw masks per microbatch and BatchNorm
    stats update sequentially per microbatch (the same semantics as a
    torch accumulation loop), which differs slightly from one full-batch
    step.
    """
    step, batch_sh = _build_step(mesh, loss_fn, stage=stage, accum=accum)
    compiled: dict = {}

    def place_state(state: TrainState) -> TrainState:
        shardings = state_shardings(state, mesh, stage=stage)
        placed = global_device_put(state, shardings)
        compiled["step"] = _jit_step(step, shardings, batch_sh, mesh)
        return placed

    def step_dispatch(state, x, y):
        if "step" not in compiled:
            raise RuntimeError("call place_state before stepping")
        return compiled["step"](state, x, y)

    return step_dispatch, place_state


def lower_zero_train_step(mesh: Mesh, loss_fn: Callable,
                          abstract_state: TrainState,
                          x_spec, y_spec, *, stage: int = 3,
                          accum: int = 1):
    """AOT-lower the zero train step for ABSTRACT inputs — nothing is
    materialized on any device, so arbitrarily large layouts (the true
    8B config 5) lower on a virtual topology. Returns the jax Lowered;
    callers ``.compile()`` it for the SPMD partitioner's verdict and
    per-chip memory analysis (scripts/validate_8b_layout.py)."""
    step, batch_sh = _build_step(mesh, loss_fn, stage=stage, accum=accum)
    shardings = state_shardings(abstract_state, mesh, stage=stage)
    state_arg = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract_state, shardings,
    )
    def arg(spec):
        return jax.ShapeDtypeStruct(spec.shape, spec.dtype,
                                    sharding=batch_sh)

    return _jit_step(step, shardings, batch_sh, mesh).lower(
        state_arg, arg(x_spec), arg(y_spec)
    )
