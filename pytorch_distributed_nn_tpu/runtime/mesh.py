"""Device mesh / topology.

The reference delegates topology to c10d process groups: a flat
``rank``/``world_size`` with NCCL communicators built per collective
(SURVEY.md §1 "Communication backend"; §3.5 init/rendezvous). TPU-native
design replaces the flat rank world with a *named* ``jax.sharding.Mesh``
whose axes map onto the hardware fabric:

- inner axes (``tensor``, ``seq``) ride ICI — highest bandwidth, so they
  carry the per-layer collectives (TP all-reduce, ring-attention ppermute);
- ``fsdp`` (sharded-DP / ZeRO) sits next — its all-gather/reduce-scatter
  wants ICI too;
- outer axes (``data``, ``pipe``) can span DCN across slices — DP gradient
  allreduce tolerates lower bandwidth, pipeline p2p is narrow.

Every strategy in :mod:`pytorch_distributed_nn_tpu.parallel` addresses the
mesh only by axis *name*, so a size-1 axis composes for free — strategies
never special-case "axis absent".
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, AbstractMesh, PartitionSpec as P

# Canonical axis order: outermost (DCN-tolerant) → innermost (ICI-hungry).
# `pipe` outermost: stages exchange only activation edges (narrow traffic,
# DCN-capable per MPMD-pipeline practice); `tensor` innermost: per-layer
# allreduce is the most bandwidth-hungry collective.
AXIS_PIPE = "pipe"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_SEQ = "seq"
AXIS_TENSOR = "tensor"

AXES: tuple[str, ...] = (
    AXIS_PIPE,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
)


@dataclasses.dataclass
class MeshSpec:
    """Logical parallelism degrees. Unused axes default to 1 and are kept in
    the mesh (size-1 axes cost nothing and keep PartitionSpecs uniform).

    ``data = -1`` means "absorb all remaining devices" — the common case
    where you fix tensor/pipe degrees and data-parallelism fills the pod.
    """

    pipe: int = 1
    data: int = -1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        bad = {name: s for name, s in sizes.items() if s < 1 and s != -1}
        if bad:
            raise ValueError(f"axis sizes must be positive or -1, got {bad}")
        wildcard = [name for name, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one -1 axis, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh spec {sizes} wants {fixed} devices, have {n_devices}"
            )
        return MeshSpec(**sizes)

    def sizes(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in AXES}

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.sizes()[a] for a in AXES)

    def world_size(self) -> int:
        if -1 in self.shape:
            raise ValueError("unresolved MeshSpec; call .resolve(n_devices)")
        return math.prod(self.shape)


def make_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named Mesh over ``devices`` (default: all).

    Uses ``jax.experimental.mesh_utils`` device assignment when available so
    inner axes land on physically adjacent chips (ICI rings); falls back to
    row-major reshape (fine for CPU test meshes).
    """
    if devices is None:
        devices = jax.devices()
    spec = (spec or MeshSpec()).resolve(len(devices))
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            spec.shape, devices=list(devices)
        )
    except ImportError:
        dev_array = np.asarray(devices, dtype=object).reshape(spec.shape)
    except Exception as e:  # topology assigner rejected the shape
        logging.getLogger(__name__).warning(
            "mesh_utils.create_device_mesh failed (%s); falling back to "
            "row-major placement — inner axes may not be ICI-adjacent", e
        )
        dev_array = np.asarray(devices, dtype=object).reshape(spec.shape)
    return Mesh(dev_array, AXES)


def make_abstract_mesh(spec: MeshSpec, n_devices: int) -> AbstractMesh:
    """Shape-only mesh for compile-only checks (no devices needed)."""
    resolved = spec.resolve(n_devices)
    return AbstractMesh(resolved.shape, AXES)


def batch_pspec(extra_inner: str | None = None) -> P:
    """PartitionSpec for a per-example batch dimension: sharded over every
    data-like axis (data × fsdp), the TPU analogue of torch's
    ``DistributedSampler`` per-rank split (SURVEY.md §2a data-loading row)."""
    first = (AXIS_DATA, AXIS_FSDP)
    return P(first, extra_inner) if extra_inner else P(first)


def replicated_pspec() -> P:
    return P()


def data_axis_size(mesh: Mesh) -> int:
    """Total data-parallel degree (data × fsdp), i.e. how many ways the
    global batch is split."""
    return mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
