"""Device mesh / topology.

The reference delegates topology to c10d process groups: a flat
``rank``/``world_size`` with NCCL communicators built per collective
(SURVEY.md §1 "Communication backend"; §3.5 init/rendezvous). TPU-native
design replaces the flat rank world with a *named* ``jax.sharding.Mesh``
whose axes map onto the hardware fabric:

- inner axes (``tensor``, ``seq``) ride ICI — highest bandwidth, so they
  carry the per-layer collectives (TP all-reduce, ring-attention ppermute);
- ``fsdp`` (sharded-DP / ZeRO) sits next — its all-gather/reduce-scatter
  wants ICI too;
- outer axes (``data``, ``pipe``) can span DCN across slices — DP gradient
  allreduce tolerates lower bandwidth, pipeline p2p is narrow.

Every strategy in :mod:`pytorch_distributed_nn_tpu.parallel` addresses the
mesh only by axis *name*, so a size-1 axis composes for free — strategies
never special-case "axis absent".
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, AbstractMesh, PartitionSpec as P

# Canonical axis order: outermost (DCN-tolerant) → innermost (ICI-hungry).
# `pipe` outermost: stages exchange only activation edges (narrow traffic,
# DCN-capable per MPMD-pipeline practice); `tensor` innermost: per-layer
# allreduce is the most bandwidth-hungry collective.
AXIS_PIPE = "pipe"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_SEQ = "seq"
AXIS_TENSOR = "tensor"

AXES: tuple[str, ...] = (
    AXIS_PIPE,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
)


@dataclasses.dataclass
class MeshSpec:
    """Logical parallelism degrees. Unused axes default to 1 and are kept in
    the mesh (size-1 axes cost nothing and keep PartitionSpecs uniform).

    ``data = -1`` means "absorb all remaining devices" — the common case
    where you fix tensor/pipe degrees and data-parallelism fills the pod.
    """

    pipe: int = 1
    data: int = -1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        bad = {name: s for name, s in sizes.items() if s < 1 and s != -1}
        if bad:
            raise ValueError(f"axis sizes must be positive or -1, got {bad}")
        wildcard = [name for name, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one -1 axis, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh spec {sizes} wants {fixed} devices, have {n_devices}"
            )
        return MeshSpec(**sizes)

    def sizes(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in AXES}

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.sizes()[a] for a in AXES)

    def world_size(self) -> int:
        if -1 in self.shape:
            raise ValueError("unresolved MeshSpec; call .resolve(n_devices)")
        return math.prod(self.shape)


def slice_count(devices: Sequence[jax.Device]) -> int:
    """Number of distinct TPU slices (pods connected by DCN) among
    ``devices``. CPU/single-slice devices report 1."""
    ids = set()
    for d in devices:
        idx = getattr(d, "slice_index", None)
        ids.add(0 if idx is None else idx)
    return max(len(ids), 1)


def dcn_factors(spec: MeshSpec, n_slices: int) -> dict[str, int]:
    """Split each logical axis into (DCN, ICI) degrees for a multi-slice
    job: the product of the returned per-axis DCN factors equals
    ``n_slices``, and factors are peeled onto the outermost axes first
    (``pipe``, then ``data``, …) — those tolerate DCN bandwidth, while
    inner axes (tensor/seq/fsdp) want to stay inside a slice on ICI.

    Raises when the slice count cannot be factored onto the mesh at
    all; when the only possible placement puts a factor on an
    ICI-hungry inner axis (e.g. tensor parallelism wider than a slice),
    the mesh still builds but a warning flags the bandwidth hit.
    """
    sizes = spec.sizes()
    remaining = n_slices
    factors = {name: 1 for name in AXES}
    for name in AXES:  # outermost first
        f = math.gcd(sizes[name], remaining)
        factors[name] = f
        remaining //= f
        if remaining == 1:
            break
    if remaining != 1:
        raise ValueError(
            f"cannot place {n_slices} slices on mesh {sizes}: outer-axis "
            f"sizes don't factor the slice count (residual {remaining})"
        )
    dcn_inner = {k: v for k, v in factors.items()
                 if v > 1 and k in (AXIS_FSDP, AXIS_EXPERT, AXIS_SEQ,
                                    AXIS_TENSOR)}
    if dcn_inner:
        logging.getLogger(__name__).warning(
            "DCN factors landed on ICI-hungry axes %s — expect degraded "
            "collective bandwidth; prefer putting pipe/data across slices",
            dcn_inner,
        )
    return factors


def make_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
    *,
    force_slices: int | None = None,
) -> Mesh:
    """Build a named Mesh over ``devices`` (default: all).

    Single slice: ``mesh_utils.create_device_mesh`` assignment so inner
    axes land on physically adjacent chips (ICI rings). Multi-slice
    (devices spanning DCN): ``create_hybrid_device_mesh`` with the DCN
    degrees peeled onto the outermost axes (:func:`dcn_factors`), so
    cross-slice traffic is only pipe edges / DP gradient allreduce.
    Falls back to row-major reshape (fine for CPU test meshes).

    ``force_slices``: treat the device list as that many DCN-connected
    slices (row-major groups) even when the backend reports one — the
    CPU-harness hook that lets tests and ``dryrun_multichip`` exercise
    the hybrid dcn-factor placement and prove the pipeline's ppermute
    schedule lowers with ``pipe`` on the DCN axis, without TPU slices.
    """
    if devices is None:
        devices = jax.devices()
    spec = (spec or MeshSpec()).resolve(len(devices))
    n_slices = force_slices or slice_count(devices)
    if force_slices and len(devices) % force_slices:
        raise ValueError(
            f"{len(devices)} devices don't split into "
            f"{force_slices} equal slices"
        )
    if n_slices > 1:
        # Outside the try: an unplaceable multi-slice spec must raise,
        # not silently fall back to slice-unaware row-major placement.
        dcn = dcn_factors(spec, n_slices)
        ici_shape = tuple(s // dcn[a] for a, s in zip(AXES, spec.shape))
    if force_slices and n_slices > 1:
        # CPU harness: build the hybrid arrangement by hand (the real
        # create_hybrid_device_mesh groups by device slice_index, which
        # CPU devices lack). Row-major slice groups; axis a's index is
        # (dcn_a, ici_a) interleaved dcn-major — the same layout the
        # hybrid assigner produces, so pipe-over-DCN placement and the
        # resulting ppermute lowering are exercised faithfully.
        dcn_shape = tuple(dcn[a] for a in AXES)
        arr = np.asarray(devices, dtype=object).reshape(
            dcn_shape + ici_shape)
        n = len(AXES)
        order = [ax for i in range(n) for ax in (i, n + i)]
        return Mesh(arr.transpose(order).reshape(spec.shape), AXES)
    try:
        from jax.experimental import mesh_utils

        if n_slices > 1:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, tuple(dcn[a] for a in AXES),
                devices=list(devices),
            )
        else:
            dev_array = mesh_utils.create_device_mesh(
                spec.shape, devices=list(devices)
            )
    except ImportError:
        dev_array = np.asarray(devices, dtype=object).reshape(spec.shape)
    except Exception as e:  # topology assigner rejected the shape
        logging.getLogger(__name__).warning(
            "mesh_utils device assignment failed (%s); falling back to "
            "row-major placement — inner axes may not be ICI-adjacent", e
        )
        dev_array = np.asarray(devices, dtype=object).reshape(spec.shape)
    return Mesh(dev_array, AXES)


def global_device_put(tree, shardings):
    """``jax.device_put`` that also works under multi-process: a
    multi-host NamedSharding cannot be device_put directly (non-
    addressable devices), so each process materializes only its
    addressable shards via ``make_array_from_callback``. Correct for
    values that are identical on every process (deterministic seeded
    init, restored checkpoints) — the per-process host value is the
    global value."""
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)

    def put(x, sh):
        is_key = (hasattr(x, "dtype")
                  and jnp.issubdtype(x.dtype, jax.dtypes.prng_key))
        if is_key:
            impl = jax.random.key_impl(x)
            x = jax.random.key_data(x)
        host = np.asarray(jax.device_get(x))
        out = jax.make_array_from_callback(
            host.shape, sh, lambda idx: host[idx]
        )
        if is_key:
            out = jax.random.wrap_key_data(out, impl=impl)
        return out

    return jax.tree.map(put, tree, shardings)


def place_like(tree, template):
    """``device_put`` each leaf of ``tree`` with the dtype and sharding
    of the matching ``template`` leaf (host values → a live state's
    layout; used by the convert/eval CLIs to install restored or
    converted weights)."""
    return jax.tree.map(
        lambda a, t: jax.device_put(
            np.asarray(a, dtype=t.dtype), t.sharding),
        tree, template,
    )


def make_abstract_mesh(spec: MeshSpec, n_devices: int) -> AbstractMesh:
    """Shape-only mesh for compile-only checks (no devices needed)."""
    resolved = spec.resolve(n_devices)
    return AbstractMesh(resolved.shape, AXES)


def batch_pspec(extra_inner: str | None = None) -> P:
    """PartitionSpec for a per-example batch dimension: sharded over every
    data-like axis (data × fsdp), the TPU analogue of torch's
    ``DistributedSampler`` per-rank split (SURVEY.md §2a data-loading row)."""
    first = (AXIS_DATA, AXIS_FSDP)
    return P(first, extra_inner) if extra_inner else P(first)


def replicated_pspec() -> P:
    return P()


def data_axis_size(mesh: Mesh) -> int:
    """Total data-parallel degree (data × fsdp), i.e. how many ways the
    global batch is split."""
    return mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
