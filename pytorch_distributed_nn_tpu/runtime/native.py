"""ctypes bindings for the native runtime (native/libtpunative.so).

Two components, each the TPU-framework replacement for a C++ piece the
reference borrowed from torch (SURVEY.md §2b):

- :class:`StoreServer` / :class:`StoreClient` — the c10d-TCPStore
  equivalent: key-value rendezvous with blocking waits, atomic counters
  (rank assignment), and barriers. Used by multi-process launch when no
  JAX coordinator is running, and by the failure detector's heartbeats.
- :func:`gen_images` / :func:`gen_lm` / :func:`gen_templates` — the
  threaded native data generator behind the ``native`` dataset backend.

The library is built on demand with ``make`` (g++ is in the image;
pybind11 is not, hence the C ABI).
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

from pytorch_distributed_nn_tpu.runtime import chaos

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libtpunative.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeUnavailable(RuntimeError):
    pass


def load_library(build: bool = True) -> ctypes.CDLL:
    """Load (building if needed) the native library; cached."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists():
            if not build:
                raise NativeUnavailable(f"{_LIB_PATH} not built")
            try:
                subprocess.run(["make", "-C", str(_NATIVE_DIR)],
                               check=True, capture_output=True)
            except (subprocess.CalledProcessError, OSError) as e:
                out = getattr(e, "stderr", b"")
                raise NativeUnavailable(
                    f"native build failed: {e}: "
                    f"{out.decode() if isinstance(out, bytes) else out}"
                ) from e
        lib = ctypes.CDLL(str(_LIB_PATH))
        _declare(lib)
        _lib = lib
        return lib


def available() -> bool:
    try:
        load_library()
        return True
    except NativeUnavailable:
        return False


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.tpustore_server_start.restype = c.c_void_p
    lib.tpustore_server_start.argtypes = [c.c_int]
    lib.tpustore_server_port.restype = c.c_int
    lib.tpustore_server_port.argtypes = [c.c_void_p]
    lib.tpustore_server_stop.argtypes = [c.c_void_p]
    lib.tpustore_connect.restype = c.c_void_p
    lib.tpustore_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.tpustore_disconnect.argtypes = [c.c_void_p]
    lib.tpustore_set.restype = c.c_int
    lib.tpustore_set.argtypes = [c.c_void_p, c.c_char_p,
                                 c.POINTER(c.c_uint8), c.c_int]
    lib.tpustore_get.restype = c.c_int
    lib.tpustore_get.argtypes = [c.c_void_p, c.c_char_p,
                                 c.POINTER(c.c_uint8), c.c_int, c.c_int64]
    lib.tpustore_add.restype = c.c_int64
    lib.tpustore_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.tpustore_check.restype = c.c_int
    lib.tpustore_check.argtypes = [c.c_void_p, c.c_char_p]
    lib.tpustore_delete.restype = c.c_int
    lib.tpustore_delete.argtypes = [c.c_void_p, c.c_char_p]

    u64, i64, i32 = c.c_uint64, c.c_int64, c.c_int32
    fp = c.POINTER(c.c_float)
    ip = c.POINTER(i32)
    lib.datagen_templates.argtypes = [u64, i64, i64, fp, c.c_int]
    lib.datagen_images.argtypes = [u64, u64, i64, i64, i64, c.c_float,
                                   fp, fp, ip, c.c_int]
    lib.datagen_lm.argtypes = [u64, u64, i64, i64, i64, i64, i64,
                               c.c_float, ip, c.c_int]


# ---------------------------------------------------------------------------
# Rendezvous store
# ---------------------------------------------------------------------------

class StoreServer:
    """Hosts the store (one per job, on the coordinator)."""

    def __init__(self, port: int = 0) -> None:
        self._lib = load_library()
        self._h = self._lib.tpustore_server_start(port)
        if not self._h:
            raise OSError(f"could not bind store server on port {port}")
        self.port = self._lib.tpustore_server_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.tpustore_server_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class StoreClient:
    """One connection to the store; thread-safe per handle."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 connect_timeout_ms: int = 30_000) -> None:
        self._lib = load_library()
        self._h = self._lib.tpustore_connect(
            host.encode(), port, connect_timeout_ms
        )
        if not self._h:
            raise ConnectionError(f"could not connect to store at "
                                  f"{host}:{port}")
        self._barrier_round: dict[str, int] = {}

    def set(self, key: str, value: bytes) -> None:
        chaos.on_store_op("set", key)  # store_flaky injection point
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value or b"\0")
        rc = self._lib.tpustore_set(self._h, key.encode(), buf, len(value))
        if rc != 0:
            raise OSError(f"store set({key!r}) failed rc={rc}")

    def get(self, key: str, *, timeout_ms: int = -1,
            max_bytes: int = 1 << 20) -> bytes:
        """Blocking wait for ``key`` (timeout_ms < 0 waits forever)."""
        chaos.on_store_op("get", key)  # store_flaky injection point
        cap = max_bytes
        while True:
            buf = (ctypes.c_uint8 * cap)()
            rc = self._lib.tpustore_get(self._h, key.encode(), buf, cap,
                                        timeout_ms)
            if rc == -3 and cap < (1 << 30):  # value larger than cap
                cap *= 4
                continue
            if rc == -2:
                raise TimeoutError(f"store get({key!r}) timed out")
            if rc < 0:
                raise OSError(f"store get({key!r}) failed rc={rc}")
            return bytes(buf[:rc])

    def add(self, key: str, delta: int = 1) -> int:
        chaos.on_store_op("add", key)  # store_flaky injection point
        out = self._lib.tpustore_add(self._h, key.encode(), delta)
        if out == -(2 ** 63):
            raise OSError(f"store add({key!r}) failed")
        return out

    def check(self, key: str) -> bool:
        chaos.on_store_op("check", key)  # store_flaky injection point
        rc = self._lib.tpustore_check(self._h, key.encode())
        if rc < 0:
            raise OSError(f"store check({key!r}) failed")
        return rc == 1

    def delete(self, key: str) -> None:
        chaos.on_store_op("delete", key)  # store_flaky injection point
        if self._lib.tpustore_delete(self._h, key.encode()) != 0:
            raise OSError(f"store delete({key!r}) failed")

    def barrier(self, name: str, world_size: int, *,
                timeout_ms: int = 60_000) -> None:
        """c10d-style store barrier: count arrivals, wait for the flag.

        Reusable: each call advances a per-name round (all participants
        must call it the same number of times, the usual contract), so
        per-step/per-epoch barriers don't see stale flags.
        """
        rnd = self._barrier_round.get(name, 0)
        self._barrier_round[name] = rnd + 1
        arrived = self.add(f"__barrier__/{name}/{rnd}/count", 1)
        flag = f"__barrier__/{name}/{rnd}/done"
        if arrived == world_size:
            self.set(flag, b"1")
        else:
            self.get(flag, timeout_ms=timeout_ms)

    def close(self) -> None:
        if self._h:
            self._lib.tpustore_disconnect(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Data generation
# ---------------------------------------------------------------------------

def gen_templates(seed: int, num_classes: int, shape: tuple[int, ...],
                  *, threads: int = 0) -> np.ndarray:
    lib = load_library()
    elems = int(np.prod(shape))
    out = np.empty((num_classes, elems), np.float32)
    lib.datagen_templates(
        seed, num_classes, elems,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        threads or _default_threads(),
    )
    return out.reshape((num_classes, *shape))


def gen_images(seed: int, step: int, batch: int, templates: np.ndarray,
               noise: float, *, threads: int = 0
               ) -> tuple[np.ndarray, np.ndarray]:
    lib = load_library()
    templates = np.ascontiguousarray(templates, np.float32)
    num_classes = templates.shape[0]
    shape = templates.shape[1:]
    elems = int(np.prod(shape))
    x = np.empty((batch, elems), np.float32)
    y = np.empty((batch,), np.int32)
    lib.datagen_images(
        seed, step, batch, elems, num_classes, noise,
        templates.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        threads or _default_threads(),
    )
    return x.reshape((batch, *shape)), y


def gen_lm(seed: int, step: int, batch: int, seq_len: int, vocab: int,
           a: int, c: int, noise_frac: float, *, threads: int = 0
           ) -> np.ndarray:
    """Returns (batch, seq_len+1) int32 tokens."""
    lib = load_library()
    out = np.empty((batch, seq_len + 1), np.int32)
    lib.datagen_lm(
        seed, step, batch, seq_len, vocab, a, c, noise_frac,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        threads or _default_threads(),
    )
    return out


def _default_threads() -> int:
    import os

    return min(8, os.cpu_count() or 1)
