"""Chaos engine: seeded, deterministic fault injection (ISSUE 3).

The detection half of the failure story (heartbeats, flight recorder,
cross-rank doctor — PR 1/2) is only as trustworthy as the faults it has
been shown. This module is the production-style fault *injector*: a
``TPUNN_CHAOS=<spec>`` env contract parsed once per process into a
:class:`ChaosEngine`, with hook points wired into the Trainer step loop
(:func:`on_step`), the collective wrappers
(``ops.collectives._record`` → :func:`on_collective`), the checkpoint
writer (``train.checkpoint`` → :func:`on_checkpoint_saved`), and the
native-store client (``runtime.native.StoreClient`` →
:func:`on_store_op`).

Spec grammar (faults joined by ``;``)::

    spec  := fault (";" fault)*
    fault := kind ["@" key "=" value (":" key "=" value)*]

    crash@step=7[:rank=1][:inc=0]        os._exit(CRASH_EXIT_CODE) at the
                                         start of step 7
    hang@collective=all_reduce[:step=5][:rank=0][:ms=...]
                                         sleep inside the collective
                                         wrapper (default: effectively
                                         forever) — the deadlocked-psum
                                         stand-in
    slow@rank=2:ms=200[:step=...]        sleep ms per step — straggler
    preempt@step=9[:rank=...][:inc=...]  SIGTERM to self — preemption
                                         notice (graceful-save path)
    corrupt_ckpt@step=6[:rank=...]       garble the just-saved step's
                                         array files — torn checkpoint
    store_flaky@p=0.1[:rank=...]         each store op raises OSError
                                         with probability p (seeded)
    serve_reject@p=0.3[:rank=...]        serving admission control sheds
                                         each arriving request with
                                         probability p (seeded) — the
                                         overload/load-shed drill for
                                         serve/scheduler.py
    kill_replica@replica=1[:after_s=2][:step=...]
                                         raise ReplicaKillError in the
                                         replica's driver loop — the
                                         fleet crash-failover drill
                                         (serve/fleet.py); after_s gates
                                         on wall time since arming,
                                         step on the replica's round
    hang_replica@replica=1[:ms=...][:step=...]
                                         sleep inside the replica's
                                         driver loop (default:
                                         effectively forever) — the
                                         replica's heartbeat goes
                                         stale and the fleet's
                                         FailureDetector flags it
    kill_coordinator@after_s=2[:rank=...]
                                         raise CoordinatorKillError in
                                         the fleet coordinator's poll
                                         loop once after_s seconds have
                                         passed since arming — the
                                         coordinator crash-recovery
                                         drill (serve/procfleet.py):
                                         workers keep running, the
                                         supervision loop dies
    store_partition@ms=500[:rank=K][:after_s=...]
                                         from the first store op on
                                         (optionally gated by after_s),
                                         EVERY store op raises OSError
                                         for a deterministic ms window
                                         — the transient-partition
                                         drill the heartbeat/publisher
                                         hardening must absorb
    evict_prefix@p=0.5[:rank=...]        each prefix-cache admission
                                         sheds the cached blocks it
                                         would have matched with
                                         probability p (seeded) — the
                                         residency drill: hits degrade
                                         to re-prefills, outputs must
                                         stay golden
                                         (serve/prefix_cache.py)
    tenant_flood@tenant=burst:rps=50[:after_s=...]
                                         one tenant's flash crowd: the
                                         serving engine owes synthetic
                                         requests for this tenant at
                                         rps (wall-clock since arming)
                                         — the quota/fairness drill for
                                         serve/scheduler.py
    kill_transfer@step=2[:replica=K][:after_s=...]
                                         raise TransferKillError inside
                                         the KV block-streaming choke
                                         point on the step-th transfer
                                         (process-wide ordinal,
                                         1-based; replica= narrows to
                                         one source replica) — the
                                         mid-transfer-death drill for
                                         the disaggregated fleet
                                         (serve/disagg.py): the request
                                         must re-prefill on a survivor,
                                         output bit-identical
    corrupt_wire@seq=N[:p=...]           declare KV wire chunk seq N
                                         torn on the pull side
                                         (checksum-failed). seq= alone
                                         fires ONCE — the bounded
                                         re-pull succeeds; with p= the
                                         chunk re-tears with
                                         probability p on every
                                         attempt (p=1: re-pulls
                                         exhaust and the decode
                                         replica degrades to a cold
                                         re-prefill); p= alone tears
                                         each chunk with probability p
                                         (seeded) — the torn-wire
                                         drill for serve/kv_wire.py
    store_partition@ms=500:window=transfer
                                         narrow the partition to the
                                         KV transfer window: only
                                         kvwire/* store ops raise, the
                                         window opening on the first
                                         such op — the mid-stream
                                         partition drill (bounded
                                         re-pull then cold re-prefill,
                                         never a wedged request)
    flip@replica=K[:step=N][:after_s=...]
                                         flip ONE emitted token id on
                                         replica K (once; step= keys on
                                         the replica's decode round) —
                                         the silent-corruption drill
                                         for Lighthouse (obs/audit.py):
                                         every metric stays green, only
                                         the output is wrong, and the
                                         audit layer must detect the
                                         fingerprint divergence, page,
                                         and quarantine the replica

``rank`` / ``inc`` (incarnation, from ``TPUNN_RESTART``) are optional
filters; a fault without them fires in every process / incarnation.
Collective names are the wrapper verbs (``all_reduce``, ``all_gather``,
``reduce_scatter``, ``broadcast``, ``ppermute``, ``all_to_all``).

Design contract (lint-enforced by tests/test_quality.py):

- **inert when unset**: every ``on_*`` hook's first statement is the
  ``_engine is None`` fast path — no parsing, no allocation, no env
  read on the hot path when chaos is off;
- **forensically visible**: every injected fault goes through
  :meth:`ChaosEngine._emit`, which lands a ``chaos`` event in the
  flight ring and bumps ``chaos_injected_total`` — post-mortems can
  never misattribute an injected fault to a real one;
- **deterministic**: ``store_flaky`` draws from a ``random.Random``
  seeded by ``(TPUNN_CHAOS_SEED, rank)``, so a rerun injects the same
  fault sequence.

Stdlib + obs-only on purpose (no jax): faults fire from signal-adjacent
paths and worker subprocesses that must not touch the backend.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal
import time

from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry

log = logging.getLogger(__name__)

ENV_CHAOS = "TPUNN_CHAOS"
ENV_CHAOS_SEED = "TPUNN_CHAOS_SEED"

# distinct from shell/signal conventions and the graceful-preempt code
# (runtime.failure.GRACEFUL_EXIT_CODE): a chaos crash must read as a
# plain worker crash to the agent
CRASH_EXIT_CODE = 43

# "forever" for an injected hang, far past any watchdog window
DEFAULT_HANG_MS = 3_600_000.0

FAULT_KINDS = ("crash", "hang", "slow", "preempt", "corrupt_ckpt",
               "store_flaky", "serve_reject", "kill_replica",
               "hang_replica", "kill_coordinator", "store_partition",
               "evict_prefix", "tenant_flood", "kill_transfer",
               "corrupt_wire", "flip")

_INT_KEYS = ("step", "rank", "inc", "replica", "seq")
_FLOAT_KEYS = ("ms", "p", "after_s", "rps")
_STR_KEYS = ("collective", "tenant", "window")

# store_partition window= values: which store-op slice the partition
# covers ("" = every op; "transfer" = only kvwire/* keys)
_PARTITION_WINDOWS = ("transfer",)


class ReplicaKillError(RuntimeError):
    """Raised by an injected ``kill_replica`` fault inside a replica's
    driver loop. Thread-backed replicas cannot ``os._exit`` (that would
    take the whole fleet down instead of one replica); the fleet
    supervisor catches this — like any other worker exception — and
    runs the failover path."""


class TransferKillError(RuntimeError):
    """Raised by an injected ``kill_transfer`` fault inside the KV
    block-streaming choke point (``ops.collectives.kv_transfer``): the
    source replica "dies" with the transfer half on the wire. The
    disaggregated fleet owns the failover — it declares the source dead
    and the in-flight request re-prefills cold on a survivor."""


class CoordinatorKillError(RuntimeError):
    """Raised by an injected ``kill_coordinator`` fault inside the
    process-fleet coordinator's poll loop. The coordinator's
    supervision thread dies on it — beats stop, polling stops — while
    the replica worker *processes* keep serving, which is exactly the
    crash shape the recovery path (``ProcessFleet.recover``) must
    re-adopt from."""


@dataclasses.dataclass
class Fault:
    kind: str
    spec: str  # the fault's own slice of the spec string (diagnostics)
    step: int | None = None
    rank: int | None = None
    inc: int | None = None
    collective: str = ""
    ms: float = 0.0
    p: float = 0.0
    replica: int | None = None
    after_s: float = 0.0
    tenant: str = ""
    rps: float = 0.0
    seq: int | None = None
    window: str = ""


def parse_spec(spec: str) -> list[Fault]:
    """Parse a ``TPUNN_CHAOS`` spec; raises ``ValueError`` with the
    offending token on any grammar violation (a typo'd chaos spec must
    fail loudly, not silently inject nothing)."""
    faults: list[Fault] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition("@")
        kind = kind.strip()
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown chaos fault {kind!r} in {part!r}; "
                f"have {FAULT_KINDS}"
            )
        fault = Fault(kind=kind, spec=part)
        for field in filter(None, rest.split(":")):
            key, eq, value = field.partition("=")
            if not eq:
                raise ValueError(f"chaos field {field!r} in {part!r} "
                                 f"is not key=value")
            key = key.strip()
            value = value.strip()
            if key not in _INT_KEYS + _FLOAT_KEYS + _STR_KEYS:
                raise ValueError(f"unknown chaos key {key!r} in {part!r}")
            try:
                if key in _INT_KEYS:
                    setattr(fault, key, int(value))
                elif key in _FLOAT_KEYS:
                    setattr(fault, key, float(value))
                else:
                    setattr(fault, key, value)
            except ValueError:
                raise ValueError(
                    f"bad value for chaos key {key!r} in {part!r}: "
                    f"{value!r}"
                ) from None
        _validate(fault)
        faults.append(fault)
    if not faults:
        raise ValueError(f"empty chaos spec {spec!r}")
    return faults


def _validate(fault: Fault) -> None:
    need = {
        "crash": ("step",), "preempt": ("step",),
        "corrupt_ckpt": ("step",), "hang": ("collective",),
        "slow": ("ms",), "store_flaky": ("p",),
        "serve_reject": ("p",),
        "kill_replica": ("replica",), "hang_replica": ("replica",),
        "kill_coordinator": ("after_s",), "store_partition": ("ms",),
        "evict_prefix": ("p",), "tenant_flood": ("tenant", "rps"),
        "kill_transfer": ("step",), "corrupt_wire": (),
        "flip": ("replica",),
    }[fault.kind]
    for key in need:
        missing = (getattr(fault, key) in (None, "", 0.0)
                   if key in ("collective", "ms", "p", "after_s",
                              "tenant", "rps")
                   else getattr(fault, key) is None)
        if missing:
            raise ValueError(
                f"chaos fault {fault.spec!r} needs {key}= "
                f"(e.g. {fault.kind}@{key}=...)"
            )
    if fault.kind in ("store_flaky", "serve_reject", "evict_prefix") \
            and not 0.0 < fault.p <= 1.0:
        raise ValueError(
            f"{fault.kind} p must be in (0, 1], got {fault.p}")
    if fault.kind == "tenant_flood" and fault.rps < 0.0:
        raise ValueError(
            f"tenant_flood rps must be > 0, got {fault.rps}")
    if fault.kind == "corrupt_wire":
        if fault.seq is None and not fault.p:
            raise ValueError(
                f"chaos fault {fault.spec!r} needs seq= or p= "
                f"(e.g. corrupt_wire@seq=1)")
        if fault.p and not 0.0 < fault.p <= 1.0:
            raise ValueError(
                f"corrupt_wire p must be in (0, 1], got {fault.p}")
    if fault.window and fault.kind != "store_partition":
        raise ValueError(
            f"chaos key window= only applies to store_partition, "
            f"not {fault.kind!r}")
    if fault.window and fault.window not in _PARTITION_WINDOWS:
        raise ValueError(
            f"unknown store_partition window {fault.window!r}; "
            f"have {_PARTITION_WINDOWS}")


class ChaosEngine:
    """One process's parsed fault set + fire-once bookkeeping.

    Hook methods are called through the module-level ``on_*`` wrappers
    (never directly from library code) so the disabled fast path stays
    a single attribute check.
    """

    def __init__(self, faults: list[Fault], *, rank: int,
                 incarnation: int = 0, seed: int = 0) -> None:
        self.faults = list(faults)
        self.rank = rank
        self.incarnation = incarnation
        self.seed = seed
        # deterministic per-(seed, rank) stream: reruns inject the same
        # store_flaky sequence on every rank
        self._rng = random.Random((seed << 8) ^ rank)
        self._fired: set[int] = set()  # fault ids that fire once
        self._step = 0  # last step seen via on_step
        self._t0 = time.monotonic()  # armed-at (after_s= gates)
        # store_partition: fault id -> window-close time (monotonic);
        # the window opens on the first matching store op
        self._partition_until: dict[int, float] = {}
        # tenant_flood: fault id -> synthetic requests already owed
        self._flood_sent: dict[int, int] = {}
        # kill_transfer: process-wide KV-transfer ordinal (1-based)
        self._transfers = 0

    def _matches(self, fault: Fault, *, step: int | None = None) -> bool:
        if fault.rank is not None and fault.rank != self.rank:
            return False
        if fault.inc is not None and fault.inc != self.incarnation:
            return False
        if fault.step is not None and step is not None \
                and fault.step != step:
            return False
        return True

    def _emit(self, fault: Fault, *, step: int | None = None,
              note: str = "") -> None:
        """Every injected fault is observable: a ``chaos`` event in the
        flight ring (post-mortems see it) + a labelled counter."""
        flight.record("chaos", fault.kind,
                      step=self._step if step is None else step,
                      note=note or fault.spec)
        get_registry().counter(
            "chaos_injected_total", "chaos faults injected",
            labels=("kind",)).inc(kind=fault.kind)
        log.warning("chaos: injecting %s (rank %d, step %d)",
                    fault.spec, self.rank, step if step is not None
                    else self._step)

    # -- hook bodies -----------------------------------------------------

    def step(self, step: int) -> None:
        self._step = int(step)
        for i, fault in enumerate(self.faults):
            if not self._matches(fault, step=step):
                continue
            if fault.kind == "slow":
                self._inject_slow(fault)
            elif i in self._fired:
                continue
            elif fault.kind == "crash":
                self._fired.add(i)
                self._inject_crash(fault)
            elif fault.kind == "preempt":
                self._fired.add(i)
                self._inject_preempt(fault)

    def collective(self, op: str) -> None:
        for i, fault in enumerate(self.faults):
            if (fault.kind != "hang" or i in self._fired
                    or fault.collective != op
                    or not self._matches(fault, step=self._step)):
                continue
            self._fired.add(i)
            self._inject_hang(fault)

    def checkpoint_saved(self, manager, step: int) -> None:
        for i, fault in enumerate(self.faults):
            if (fault.kind != "corrupt_ckpt" or i in self._fired
                    or not self._matches(fault, step=step)):
                continue
            self._fired.add(i)
            self._inject_corrupt_ckpt(fault, manager, step)

    def store_op(self, op: str, key: str = "") -> None:
        for i, fault in enumerate(self.faults):
            if not self._matches(fault):
                continue
            if fault.kind == "store_flaky":
                if self._rng.random() < fault.p:
                    self._inject_store_flaky(fault, op, key)
            elif fault.kind == "store_partition":
                if fault.window == "transfer" and "kvwire/" not in key:
                    # narrowed partition: only the KV transfer wire is
                    # unreachable; coordination traffic flows
                    continue
                now = time.monotonic()
                if fault.after_s and now - self._t0 < fault.after_s:
                    continue
                if i not in self._fired:
                    # window opens on the first eligible store op and
                    # closes ms later — deterministic, clock-driven
                    self._fired.add(i)
                    self._partition_until[i] = now + fault.ms / 1000.0
                if now < self._partition_until[i]:
                    self._inject_store_partition(fault, op, key)

    def coordinator_poll(self) -> None:
        """Fleet-coordinator poll hook: kill the coordinator (once)
        after ``after_s`` seconds of armed wall time. Raises
        :class:`CoordinatorKillError` out of the poll loop — workers
        are separate processes and never see it."""
        for i, fault in enumerate(self.faults):
            if (fault.kind != "kill_coordinator" or i in self._fired
                    or not self._matches(fault)):
                continue
            if time.monotonic() - self._t0 < fault.after_s:
                continue
            self._fired.add(i)
            self._inject_kill_coordinator(fault)

    def admit(self, request_id: str = "") -> bool:
        """Serving admission hook: True = shed this request."""
        for fault in self.faults:
            if fault.kind != "serve_reject" or not self._matches(fault):
                continue
            if self._rng.random() < fault.p:
                self._inject_serve_reject(fault, request_id)
                return True
        return False

    def prefix_evict(self) -> bool:
        """Prefix-cache admission hook: True = shed the cached blocks
        this admission would have matched (the residency drill)."""
        for fault in self.faults:
            if fault.kind != "evict_prefix" or not self._matches(fault):
                continue
            if self._rng.random() < fault.p:
                self._inject_evict_prefix(fault)
                return True
        return False

    def tenant_flood(self) -> list[tuple[str, int]]:
        """Serving step hook: ``[(tenant, n_owed), ...]`` synthetic
        requests the engine must submit now. Owed count is wall-clock
        (``rps * seconds since arming``) minus what was already owed —
        a compile-stalled step grants the whole backlog at once, which
        is exactly a flash crowd's shape."""
        owed: list[tuple[str, int]] = []
        now = time.monotonic()
        for i, fault in enumerate(self.faults):
            if fault.kind != "tenant_flood" or not self._matches(fault):
                continue
            if fault.after_s and now - self._t0 < fault.after_s:
                continue
            due = int((now - self._t0 - fault.after_s) * fault.rps)
            sent = self._flood_sent.get(i, 0)
            if due > sent:
                self._flood_sent[i] = due
                self._inject_tenant_flood(fault, due - sent)
                owed.append((fault.tenant, due - sent))
        return owed

    def replica_round(self, replica: int, round_: int) -> None:
        """Fleet replica-driver hook: kill/hang one replica. Both fire
        once; ``step=`` keys on the replica's own round counter and
        ``after_s=`` on wall time since the engine armed."""
        for i, fault in enumerate(self.faults):
            if (fault.kind not in ("kill_replica", "hang_replica")
                    or i in self._fired or fault.replica != replica
                    or not self._matches(fault, step=round_)):
                continue
            if fault.after_s \
                    and time.monotonic() - self._t0 < fault.after_s:
                continue
            self._fired.add(i)
            if fault.kind == "kill_replica":
                self._inject_kill_replica(fault, replica)
            else:
                self._inject_hang_replica(fault, replica)

    def flip_token(self, replica: int, step: int) -> bool:
        """Serving token-collect hook (flip): True = the engine must
        perturb the token it just fetched for this ``replica``'s
        ``step``-th decode round. Fires once; ``step=`` keys on the
        replica's own round counter, ``after_s=`` on wall time since
        arming. The engine owns the actual bit-flip — chaos only
        declares it, forensically (emit-first), so Lighthouse's later
        divergence page can never be mistaken for real HBM rot."""
        for i, fault in enumerate(self.faults):
            if (fault.kind != "flip" or i in self._fired
                    or fault.replica != replica
                    or not self._matches(fault, step=step)):
                continue
            if fault.after_s \
                    and time.monotonic() - self._t0 < fault.after_s:
                continue
            self._fired.add(i)
            self._inject_flip(fault, replica, step)
            return True
        return False

    def transfer(self, src: int, dst: int) -> None:
        """KV block-streaming hook (kill_transfer). ``step=`` keys on
        the process-wide transfer ordinal (1-based: the Nth transfer),
        ``replica=`` optionally narrows to one *source* replica index.
        Fires once; raises :class:`TransferKillError` mid-transfer."""
        self._transfers += 1
        for i, fault in enumerate(self.faults):
            if (fault.kind != "kill_transfer" or i in self._fired
                    or (fault.replica is not None
                        and fault.replica != src)
                    or not self._matches(fault, step=self._transfers)):
                continue
            if fault.after_s \
                    and time.monotonic() - self._t0 < fault.after_s:
                continue
            self._fired.add(i)
            self._inject_kill_transfer(fault, src, dst)

    def wire_chunk(self, seq: int) -> bool:
        """KV wire pull-side hook (corrupt_wire): True = treat this
        chunk read as torn (checksum-failed). ``seq=`` alone fires
        once (the re-pull succeeds); with ``p=`` the chunk re-tears
        with probability p per attempt; ``p=`` alone tears any chunk
        with probability p (seeded)."""
        for i, fault in enumerate(self.faults):
            if fault.kind != "corrupt_wire" or not self._matches(fault):
                continue
            if fault.seq is not None and fault.seq != seq:
                continue
            if fault.p:
                if self._rng.random() < fault.p:
                    self._inject_corrupt_wire(fault, seq)
                    return True
                continue
            if i in self._fired:
                continue
            self._fired.add(i)
            self._inject_corrupt_wire(fault, seq)
            return True
        return False

    # -- injections (each one _emits first: lint-enforced) ---------------

    def _inject_crash(self, fault: Fault) -> None:
        self._emit(fault)
        # the ring must reach disk: os._exit skips excepthooks/atexit
        flight.dump_now(f"chaos:{fault.spec}", force=True)
        os._exit(CRASH_EXIT_CODE)

    def _inject_hang(self, fault: Fault) -> None:
        self._emit(fault)
        time.sleep((fault.ms or DEFAULT_HANG_MS) / 1000.0)

    def _inject_slow(self, fault: Fault) -> None:
        self._emit(fault)
        time.sleep(fault.ms / 1000.0)

    def _inject_preempt(self, fault: Fault) -> None:
        self._emit(fault)
        # the real preemption notice: the worker's SIGTERM handler
        # (runtime.failure) finishes the step, saves, exits graceful
        os.kill(os.getpid(), signal.SIGTERM)

    def _inject_corrupt_ckpt(self, fault: Fault, manager,
                             step: int) -> None:
        self._emit(fault, step=step)
        manager.wait()  # the torn step must be fully on disk first
        corrupt_step_dir(os.path.join(str(manager.directory), str(step)))

    def _inject_store_flaky(self, fault: Fault, op: str,
                            key: str) -> None:
        self._emit(fault, note=f"{fault.spec} [{op} {key}]")
        raise OSError(f"chaos: injected store fault on {op}({key!r})")

    def _inject_serve_reject(self, fault: Fault,
                             request_id: str) -> None:
        # emit-first (lint): the shed itself happens in the scheduler,
        # which turns this hook's True into a counted rejection — the
        # flight ring must already hold the injection when it does
        self._emit(fault, note=f"{fault.spec} [{request_id}]")

    def _inject_kill_replica(self, fault: Fault, replica: int) -> None:
        self._emit(fault, note=f"{fault.spec} [replica {replica}]")
        raise ReplicaKillError(
            f"chaos: injected kill on replica {replica}")

    def _inject_kill_coordinator(self, fault: Fault) -> None:
        self._emit(fault)
        # the ring must reach disk NOW: the recovered coordinator's
        # obs_doctor pass names the gap from this dump
        flight.dump_now(f"chaos:{fault.spec}", force=True)
        raise CoordinatorKillError(
            "chaos: injected coordinator kill")

    def _inject_store_partition(self, fault: Fault, op: str,
                                key: str) -> None:
        self._emit(fault, note=f"{fault.spec} [{op} {key}]")
        raise OSError(
            f"chaos: store partitioned, {op}({key!r}) unreachable")

    def _inject_evict_prefix(self, fault: Fault) -> None:
        # emit-first (lint): the eviction itself happens in the prefix
        # cache, which counts it through _account("evict") — the flight
        # ring must already hold the injection when it does
        self._emit(fault)

    def _inject_tenant_flood(self, fault: Fault, n: int) -> None:
        # emit-first (lint): the engine owns the synthetic submissions,
        # each one counted through the scheduler like real traffic
        self._emit(fault, note=f"{fault.spec} [+{n} req]")

    def _inject_kill_transfer(self, fault: Fault, src: int,
                              dst: int) -> None:
        self._emit(fault, note=f"{fault.spec} [r{src}->r{dst}]")
        raise TransferKillError(
            f"chaos: injected kill mid-transfer r{src}->r{dst}")

    def _inject_corrupt_wire(self, fault: Fault, seq: int) -> None:
        # emit-first (lint): the torn read itself is kv_wire.pull's to
        # handle (bounded re-pull, then cold re-prefill) — the flight
        # ring must already hold the injection when it does
        self._emit(fault, note=f"{fault.spec} [chunk {seq}]")

    def _inject_flip(self, fault: Fault, replica: int,
                     step: int) -> None:
        # emit-first (lint): the perturbation itself happens in the
        # engine's token collect — the flight ring must already name
        # this as an *injected* flip when Lighthouse's divergence page
        # fires, or the drill would be indistinguishable from real rot
        self._emit(fault, step=step,
                   note=f"{fault.spec} [replica {replica}]")

    def _inject_hang_replica(self, fault: Fault, replica: int) -> None:
        self._emit(fault, note=f"{fault.spec} [replica {replica}]")
        # the driver thread wedges here; its heartbeat's progress
        # watchdog goes quiet and the fleet's FailureDetector flags the
        # replica stale. The fleet abandons the thread (daemon) — when
        # the sleep ends it must observe its stop flag and exit without
        # touching the engine a successor replica replaced.
        time.sleep((fault.ms or DEFAULT_HANG_MS) / 1000.0)


def corrupt_step_dir(step_dir: str) -> int:
    """Garble every array payload under one checkpoint step directory
    (same length, garbage bytes), leaving commit metadata intact so the
    step still *looks* valid — the torn-write failure mode
    ``CheckpointManager.restore`` must survive. Returns files touched."""
    touched = 0
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for name in filenames:
            if name.startswith(("_", ".")) or "METADATA" in name.upper():
                continue  # keep the step listed; tear only the payload
            path = os.path.join(dirpath, name)
            try:
                size = max(os.path.getsize(path), 4)
                garbage = (b"\xde\xc0\xad\xde" * (size // 4 + 1))[:size]
                with open(path, "r+b") as f:
                    f.write(garbage)
                touched += 1
            except OSError:
                continue
    return touched


# ---------------------------------------------------------------------------
# Module singleton + the hot-path hooks
# ---------------------------------------------------------------------------

_engine: ChaosEngine | None = None


def maybe_init(spec: str | None = None, *, rank: int | None = None,
               incarnation: int | None = None,
               seed: int | None = None) -> ChaosEngine | None:
    """Build the process engine from ``TPUNN_CHAOS`` (or an explicit
    ``spec``). No-op (and allocation-free beyond one env read) when the
    env is unset; idempotent when set."""
    global _engine
    if _engine is not None:
        return _engine
    spec = os.environ.get(ENV_CHAOS) if spec is None else spec
    if not spec:
        return None
    _engine = ChaosEngine(
        parse_spec(spec),
        rank=flight.default_rank() if rank is None else rank,
        incarnation=int(os.environ.get("TPUNN_RESTART", "0"))
        if incarnation is None else incarnation,
        seed=int(os.environ.get(ENV_CHAOS_SEED, "0"))
        if seed is None else seed,
    )
    log.warning("chaos engine armed: %s (rank %d, incarnation %d)",
                spec, _engine.rank, _engine.incarnation)
    return _engine


def enabled() -> bool:
    return _engine is not None


def engine() -> ChaosEngine | None:
    return _engine


def reset() -> None:
    """Disarm (test isolation)."""
    global _engine
    _engine = None


def on_step(step: int) -> None:
    """Trainer step-loop hook (crash / slow / preempt)."""
    if _engine is None:
        return
    _engine.step(step)


def on_collective(op: str) -> None:
    """``ops.collectives._record`` hook (hang)."""
    if _engine is None:
        return
    _engine.collective(op)


def on_checkpoint_saved(manager, step: int) -> None:
    """``train.checkpoint.CheckpointManager.save`` hook (corrupt_ckpt)."""
    if _engine is None:
        return
    _engine.checkpoint_saved(manager, step)


def on_store_op(op: str, key: str = "") -> None:
    """``runtime.native.StoreClient`` hook (store_flaky)."""
    if _engine is None:
        return
    _engine.store_op(op, key)


def on_admit(request_id: str = "") -> bool:
    """``serve.scheduler`` admission hook (serve_reject).

    Returns True when chaos says to shed this request; the scheduler
    owns the actual rejection (counted + flight-visible there too)."""
    if _engine is None:
        return False
    return _engine.admit(request_id)


def on_coordinator_poll() -> None:
    """``serve.procfleet`` coordinator poll-loop hook
    (kill_coordinator). May raise :class:`CoordinatorKillError` — the
    coordinator's supervision thread dies on it while worker processes
    keep serving; recovery is ``ProcessFleet.recover``'s job."""
    if _engine is None:
        return
    _engine.coordinator_poll()


def on_prefix_evict() -> bool:
    """``serve.prefix_cache`` admission hook (evict_prefix).

    True when chaos says to shed the cached blocks this admission
    would have hit; the prefix cache owns the actual eviction (counted
    + flight-visible there too)."""
    if _engine is None:
        return False
    return _engine.prefix_evict()


def on_tenant_flood() -> list[tuple[str, int]]:
    """``serve.engine`` step hook (tenant_flood): the synthetic
    flash-crowd submissions owed now, as ``[(tenant, count), ...]``.
    The engine submits them through the normal scheduler path so the
    quota/fairness machinery sees real counted traffic."""
    if _engine is None:
        return []
    return _engine.tenant_flood()


def on_transfer(src: int = -1, dst: int = -1) -> None:
    """``ops.collectives.kv_transfer`` hook (kill_transfer). May raise
    :class:`TransferKillError` with the payload half-shipped — the
    disaggregated fleet (:mod:`serve.disagg`) owns the failover: the
    source replica is declared dead and the request re-prefills cold
    on a survivor, stitched output still bit-identical."""
    if _engine is None:
        return
    _engine.transfer(src, dst)


def on_wire_chunk(seq: int) -> bool:
    """``serve.kv_wire`` pull-side hook (corrupt_wire).

    True when chaos says this chunk read is torn (checksum-failed);
    kv_wire owns the response — a bounded re-pull, then graceful
    degradation to a cold re-prefill on the decode replica."""
    if _engine is None:
        return False
    return _engine.wire_chunk(seq)


def on_flip_token(replica: int, step: int) -> bool:
    """``serve.engine`` token-collect hook (flip).

    True when chaos says to perturb the one token this replica just
    fetched this round; the engine owns the actual flip (the corrupted
    id flows into the slot, the JSONL record, and the fingerprint
    chain like a real silent corruption would). Lighthouse
    (:mod:`obs.audit`) owns detection and quarantine."""
    if _engine is None:
        return False
    return _engine.flip_token(replica, step)


def on_replica_round(replica: int, round_: int) -> None:
    """``serve.fleet`` replica-driver hook (kill_replica /
    hang_replica). Called once per driver-loop iteration, outside the
    engine's ``_decode_round`` hot loop (its lint bans extras there).
    May raise :class:`ReplicaKillError` (crash drill) or block (hang
    drill) — the fleet supervisor owns the failover either way."""
    if _engine is None:
        return
    _engine.replica_round(replica, round_)
