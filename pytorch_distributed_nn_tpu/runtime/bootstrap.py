"""Process bootstrap — the ``torchrun`` / ``dist.init_process_group``
replacement (SURVEY.md §3.5: TCPStore rendezvous → backend pg → barrier).

TPU-native flow: each host process calls :func:`initialize` once;
``jax.distributed.initialize`` connects to the coordinator (rank 0), PJRT
enumerates the local chips, and the global device list becomes visible to
every process. Environment variables mirror the reference's contract
(``RANK``/``WORLD_SIZE``/``MASTER_ADDR``/``MASTER_PORT`` — SURVEY.md §1
Launch row) with JAX-native names taking precedence.
"""

from __future__ import annotations

import dataclasses
import logging
import os

import jax

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ProcessInfo:
    process_index: int
    process_count: int
    coordinator: str
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_index == 0


def _env(*names: str, default: str | None = None) -> str | None:
    for name in names:
        if name in os.environ:
            return os.environ[name]
    return default


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> ProcessInfo:
    """Initialize multi-process JAX. Single-process (the common test and
    single-host case) needs no rendezvous and is a no-op.

    Resolution order for each field: explicit argument → JAX-native env var
    (``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``) → the
    reference's torch-style env contract (``MASTER_ADDR:MASTER_PORT`` /
    ``WORLD_SIZE`` / ``RANK``).
    """
    if num_processes is None:
        raw = _env("NUM_PROCESSES", "WORLD_SIZE", default="1")
        num_processes = int(raw)
    if process_id is None:
        process_id = int(_env("PROCESS_ID", "RANK", default="0"))
    if coordinator_address is None:
        coordinator_address = _env("COORDINATOR_ADDRESS")
        if coordinator_address is None:
            addr = _env("MASTER_ADDR", default="127.0.0.1")
            port = _env("MASTER_PORT", default="12355")
            coordinator_address = f"{addr}:{port}"

    if num_processes > 1:
        log.info(
            "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
            coordinator_address, num_processes, process_id,
        )
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    # Under the elastic agent (launch.py) this starts the liveness
    # heartbeat; a plain launch has no store env and it is a no-op.
    from . import failure

    failure.maybe_start_heartbeat(rank=process_id)

    return ProcessInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        coordinator=coordinator_address,
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )


def shutdown() -> None:
    if jax.process_count() > 1:
        jax.distributed.shutdown()
