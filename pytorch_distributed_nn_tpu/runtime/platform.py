"""Platform selection for entrypoints.

This container's ``sitecustomize`` registers the TPU backend at interpreter
start and overwrites ``jax_platforms`` (to ``axon,cpu``), so the standard
``JAX_PLATFORMS=cpu`` env contract is silently ignored by the time any
script body runs. Entrypoints call :func:`apply_platform_overrides` first
thing to re-assert the user's env intent through ``jax.config`` (effective
until the first backend use).
"""

from __future__ import annotations

import os

import jax


def apply_platform_overrides() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms and jax.config.jax_platforms != platforms:
        jax.config.update("jax_platforms", platforms)
    n_cpu = os.environ.get("JAX_NUM_CPU_DEVICES")
    if n_cpu:
        try:
            jax.config.update("jax_num_cpu_devices", int(n_cpu))
        except AttributeError:
            # older jax: no such option; the XLA flag is equivalent and
            # read at backend init (which hasn't happened yet here)
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={int(n_cpu)}"
            )
