"""Failure detection — heartbeats over the native rendezvous store.

The reference ecosystem's failure story is ``torchrun``'s elastic agent:
a supervisor process watches workers and tears the job down (or restarts
it) when one dies or hangs (SURVEY.md §5 "Failure detection" row; §2b
"torchrun elastic agent / c10d TCPStore" row). The TPU-native equivalent
here has two halves:

- **Worker side** (:class:`HeartbeatReporter`): a daemon thread that
  writes ``hb/<incarnation>/<rank> -> monotonic-ish wall time`` into the
  job's store every ``interval`` seconds. :func:`maybe_start_heartbeat`
  is called from :func:`runtime.bootstrap.initialize`, so any worker
  launched by the elastic agent heartbeats automatically. Two modes:

  - *liveness* (default): the thread beats as long as the process is
    up — catches crashed-but-not-exited and SIGSTOP-frozen workers.
  - *progress watchdog* (``progress_window_s`` set, from the agent's
    ``--progress-timeout``): once armed by the first
    :func:`notify_progress` call, the thread goes silent unless
    application code has called :func:`notify_progress` within the
    window (before that it beats as pure liveness, so an arbitrarily
    long first-step trace+compile is not mistaken for a hang). The
    training loop calls it once per completed step, so a worker whose
    main thread is stuck inside a hung collective stops beating even
    though the daemon thread itself is fine — this is what makes a
    deadlocked ``psum`` detectable at all (the daemon thread alone
    would happily beat forever under it).

- **Supervisor side** (:class:`FailureDetector`): polls those keys and
  reports still-running ranks whose last beat is older than
  ``timeout`` — the hang detector that exit-code monitoring alone
  cannot provide (a deadlocked collective never exits).

Both halves speak to the C++ store (native/store.cpp) through the ctypes
bindings in :mod:`runtime.native`; the store is the same one used for
rank rendezvous, so no extra service is needed.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time

from . import native
from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry

log = logging.getLogger(__name__)


def count_store_error(op: str) -> None:
    """One transient store-op failure absorbed as a counted retry
    (``store_errors_total{op}``) instead of a dead daemon thread or a
    silent drop — the heartbeat/publisher hardening contract. ``op``
    names the caller's operation (``beat``, ``publish``, ``dump_poll``),
    not the wire verb."""
    get_registry().counter(
        "store_errors_total",
        "transient store failures absorbed as counted retries",
        labels=("op",)).inc(op=op)


_RAISE = object()  # store_call sentinel: re-raise on deadline


def store_call(fn, *, op: str, deadline_s: float = 5.0,
               base_s: float = 0.01, max_s: float = 0.25,
               seed: int = 0, on_retry=None, fallback=_RAISE):
    """THE counted retry helper for one store operation on a path that
    must survive a partition window (the KV transfer wire, daemon
    publish loops): call ``fn()`` until it returns, retrying
    ``OSError``/``TimeoutError`` with exponential backoff + seeded
    jitter, each failure counted in ``store_errors_total{op}``.

    Semantics:

    - every failed attempt bumps ``store_errors_total{op}`` and (when
      given) calls ``on_retry()`` — the hook kv_wire uses to bump its
      own ``kv_wire_retries_total{op}`` without a second ``except``
      site (the lint contract: this function is the only
      ``except OSError`` on the transfer path);
    - backoff is ``min(base_s * 2**attempt, max_s)`` scaled by a
      jitter factor in ``[0.5, 1.5)`` drawn from a ``random.Random``
      seeded by ``(seed, op)`` — deterministic per (seed, op) stream,
      so a rerun retries on the same schedule;
    - ``deadline_s`` bounds the whole call: once it elapses the last
      error re-raises to the caller — or, when ``fallback=`` is given,
      returns that value instead, which is how callers own graceful
      degradation (kv_wire's pull passes ``fallback=None`` and turns a
      dead wire into a cold re-prefill — a bounded failure, never a
      wedged request) without growing a second ``except`` site.
    """
    rng = random.Random((int(seed) << 16) ^ (hash(op) & 0xFFFF))
    deadline = time.monotonic() + float(deadline_s)
    attempt = 0
    while True:
        try:
            return fn()
        except (OSError, TimeoutError):
            count_store_error(op)
            if on_retry is not None:
                on_retry()
            now = time.monotonic()
            if now >= deadline:
                if fallback is not _RAISE:
                    return fallback
                raise
            delay = min(base_s * (2.0 ** attempt), max_s)
            delay *= 0.5 + rng.random()
            time.sleep(min(delay, max(deadline - now, 0.0)))
            attempt += 1

# Environment contract between the elastic agent and its workers.
ENV_STORE_PORT = "TPUNN_STORE_PORT"
ENV_STORE_HOST = "TPUNN_STORE_HOST"
ENV_RESTART = "TPUNN_RESTART"          # incarnation index (0 on first launch)
ENV_HB_INTERVAL = "TPUNN_HEARTBEAT_INTERVAL"
ENV_PROGRESS_WINDOW = "TPUNN_PROGRESS_WINDOW"
ENV_PREEMPT = "TPUNN_PREEMPT"  # "1" forces preemption handling on

# Worker exit code for a *graceful* preemption exit (SIGTERM → finish
# the in-flight step → synchronous checkpoint save → exit). The elastic
# agent restarts on it WITHOUT charging the restart budget — a
# preempted worker did nothing wrong. Distinct from chaos.CRASH_EXIT_CODE
# and outside the 128+N signal-kill convention.
GRACEFUL_EXIT_CODE = 83


def _hb_key(incarnation: int, rank: int) -> str:
    return f"hb/{incarnation}/{rank}"


def _flight_dump_key(incarnation: int) -> str:
    """Supervisor→worker flight-dump request over the heartbeat store.
    The heartbeat daemon thread serves it — the one thread guaranteed
    alive when the main thread is wedged inside a hung collective."""
    return f"flight/dump/{incarnation}"


class HeartbeatReporter:
    """Worker-side daemon thread: periodic ``set(hb/<inc>/<rank>, now)``.

    With ``progress_window_s`` set, beats are suppressed once
    :meth:`notify_progress` has not been called for that long (progress
    watchdog mode — see module docstring).
    """

    def __init__(self, client: native.StoreClient, *, rank: int,
                 incarnation: int = 0, interval_s: float = 1.0,
                 progress_window_s: float | None = None) -> None:
        self._client = client
        self.rank = rank
        self.incarnation = incarnation
        self._key = _hb_key(incarnation, rank)
        self._dump_key = _flight_dump_key(incarnation)
        self._dump_served = False
        self._was_suppressed = False
        self._interval = interval_s
        self._window = progress_window_s
        # observability counters (obs/runtime_gauges.py reads these):
        # beats written, beats withheld by the watchdog, last beat time
        self._beats = 0
        self._suppressed = 0
        self.store_errors = 0  # beats absorbed as counted retries
        self._last_beat: float | None = None
        # None until the first notify_progress: the watchdog only arms
        # once a step has completed, so an arbitrarily long first-step
        # trace+compile can't read as a hang and livelock the restarts
        # (until then, beats are pure process liveness).
        self._last_progress: float | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeat-r{rank}", daemon=True
        )
        self.beat()  # one synchronous beat so the detector sees us at once
        self._thread.start()

    @property
    def client(self) -> native.StoreClient:
        """The live store connection (obs/aggregate.py publishes
        snapshots through it — same handle, thread-safe)."""
        return self._client

    def beat(self) -> None:
        now = time.time()
        self._client.set(self._key, repr(now).encode())
        self._beats += 1
        self._last_beat = now

    def stats(self) -> dict:
        """Liveness counters for the metric registry: seconds since the
        last beat, beats written, watchdog-suppressed beats."""
        now = time.time()
        return {
            "age_s": (now - self._last_beat
                      if self._last_beat is not None else -1.0),
            "beats": self._beats,
            "suppressed": self._suppressed,
            "store_errors": self.store_errors,
        }

    def notify_progress(self) -> None:
        """Application-level liveness: the step loop moved forward."""
        self._last_progress = time.time()

    def disarm(self) -> None:
        """Back to liveness-only (training loop exited): post-loop work
        of unbounded length — checkpoint drains, eval — must not read
        as a hang."""
        self._last_progress = None

    def _maybe_serve_dump_request(self) -> None:
        """Serve a supervisor-initiated flight-dump request (launch.py
        sets the key when FailureDetector sees stale ranks). Runs on
        this daemon thread precisely because the main thread may be
        stuck inside the hung collective being diagnosed."""
        if self._dump_served:
            return
        try:
            if not self._client.check(self._dump_key):
                return
            reason = self._client.get(
                self._dump_key, timeout_ms=1000).decode("utf-8", "replace")
        except (OSError, TimeoutError):
            count_store_error("dump_poll")
            return
        self._dump_served = True
        flight.dump_now(f"supervisor:{reason}", force=True)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._maybe_serve_dump_request()
            except Exception:  # a dump must never kill the beat thread
                log.exception("flight dump request handling failed")
            if (self._window is not None
                    and self._last_progress is not None
                    and time.time() - self._last_progress > self._window):
                if not self._was_suppressed:
                    # first watchdog trip: the main loop stopped making
                    # progress — capture the ring NOW, while the hung
                    # collective is still the newest entry
                    self._was_suppressed = True
                    flight.dump_now("progress_watchdog")
                self._suppressed += 1
                continue  # main thread looks stuck: go silent, get flagged
            self._was_suppressed = False
            try:
                self.beat()
            except (OSError, TimeoutError):
                # Transient store failure (partition, flake, a
                # supervisor mid-teardown): a missed beat must degrade
                # to a counted retry, never kill this thread — a beat
                # thread that died during a 500 ms partition would
                # leave a perfectly healthy worker reading as hung
                # forever after. A store that is truly gone keeps the
                # counter climbing while the supervisor-side staleness
                # math does its job.
                self.store_errors += 1
                count_store_error("beat")

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2 * self._interval)
        if self._thread.is_alive():
            # Beat thread is wedged inside a store call; closing now
            # would free the C handle under it. Leak the connection —
            # the process is exiting anyway.
            return
        self._client.close()


_reporter: HeartbeatReporter | None = None


def maybe_start_heartbeat(rank: int | None = None) -> HeartbeatReporter | None:
    """Start heartbeating iff launched under the elastic agent.

    Reads the agent's env contract; a plain (non-agent) launch has no
    ``TPUNN_STORE_PORT`` and this is a no-op. Idempotent.
    """
    global _reporter
    if _reporter is not None:
        return _reporter
    port = os.environ.get(ENV_STORE_PORT)
    if not port:
        return None
    if rank is None:
        rank = int(os.environ.get("PROCESS_ID", os.environ.get("RANK", "0")))
    window = os.environ.get(ENV_PROGRESS_WINDOW)
    try:
        client = native.StoreClient(
            os.environ.get(ENV_STORE_HOST, "127.0.0.1"), int(port)
        )
        # OSError can come from the constructor's first beat when the
        # agent is tearing the store down at this very moment; a dying
        # job must not gain a worker traceback on top.
        _reporter = HeartbeatReporter(
            client,
            rank=rank,
            incarnation=int(os.environ.get(ENV_RESTART, "0")),
            interval_s=float(os.environ.get(ENV_HB_INTERVAL, "1.0")),
            progress_window_s=float(window) if window else None,
        )
    except (native.NativeUnavailable, ConnectionError, OSError) as e:
        log.warning("heartbeat disabled: %s", e)
        return None
    # flight-recorder dump triggers ride the agent contract: fatal
    # signals + unhandled exceptions dump the ring, and the flight
    # watchdog dumps when no event lands for a progress window (a
    # collective that never completes stops the event stream)
    flight.install_crash_hooks()
    if window:
        flight.start_watchdog(float(window))
    return _reporter


def reporter() -> HeartbeatReporter | None:
    """The live worker-side reporter, if the agent started one."""
    return _reporter


def heartbeat_stats() -> dict | None:
    """This worker's liveness counters; None outside the agent."""
    return _reporter.stats() if _reporter is not None else None


def notify_progress() -> None:
    """Per-step hook for training loops; no-op outside the agent."""
    if _reporter is not None:
        _reporter.notify_progress()


def notify_done() -> None:
    """Loop-exit hook: disarm the progress watchdog; no-op outside the
    agent."""
    if _reporter is not None:
        _reporter.disarm()


# ---------------------------------------------------------------------------
# Worker-side preemption handling (SIGTERM → cooperative graceful exit)
# ---------------------------------------------------------------------------

_preempt_flag = threading.Event()
_preempt_prev_handler = None
_preempt_installed = False


def install_preemption_handler(force: bool = False) -> bool:
    """SIGTERM becomes a *preemption notice* instead of an immediate
    kill: the handler only sets a flag (and snapshots the flight ring);
    the training loop notices it at the next step boundary, forces a
    synchronous checkpoint save, and exits ``GRACEFUL_EXIT_CODE``.

    Installed only when it can matter: under the elastic agent
    (``TPUNN_STORE_PORT`` set — the agent classifies the graceful code)
    or when ``TPUNN_PREEMPT=1`` / ``force`` asks for it (bare runs on
    preemptible VMs). Main-thread only (signal API constraint);
    idempotent. Returns True when the handler is active."""
    global _preempt_installed, _preempt_prev_handler
    if _preempt_installed:
        return True
    if not force and not os.environ.get(ENV_STORE_PORT) \
            and os.environ.get(ENV_PREEMPT, "0") != "1":
        return False

    def _handler(signum, frame):
        # flag-only + ring snapshot: no locks we might already hold
        # beyond what the flight dump path has always taken
        _preempt_flag.set()
        try:
            flight.dump_now("preempt:SIGTERM", force=True)
        except Exception:
            pass

    try:
        _preempt_prev_handler = signal.signal(signal.SIGTERM, _handler)
    except ValueError:  # not the main thread
        return False
    _preempt_installed = True
    return True


def uninstall_preemption_handler() -> None:
    """Restore the previous SIGTERM disposition (Trainer.close)."""
    global _preempt_installed, _preempt_prev_handler
    if not _preempt_installed:
        return
    try:
        signal.signal(signal.SIGTERM, _preempt_prev_handler)
    except (ValueError, TypeError):
        pass
    _preempt_installed = False
    _preempt_prev_handler = None
    _preempt_flag.clear()


def preempt_requested() -> bool:
    """True once a preemption notice (SIGTERM) has arrived."""
    return _preempt_flag.is_set()


def request_preemption() -> None:
    """Programmatic preemption notice (tests / cluster integrations that
    learn about preemption out-of-band rather than via SIGTERM)."""
    _preempt_flag.set()


class FailureDetector:
    """Supervisor-side staleness check over the workers' heartbeat keys.

    Node-local by design: each elastic agent hosts its own store and
    watches only the ranks it spawned (crashes/hangs on other nodes are
    that node's agent's job; cross-node teardown rides the job-level
    restart because a killed gang takes the JAX coordinator down with
    it).
    """

    def __init__(self, client: native.StoreClient, *, ranks: list[int],
                 incarnation: int, timeout_s: float) -> None:
        self._client = client
        self._ranks = list(ranks)
        self._incarnation = incarnation
        self._timeout = timeout_s
        self._first_seen: dict[int, float] = {}
        # rank -> number of times it has been reported stale (the
        # supervisor-side missed-beat gauge, obs/runtime_gauges.py)
        self.missed_counts: dict[int, int] = {r: 0 for r in self._ranks}

    def any_beats(self) -> bool:
        """Whether ANY watched rank has ever heartbeaten this
        incarnation — the restart policy's fail-fast discriminator
        (a gang that died before its first beat is a startup crash,
        not a mid-training fault)."""
        try:
            return any(a is not None
                       for a in self.last_beat_ages().values())
        except OSError:
            return False

    def last_beat_ages(self) -> dict[int, float | None]:
        """Per-rank seconds since the last beat (None = never beaten) —
        the raw staleness signal behind :meth:`stale_ranks`, exported
        as gauges by obs/runtime_gauges.export_detector_gauges."""
        now = time.time()
        ages: dict[int, float | None] = {}
        for rank in self._ranks:
            key = _hb_key(self._incarnation, rank)
            if self._client.check(key):
                ages[rank] = now - float(
                    self._client.get(key, timeout_ms=1000))
            else:
                ages[rank] = None
        return ages

    def request_flight_dump(self, reason: str) -> bool:
        """Ask every worker to dump its flight ring (served by each
        worker's heartbeat daemon thread — see
        :meth:`HeartbeatReporter._maybe_serve_dump_request`). Called by
        the agent when stale ranks are detected, BEFORE the gang is
        killed. Returns False when the store write fails (a dying store
        must not mask the hang report)."""
        try:
            self._client.set(_flight_dump_key(self._incarnation),
                             reason.encode())
            return True
        except OSError as e:
            log.warning("flight dump request failed: %s", e)
            return False

    def stale_ranks(self, alive: set[int] | None = None) -> list[int]:
        """Ranks whose heartbeat is older than the timeout.

        ``alive`` — ranks whose process is still running; ranks not in
        it have exited and are the exit-code watcher's business, not
        ours (a worker that finished cleanly stops beating and must not
        read as hung). A rank that has never beaten is only stale once
        it has been up longer than the timeout (startup grace: workers
        need time to import jax and connect).
        """
        now = time.time()
        stale = []
        for rank in self._ranks:
            if alive is not None and rank not in alive:
                continue
            key = _hb_key(self._incarnation, rank)
            if self._client.check(key):
                last = float(self._client.get(key, timeout_ms=1000))
                if now - last > self._timeout:
                    stale.append(rank)
            else:
                first = self._first_seen.setdefault(rank, now)
                if now - first > self._timeout:
                    stale.append(rank)
        for rank in stale:
            self.missed_counts[rank] = self.missed_counts.get(rank, 0) + 1
        return stale
