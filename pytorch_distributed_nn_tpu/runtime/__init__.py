"""Runtime layer: device topology, process bootstrap, launch, checkpoint,
profiling — the TPU-native replacement for the reference's launch scripts,
``torchrun`` rendezvous, and c10d process-group plumbing (SURVEY.md §1
"Launch / CLI" and "Communication backend" rows)."""

from pytorch_distributed_nn_tpu.runtime.mesh import (
    AXES,
    MeshSpec,
    make_mesh,
    make_abstract_mesh,
)

__all__ = ["AXES", "MeshSpec", "make_mesh", "make_abstract_mesh"]
