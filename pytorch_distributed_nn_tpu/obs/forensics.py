"""Cross-rank flight-dump forensics: who stalled, where, on what.

The producer side (:mod:`obs.flight` + the dump triggers in
:mod:`runtime.failure` / :mod:`launch`) leaves one
``flight_rank<k>.json`` per worker. This module is the consumer: load
the per-rank dumps, align their collective streams by position, find
the **first divergent collective** (a rank that never recorded it —
the stall point — or ranks that recorded *different* ops/bytes at the
same position — a desync), classify the failure (hang vs crash vs
straggler), and render per-rank step-time percentiles so a slow rank
stands out even when nothing diverged.

Alignment contract: collective records are compared by their *position
in the per-rank collective stream*, not by raw ``seq`` (raw seqs can
drift when ranks record rank-local events like checkpoint metadata);
an SPMD program records the same collective stream on every rank, so
position i on rank a and position i on rank b are the same program
point. The first position where any rank is missing, or where the
``(op, axis, nbytes)`` signatures disagree, is the divergence.

Stdlib-only (like :mod:`obs.flight`): the doctor must run on a dev box
with nothing but the dumps.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re

from pytorch_distributed_nn_tpu.obs.stats import percentile

_COLLECTIVE_KINDS = ("collective",)
_CRASH_REASON = re.compile(r"^(exception:|signal:SIGABRT|chaos:crash)")
_HANG_REASON = re.compile(
    r"^(progress_watchdog|flight_watchdog|supervisor:)")
# graceful preemption (runtime.failure SIGTERM handler / Trainer's
# graceful-exit dump): a verdict of its own — NOT a crash, NOT a hang
_PREEMPT_REASON = re.compile(r"^preempt:")

# a rank whose median step time exceeds the cross-rank median by this
# factor is flagged a straggler
STRAGGLER_FACTOR = 1.5


@dataclasses.dataclass
class RankDump:
    rank: int
    reason: str
    reasons: list[str]
    dumped_at: float
    dropped: int
    events: list[dict]
    path: str = ""

    @property
    def collectives(self) -> list[dict]:
        return [e for e in self.events if e.get("kind")
                in _COLLECTIVE_KINDS]

    @property
    def steps(self) -> list[dict]:
        return [e for e in self.events if e.get("kind") == "step"]

    @property
    def chaos_events(self) -> list[dict]:
        """Injected faults (runtime/chaos.py) recorded in this rank's
        ring — surfaced so a post-mortem never misattributes a test
        fault to a production failure."""
        return [e for e in self.events if e.get("kind") == "chaos"]

    @property
    def alert_events(self) -> list[dict]:
        """Watchtower alerts (obs/watchtower.py) that fired before the
        dump — the online detector's verdicts ride the ring so the
        doctor sees what the run already knew about itself."""
        return [e for e in self.events if e.get("kind") == "alert"]

    @property
    def xray_events(self) -> list[dict]:
        """Profiler-capture lifecycle (obs/xray.py): every anomaly-
        triggered capture emits ``capture`` (with the landing dir in the
        note) before the profiler starts and ``capture_done`` after —
        so the doctor can point the operator at the device trace that
        covers the incident window."""
        return [e for e in self.events if e.get("kind") == "xray"]

    @property
    def autoscale_events(self) -> list[dict]:
        """Helm decisions (serve/autoscale.py) that landed before the
        dump — emit-first means every scale_up/scale_down/hold is in
        the ring, so a post-mortem sees what the autoscaler did (and
        why) around the incident window."""
        return [e for e in self.events if e.get("kind") == "autoscale"]

    @property
    def fleet_events(self) -> list[dict]:
        """Replica-fleet lifecycle (serve/fleet.py): state changes,
        replica_down, re-admissions, reloads. A fleet failover dump is
        diagnosed from these — which replica died and which requests it
        stranded."""
        return [e for e in self.events if e.get("kind") == "fleet"]

    @property
    def meter_events(self) -> list[dict]:
        """Abacus charges (obs/meter.py) in this rank's ring — every
        billed amount rides the ring (emit-first choke point), so a
        post-mortem sees who was being billed for what right up to the
        crash."""
        return [e for e in self.events if e.get("kind") == "meter"]

    @property
    def trace_events(self) -> list[dict]:
        """Causeway spans (obs/trace.py) in the ring before the dump —
        emit-first puts every completed segment here, so a post-mortem
        names the exact traces in flight when the process died. The
        note leads with the trace_id (``<trace> leg=<n> <request>``)."""
        return [e for e in self.events if e.get("kind") == "trace"]

    def last_event(self) -> dict | None:
        return self.events[-1] if self.events else None

    def incomplete(self) -> list[dict]:
        """Events begun but never completed — a collective here is the
        hang's smoking gun ("enqueued, never completed")."""
        return [e for e in self.events if e.get("t1") is None]


def load_dump(path: str) -> RankDump:
    with open(path) as f:
        d = json.load(f)
    return RankDump(
        rank=int(d.get("rank", 0)),
        reason=str(d.get("reason", "")),
        reasons=[str(r) for r in d.get("reasons", [])] or
                ([str(d["reason"])] if d.get("reason") else []),
        dumped_at=float(d.get("dumped_at", 0.0)),
        dropped=int(d.get("dropped", 0)),
        events=list(d.get("events", [])),
        path=path,
    )


def find_dump_paths(directory: str) -> list[str]:
    """All ``flight_rank*.json`` under a run directory, rank order."""
    paths = glob.glob(os.path.join(directory, "flight_rank*.json"))

    def _rank(p):
        m = re.search(r"flight_rank(\d+)\.json$", p)
        return int(m.group(1)) if m else 1 << 30

    return sorted(paths, key=_rank)


def load_dumps(paths_or_dir) -> dict[int, RankDump]:
    """{rank: dump} from explicit paths or a directory."""
    if isinstance(paths_or_dir, (str, os.PathLike)):
        paths = find_dump_paths(str(paths_or_dir))
    else:
        paths = [str(p) for p in paths_or_dir]
    out: dict[int, RankDump] = {}
    for p in paths:
        d = load_dump(p)
        # duplicate rank files: keep the freshest dump
        if d.rank not in out or d.dumped_at > out[d.rank].dumped_at:
            out[d.rank] = d
    return out


# ---------------------------------------------------------------------------
# Divergence: the first collective the ranks disagree on
# ---------------------------------------------------------------------------

def _signature(ev: dict) -> tuple:
    return (ev.get("op", ""), ev.get("axis", ""),
            int(ev.get("nbytes", 0)))


@dataclasses.dataclass
class Divergence:
    index: int  # position in the per-rank collective stream
    kind: str  # "missing" | "mismatch"
    missing_ranks: list[int]
    per_rank: dict[int, dict]  # present ranks' event at this position

    def reference(self) -> dict:
        """A surviving rank's view of the divergent collective."""
        return next(iter(self.per_rank.values()), {})


def find_divergence(dumps: dict[int, RankDump]) -> Divergence | None:
    """First collective-stream position where ranks disagree; None when
    every rank recorded an identical stream.

    A ring that wrapped (``dropped > 0``) starts mid-program, so
    position-0 alignment no longer holds; wrapped dumps are re-aligned
    on the first *step* every rank still fully holds (step markers ride
    the events), falling back to tail-truncation when no step numbers
    are available."""
    if not dumps:
        return None
    streams = {r: d.collectives for r, d in dumps.items()}
    if any(d.dropped for d in dumps.values()):
        mins = [min((e.get("step", -1) for e in s), default=-1)
                for s in streams.values() if s]
        start = max(mins, default=-1) + 1  # skip the torn wrap step
        aligned = {r: [e for e in s if e.get("step", -1) >= start]
                   for r, s in streams.items()}
        if any(aligned.values()):
            # an empty aligned stream = that rank stopped before the
            # common step window even began: missing at position 0
            streams = aligned
        else:  # step numbers absent/degenerate: best-effort tail align
            shortest = min(len(s) for s in streams.values())
            streams = {r: s[len(s) - shortest:] for r, s in
                       streams.items()}
    longest = max(len(s) for s in streams.values())
    for i in range(longest):
        present = {r: s[i] for r, s in streams.items() if i < len(s)}
        missing = sorted(r for r, s in streams.items() if i >= len(s))
        if missing:
            return Divergence(index=i, kind="missing",
                              missing_ranks=missing, per_rank=present)
        if len({_signature(e) for e in present.values()}) > 1:
            return Divergence(index=i, kind="mismatch",
                              missing_ranks=[], per_rank=present)
    return None


# ---------------------------------------------------------------------------
# Single-ring attribution (the watchtower's page-alert classifier)
# ---------------------------------------------------------------------------

_RANK_IN_SPEC = re.compile(r"\brank=(\d+)\b")


def attribute(events: list[dict]) -> dict:
    """Name the suspect from ONE ring's events (no cross-rank dumps
    yet): the last incomplete collective (a hang's smoking gun), the
    last shed/evicted request, and any injected chaos faults — with the
    chaos spec's ``rank=`` parsed out so a synthetic straggler points at
    the injected rank. Timestamp-free on purpose: the watchtower embeds
    this in alerts that must be byte-identical across replays."""
    out: dict = {"suspect_rank": None, "suspect_collective": "",
                 "suspect_request": "", "chaos_kinds": [],
                 "incomplete_collectives": 0}
    chaos = [e for e in events if e.get("kind") == "chaos"]
    out["chaos_kinds"] = sorted({e.get("op", "") for e in chaos})
    for e in chaos:
        m = _RANK_IN_SPEC.search(e.get("note", ""))
        if m:
            out["suspect_rank"] = int(m.group(1))
    incomplete = [e for e in events
                  if e.get("kind") in _COLLECTIVE_KINDS
                  and e.get("t1") is None]
    out["incomplete_collectives"] = len(incomplete)
    if incomplete:
        out["suspect_collective"] = incomplete[-1].get("op", "")
    for e in events:
        if e.get("kind") == "serve" and \
                str(e.get("op", "")).startswith(("reject:", "evict:")):
            out["suspect_request"] = e.get("note", "")
    # fleet failover (serve/fleet.py): name the dead replica and the
    # requests it stranded. Keys are CONDITIONAL — non-fleet rings keep
    # their existing attribution dict byte-identical (replay contract).
    downs = [e for e in events if e.get("kind") == "fleet"
             and e.get("op") == "replica_down"]
    if downs:
        replica, stranded = _parse_replica_down(downs[-1])
        out["dead_replica"] = replica
        out["stranded_requests"] = stranded
    # coordinator lifecycle (serve/procfleet.py): a supervision gap is
    # a suspect in its own right — replicas keep decoding through it,
    # but nothing finalizes, restarts, or scales until a successor
    # takes over. Same conditional-key contract as fleet above.
    gaps = [e for e in events if e.get("kind") == "fleet"
            and e.get("op") == "coordinator_gap"]
    if gaps:
        out["coordinator_gap_s"] = _parse_gap_s(gaps[-1])
    # xray capture (obs/xray.py): the device trace that covers the
    # incident window. Same conditional-key contract as fleet above.
    caps = [e for e in events if e.get("kind") == "xray"
            and e.get("op") == "capture"]
    if caps:
        note = str(caps[-1].get("note", ""))
        out["xray_capture"] = note.rsplit(" -> ", 1)[-1] if note else ""
    # Abacus billing (obs/meter.py): name the top-billing tenant from
    # the ring's FLOP charges — a cost_anomaly page lands here with the
    # tenant that was spending the machine when it fired. Same
    # conditional-key contract: unmetered rings stay byte-identical.
    flops_by_tenant: dict[str, int] = {}
    for e in events:
        if e.get("kind") == "meter" and e.get("op") == "flops":
            tenant = str(e.get("note", "")).rsplit(":", 1)[0]
            flops_by_tenant[tenant] = (flops_by_tenant.get(tenant, 0)
                                       + int(e.get("nbytes", 0)))
    if flops_by_tenant:
        top = max(sorted(flops_by_tenant), key=flops_by_tenant.get)
        out["top_billing_tenant"] = top
        out["top_billing_flops"] = flops_by_tenant[top]
    return out


def _parse_gap_s(ev: dict) -> float:
    """Supervision-gap seconds from a fleet coordinator_gap event note
    (``gap_s=1.234 inc=2``)."""
    m = re.search(r"gap_s=([0-9.]+)", str(ev.get("note", "")))
    return float(m.group(1)) if m else 0.0


def _parse_replica_down(ev: dict) -> tuple[str, list[str]]:
    """('r1', ['freq-3', ...]) from a fleet replica_down event note
    (``r1 reason=... stranded=freq-3,freq-5``)."""
    note = str(ev.get("note", ""))
    replica = note.split(" ", 1)[0] if note else ""
    m = re.search(r"stranded=([^\s]+)", note)
    stranded = [s for s in (m.group(1).split(",") if m else []) if s]
    return replica, stranded


def fleet_summary(dumps: dict[int, RankDump]) -> dict | None:
    """Aggregate fleet lifecycle across the dumps: dead replicas with
    their stranded requests, re-admission count, reload count, state-
    transition tally. None when no dump holds fleet events (single-
    engine runs stay fleet-silent)."""
    events = [e for d in dumps.values() for e in d.fleet_events]
    if not events:
        return None
    downs, readmits, reloads = [], 0, 0
    coord_ups = coord_downs = 0
    max_gap_s = 0.0
    states: dict[str, int] = {}
    for e in events:
        op = str(e.get("op", ""))
        if op == "replica_down":
            replica, stranded = _parse_replica_down(e)
            downs.append({"replica": replica, "stranded": stranded,
                          "note": e.get("note", "")})
        elif op == "readmit":
            readmits += 1
        elif op == "reload":
            reloads += 1
        elif op == "coordinator_up":
            coord_ups += 1
        elif op == "coordinator_down":
            coord_downs += 1
        elif op == "coordinator_gap":
            max_gap_s = max(max_gap_s, _parse_gap_s(e))
        elif op.startswith("state:"):
            s = op.split(":", 1)[1]
            states[s] = states.get(s, 0) + 1
    summary = {"replicas_down": downs, "readmits": readmits,
               "reloads": reloads, "state_transitions": states}
    # conditional: thread-fleet dumps (no coordinator lifecycle) keep
    # their summary dict unchanged
    if coord_ups or coord_downs or max_gap_s:
        summary["coordinator"] = {"ups": coord_ups,
                                  "downs": coord_downs,
                                  "max_gap_s": max_gap_s}
    return summary


def meter_summary(dumps: dict[int, RankDump]) -> dict | None:
    """Abacus charges (obs/meter.py) across the dumps: per-kind billed
    totals (the ring is the ledger's emit-first shadow) and the top-
    billing tenant by FLOPs. None when no dump holds meter events
    (TPUNN_METER unset stays meter-silent — the doctor's JSON is
    byte-identical to pre-Abacus output)."""
    events = [e for d in dumps.values() for e in d.meter_events]
    if not events:
        return None
    by_kind: dict[str, int] = {}
    flops_by_tenant: dict[str, int] = {}
    for e in events:
        op = str(e.get("op", ""))
        amt = int(e.get("nbytes", 0))
        by_kind[op] = by_kind.get(op, 0) + amt
        if op == "flops":
            tenant = str(e.get("note", "")).rsplit(":", 1)[0]
            flops_by_tenant[tenant] = (flops_by_tenant.get(tenant, 0)
                                       + amt)
    out = {"charges": len(events), "by_kind": by_kind}
    if flops_by_tenant:
        top = max(sorted(flops_by_tenant), key=flops_by_tenant.get)
        out["top_billing_tenant"] = top
        out["top_billing_flops"] = flops_by_tenant[top]
    return out


def trace_summary(dumps: dict[int, RankDump]) -> dict | None:
    """Causeway traces (obs/trace.py) alive in each rank's ring when
    the dump landed: per-rank {trace_id: {segments tally, legs seen}},
    so a post-mortem goes from a crashed rank straight to the request
    waterfalls to pull (``scripts/obs_trace.py``). None when no dump
    holds trace events (TPUNN_TRACE unset stays trace-silent)."""
    out: dict[str, dict] = {}
    for rank, d in sorted(dumps.items()):
        per: dict[str, dict] = {}
        for e in d.trace_events:
            note = str(e.get("note", ""))
            trace_id = note.split(" ", 1)[0]
            if not trace_id:
                continue
            ent = per.setdefault(trace_id, {"segments": {}, "legs": []})
            seg = str(e.get("op", ""))
            ent["segments"][seg] = ent["segments"].get(seg, 0) + 1
            for part in note.split():
                if part.startswith("leg="):
                    leg = int(part[4:])
                    if leg not in ent["legs"]:
                        ent["legs"].append(leg)
        if per:
            out[str(rank)] = per
    return out or None


# ---------------------------------------------------------------------------
# Straggler report: per-rank step-time percentiles
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StragglerRow:
    rank: int
    steps: int
    p50_s: float
    p90_s: float
    max_s: float
    last_step: int
    last_event_age_s: float  # vs the rank's own dump time
    flagged: bool = False


def straggler_report(dumps: dict[int, RankDump]) -> list[StragglerRow]:
    """Per-rank inter-step wall times from the ``step`` markers. A rank
    whose p50 exceeds the cross-rank median p50 by
    ``STRAGGLER_FACTOR`` is flagged."""
    rows: list[StragglerRow] = []
    for rank in sorted(dumps):
        d = dumps[rank]
        ts = [e["t0"] for e in d.steps]
        deltas = sorted(b - a for a, b in zip(ts, ts[1:]))
        last = d.last_event()
        last_t = (last.get("t1") or last.get("t0")) if last else None
        rows.append(StragglerRow(
            rank=rank,
            steps=len(ts),
            p50_s=percentile(deltas, 0.50),
            p90_s=percentile(deltas, 0.90),
            max_s=deltas[-1] if deltas else 0.0,
            last_step=d.steps[-1]["step"] if d.steps else -1,
            last_event_age_s=(d.dumped_at - last_t
                              if last_t is not None else -1.0),
        ))
    # leave-one-out baseline: each rank is compared against the median
    # of the OTHER ranks (a plain median of 2 ranks lands on the slow
    # rank itself and can never flag it)
    for r in rows:
        others = sorted(o.p50_s for o in rows
                        if o.rank != r.rank and o.steps > 1)
        base = percentile(others, 0.5)
        r.flagged = (base > 0 and r.steps > 1
                     and r.p50_s > STRAGGLER_FACTOR * base)
    return rows


# ---------------------------------------------------------------------------
# Classification: hang vs crash vs straggler
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Classification:
    kind: str  # "hang" | "crash" | "preempt" | "straggler" | "healthy"
    stalled_ranks: list[int]
    crashed_ranks: list[int]
    missing_dumps: list[int]
    divergence: Divergence | None
    detail: str
    # rank -> injected-chaos event count (runtime/chaos.py faults found
    # in the rings; nonzero means the failure was at least partly
    # synthetic)
    chaos_injected: dict[int, int] = dataclasses.field(
        default_factory=dict)


def _chaos_counts(dumps: dict[int, RankDump]) -> dict[int, int]:
    return {r: len(d.chaos_events) for r, d in dumps.items()
            if d.chaos_events}


def _chaos_note(chaos: dict[int, int]) -> str:
    if not chaos:
        return ""
    total = sum(chaos.values())
    return (f" [{total} injected chaos fault(s) in the ring(s) — "
            f"TPUNN_CHAOS run, not an organic failure]")


def classify(dumps: dict[int, RankDump],
             expected_ranks: list[int] | None = None) -> Classification:
    crashed = sorted(r for r, d in dumps.items()
                     if any(_CRASH_REASON.match(x) for x in d.reasons))
    preempted = sorted(r for r, d in dumps.items()
                       if any(_PREEMPT_REASON.match(x)
                              for x in d.reasons))
    hang_evidence = sorted(r for r, d in dumps.items()
                           if any(_HANG_REASON.match(x)
                                  for x in d.reasons))
    missing = sorted(set(expected_ranks or []) - set(dumps))
    div = find_divergence(dumps)
    chaos = _chaos_counts(dumps)

    if crashed:
        return Classification(
            kind="crash", stalled_ranks=[], crashed_ranks=crashed,
            missing_dumps=missing, divergence=div, chaos_injected=chaos,
            detail=f"rank(s) {crashed} dumped on a crash reason "
                   f"({', '.join(dumps[crashed[0]].reasons)})"
                   + _chaos_note(chaos),
        )
    if preempted:
        # graceful preemption: ranks dumped on the SIGTERM-notice path
        # and (if the loop was healthy) saved a final checkpoint. A
        # divergence here is expected — ranks stop at whatever step the
        # notice caught them on — so it must NOT read as a hang.
        return Classification(
            kind="preempt", stalled_ranks=[], crashed_ranks=[],
            missing_dumps=missing, divergence=div, chaos_injected=chaos,
            detail=(f"rank(s) {preempted} exited on a preemption notice "
                    f"(SIGTERM → final checkpoint → graceful exit); "
                    f"restart resumes from the final save")
                   + _chaos_note(chaos),
        )
    if div is not None and div.missing_ranks:
        ref = div.reference()
        return Classification(
            kind="hang", stalled_ranks=div.missing_ranks,
            crashed_ranks=[], missing_dumps=missing, divergence=div,
            chaos_injected=chaos,
            detail=(f"rank(s) {div.missing_ranks} never reached "
                    f"collective #{div.index} "
                    f"(op={ref.get('op')} step={ref.get('step')}) that "
                    f"other ranks enqueued") + _chaos_note(chaos),
        )
    if div is not None:
        return Classification(
            kind="hang", stalled_ranks=[], crashed_ranks=[],
            missing_dumps=missing, divergence=div, chaos_injected=chaos,
            detail=(f"desync at collective #{div.index}: ranks recorded "
                    f"different ops/bytes at the same program point")
                   + _chaos_note(chaos),
        )
    if missing and dumps:
        return Classification(
            kind="crash", stalled_ranks=[], crashed_ranks=missing,
            missing_dumps=missing, divergence=None,
            chaos_injected=chaos,
            detail=(f"rank(s) {missing} left no dump at all (died "
                    f"before any trigger could fire)")
                   + _chaos_note(chaos),
        )
    rows = straggler_report(dumps)
    flagged = [r.rank for r in rows if r.flagged]
    if flagged:
        return Classification(
            kind="straggler", stalled_ranks=flagged, crashed_ranks=[],
            missing_dumps=missing, divergence=None,
            chaos_injected=chaos,
            detail=(f"rank(s) {flagged} run ≥{STRAGGLER_FACTOR}x slower "
                    f"than the median rank (see step percentiles)")
                   + _chaos_note(chaos),
        )
    if hang_evidence:
        # everyone stalled at the same program point: the rank whose
        # event stream went quiet FIRST is the best stall candidate
        ages = {r: d.last_event() for r, d in dumps.items()}
        times = {r: (e.get("t1") or e.get("t0"))
                 for r, e in ages.items() if e}
        first_quiet = (min(times, key=times.get) if times else None)
        return Classification(
            kind="hang",
            stalled_ranks=[first_quiet] if first_quiet is not None
            else [],
            crashed_ranks=[], missing_dumps=missing, divergence=None,
            chaos_injected=chaos,
            detail=("all ranks stalled at the same collective position; "
                    f"rank {first_quiet} went quiet first")
                   + _chaos_note(chaos),
        )
    return Classification(
        kind="healthy", stalled_ranks=[], crashed_ranks=[],
        missing_dumps=missing, divergence=None, chaos_injected=chaos,
        detail="collective streams agree and no crash/hang trigger "
               "fired" + _chaos_note(chaos),
    )


# ---------------------------------------------------------------------------
# Report rendering (the doctor's output)
# ---------------------------------------------------------------------------

def _fmt_event(ev: dict) -> str:
    t1 = ev.get("t1")
    state = "completed" if t1 is not None else "NEVER COMPLETED"
    extra = f" axis={ev['axis']}" if ev.get("axis") else ""
    nb = f" nbytes={ev['nbytes']}" if ev.get("nbytes") else ""
    note = f" [{ev['note']}]" if ev.get("note") else ""
    return (f"seq {ev.get('seq')} {ev.get('kind')}/{ev.get('op')}"
            f" step={ev.get('step')}{extra}{nb}{note} — {state}")


def render_report(dumps: dict[int, RankDump],
                  expected_ranks: list[int] | None = None,
                  last: int = 5) -> str:
    lines: list[str] = []
    out = lines.append
    ranks = sorted(dumps)
    out(f"== flight forensics: {len(dumps)} rank dump(s) "
        f"(ranks {ranks}) ==")
    for r in ranks:
        d = dumps[r]
        out(f"  rank {r}: {len(d.events)} events "
            f"({d.dropped} dropped), reasons: {d.reasons}")

    cls = classify(dumps, expected_ranks)
    out("")
    out(f"classification: {cls.kind.upper()}")
    out(f"  {cls.detail}")
    if cls.stalled_ranks:
        out(f"  stalled rank(s): {cls.stalled_ranks}")
    if cls.crashed_ranks:
        out(f"  crashed/missing rank(s): {cls.crashed_ranks}")

    div = cls.divergence
    if div is not None:
        ref = div.reference()
        out("")
        out(f"first divergent collective: #{div.index} "
            f"op={ref.get('op')} seq={ref.get('seq')} "
            f"step={ref.get('step')}"
            + (f" axis={ref['axis']}" if ref.get("axis") else "")
            + (f" nbytes={ref['nbytes']}" if ref.get("nbytes") else ""))
        for r in sorted(div.per_rank):
            out(f"  rank {r}: {_fmt_event(div.per_rank[r])}")
        for r in div.missing_ranks:
            d = dumps[r]
            tail = d.collectives[-1] if d.collectives else None
            out(f"  rank {r}: MISSING — last collective "
                f"{_fmt_event(tail) if tail else '(none recorded)'}")

    chaos = {r: d.chaos_events for r, d in dumps.items()
             if d.chaos_events}
    if chaos:
        out("")
        out("injected chaos events (TPUNN_CHAOS — synthetic faults, "
            "not organic):")
        for r in sorted(chaos):
            for ev in chaos[r]:
                out(f"  rank {r}: {_fmt_event(ev)}")

    alerts = {r: d.alert_events for r, d in dumps.items()
              if d.alert_events}
    if alerts:
        out("")
        out("watchtower alerts (obs/watchtower.py — fired online, "
            "before the dump):")
        for r in sorted(alerts):
            for ev in alerts[r][-5:]:
                out(f"  rank {r}: {_fmt_event(ev)}")

    fleet = fleet_summary(dumps)
    if fleet is not None:
        out("")
        out("fleet (serve/fleet.py — replica lifecycle in the ring):")
        for down in fleet["replicas_down"]:
            ids = ", ".join(down["stranded"]) or "(none)"
            out(f"  replica {down['replica']} DOWN — stranded "
                f"request(s): {ids}")
        out(f"  re-admissions: {fleet['readmits']}, reloads: "
            f"{fleet['reloads']}, state transitions: "
            f"{fleet['state_transitions']}")
        coord = fleet.get("coordinator")
        if coord:
            out(f"  coordinator: {coord['downs']} down / "
                f"{coord['ups']} up, max supervision gap "
                f"{coord['max_gap_s']:.3f}s — replicas kept decoding "
                f"through the gap; the successor adopted them")

    ms = meter_summary(dumps)
    if ms is not None:
        out("")
        out("abacus billing (obs/meter.py — charges in the ring):")
        kinds = ", ".join(f"{k}={v}" for k, v in
                          sorted(ms["by_kind"].items()))
        out(f"  {ms['charges']} charge(s): {kinds}")
        if "top_billing_tenant" in ms:
            out(f"  top-billing tenant: {ms['top_billing_tenant']} "
                f"({ms['top_billing_flops']} FLOPs)")

    hung = {r: d.incomplete() for r, d in dumps.items()
            if d.incomplete()}
    if hung:
        out("")
        out("in-flight at dump time (begun, never completed):")
        for r in sorted(hung):
            for ev in hung[r][-3:]:
                out(f"  rank {r}: {_fmt_event(ev)}")

    rows = straggler_report(dumps)
    if any(r.steps for r in rows):
        out("")
        out("straggler report (inter-step wall time, seconds):")
        out(f"  {'rank':>4} {'steps':>5} {'p50':>9} {'p90':>9} "
            f"{'max':>9} {'last_step':>9} {'quiet_for':>9}")
        for r in rows:
            flag = "  <-- straggler" if r.flagged else ""
            out(f"  {r.rank:>4} {r.steps:>5} {r.p50_s:>9.4f} "
                f"{r.p90_s:>9.4f} {r.max_s:>9.4f} {r.last_step:>9} "
                f"{r.last_event_age_s:>9.2f}{flag}")

    out("")
    out(f"last {last} events per rank:")
    for r in ranks:
        for ev in dumps[r].events[-last:]:
            out(f"  rank {r}: {_fmt_event(ev)}")
    return "\n".join(lines)
