"""Unified telemetry: metric registry, span tracing, goodput accounting.

One subsystem the whole stack reports into (ISSUE 1), replacing four
disconnected islands (JSONL logger, StepTimer, perfetto parsing, store
heartbeats) with:

- :mod:`obs.registry` — process-wide counters/gauges/histograms with
  Prometheus text exposition and the JSONL sink as backends;
- :mod:`obs.span` — ``with obs.span("data/next_batch"): ...`` Chrome
  trace events per host, free when disabled;
- :mod:`obs.goodput` — per-step wall-time decomposition into
  data/compute/collective/checkpoint/eval/other;
- :mod:`obs.runtime_gauges` — mesh topology + heartbeat state gauges;
- :mod:`obs.aggregate` — cross-host snapshot aggregation through the
  native store;
- :mod:`obs.flight` — the post-mortem flight recorder (ISSUE 2): a
  bounded per-host ring of collective/step/checkpoint/data events,
  dumped to ``flight_rank<k>.json`` on hangs/crashes;
- :mod:`obs.forensics` — cross-rank dump analysis (first divergent
  collective, hang/crash/straggler classification);
- :mod:`obs.stats` — shared stdlib-only percentile/median/MAD/EWMA
  helpers the reporting and detection layers agree on;
- :mod:`obs.watchtower` — online anomaly detection (ISSUE 7): streaming
  detectors over the metric/flight streams raising structured alerts
  (step-time outliers, loss spikes, straggler drift, queue/KV pressure,
  multi-window SLO burn rate), inert unless ``TPUNN_WATCH`` is set;
- :mod:`obs.capacity` — Skyline capacity frontier (ISSUE 11): sweep
  :mod:`serve.traffic` offered-load rungs against a fleet (or the
  deterministic service model), judge each rung with the watchtower's
  burn-rate signal, and emit the max-sustainable-rate frontier, the
  goodput-saturation knee, and the "replicas needed per SLO per
  traffic shape" planning report (``bench.py --capacity``,
  ``scripts/obs_report.py --capacity``);
- :mod:`obs.trace` — Causeway distributed request tracing (ISSUE 16):
  per-request :class:`~obs.trace.TraceContext` minted at submit,
  propagated across scheduler transitions, prefill/decode legs, KV
  transfers, failover re-admissions, and the process-fleet store wire;
  inert unless ``TPUNN_TRACE`` is set;
- :mod:`obs.critpath` — waterfall assembly + critical-path attribution
  over Causeway spans: per-trace segment decomposition
  (queued/prefill/transfer/failover/restore/decode/stitch) that
  provably sums to end-to-end latency, plus the fleet rollup per SLO
  bucket (``scripts/obs_trace.py`` renders both);
- :mod:`obs.meter` — Abacus per-tenant resource metering (ISSUE 17):
  analytic FLOPs, refcount-weighted KV block-seconds, wire bytes, and
  lifecycle wall time attributed to (tenant, request) pairs at the
  engine/scheduler/pool/collective choke points; ledgers publish at
  ``meter/<rank>`` for fleet rollup and feed ``scripts/obs_cost.py``'s
  showback report; inert unless ``TPUNN_METER`` is set;
- :mod:`obs.audit` — Lighthouse output-integrity auditing (ISSUE 19):
  rolling sha1 fingerprint chains over emitted token ids, shadow
  replay of a sampled request slice to a second replica, golden
  probes at idle cadence, and quarantine of a confirmed-diverging
  replica through the counted state choke points; divergence pages
  land in the watchtower and ``scripts/obs_audit.py`` renders the
  integrity report; inert unless ``TPUNN_AUDIT`` is set;
- :mod:`obs.xray` — anomaly-triggered device profiling (ISSUE 10):
  bounded, rate-limited ``jax.profiler`` captures (page/interval/
  on-demand triggers), per-op MFU/roofline attribution, compile
  telemetry feeding the ``recompile_storm`` detector, and the
  ``bench.py --ledger`` perf-regression gate; inert unless
  ``TPUNN_XRAY`` is set.

``scripts/obs_report.py`` renders the JSONL/trace output;
``scripts/obs_doctor.py`` analyzes flight dumps;
``scripts/obs_watch.py`` tails/replays alerts and burn rates;
``scripts/obs_xray.py`` renders capture attribution tables;
``bench.py --goodput`` attaches the breakdown to benchmark records.
"""

from pytorch_distributed_nn_tpu.obs import audit  # noqa: F401
from pytorch_distributed_nn_tpu.obs import critpath  # noqa: F401
from pytorch_distributed_nn_tpu.obs import flight  # noqa: F401
from pytorch_distributed_nn_tpu.obs import meter  # noqa: F401
from pytorch_distributed_nn_tpu.obs import stats  # noqa: F401
from pytorch_distributed_nn_tpu.obs import trace  # noqa: F401
from pytorch_distributed_nn_tpu.obs import watchtower  # noqa: F401
from pytorch_distributed_nn_tpu.obs import xray  # noqa: F401
from pytorch_distributed_nn_tpu.obs.goodput import (  # noqa: F401
    PHASES,
    GoodputMeter,
    StepBreakdown,
)
from pytorch_distributed_nn_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    get_registry,
    reset_registry,
)
from pytorch_distributed_nn_tpu.obs.span import (  # noqa: F401
    current_recorder,
    disable_tracing,
    enable_tracing,
    merge_chrome_traces,
    span,
    tracing_enabled,
    write_trace,
)


def __getattr__(name):
    # capacity pulls in serve/, whose engine imports back through
    # inference.generate -> obs; an eager import here would leave
    # generate partially initialized. Resolve it on first attribute
    # access instead (PEP 562), when both packages are settled.
    if name == "capacity":
        import importlib
        return importlib.import_module(
            "pytorch_distributed_nn_tpu.obs.capacity")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
