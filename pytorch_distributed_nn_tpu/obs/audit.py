"""Lighthouse: online output-integrity auditing.

Every load-bearing invariant in this repo — failover stitching,
prefix-cache restore, disagg handoff, process-fleet re-admission — is
certified by greedy bit-identity goldens, but only in tests. A
production replica that silently corrupts output (flaky HBM, a bad
compile, a torn KV restore) serves wrong tokens with green metrics.
This module turns the bit-identity discipline into an always-on
observability layer with three escalating checks:

1. **Fingerprint chains.** Every request accrues a rolling sha1 chain
   over its emitted token ids (:func:`chain`), computed from the single
   host fetch the engine already does per round — the decode hot loop
   is untouched; the fold happens at retire in
   ``ServingEngine._finish_record`` (the ONE engine call site,
   lint-pinned). The chain is *resumable*: ``chain(chain(s, a), b) ==
   chain(s, a + b)``, so every boundary that rewrites a request
   (failover re-admission, disagg handoff, process-fleet adoption)
   seeds the new leg with the chain over the prefix it carries and the
   final fingerprint is identical to a single uninterrupted leg.
   Fingerprints ride the ``serve_request`` JSONL (``fp`` key, absent
   when unarmed), flight-ring ``audit`` events, Causeway decode spans,
   and — process fleet — a ``fp/<rid>`` store key so worker
   fingerprints are comparable coordinator-side.
2. **Shadow replay.** A deterministic request-id-hash sample
   (``sample=``) of fleet requests is duplicated by the Router onto a
   second READY replica (``Router.place_shadow``). The shadow leg is
   excluded from TTFT histograms (pre-set ``t_first_origin``) and from
   Abacus billing (the reserved :data:`SHADOW_TENANT`). A fingerprint
   mismatch between the legs is tie-broken by a third *referee* leg
   (majority) or the golden-probe record, then raised as a Watchtower
   ``output_divergence`` page naming the disagreeing pair — pages
   auto-dump the flight ring and trigger an Xray capture.
3. **Golden probes.** A background prober pushes a canned prompt
   (:data:`PROBE_PROMPT`) through live replicas at ``probe_every_s``
   idle cadence; the first fingerprint observed per prompt is golden
   and every later disagreement is a confirmed probe failure — so even
   replicas the sample never lands on get audited.

A confirmed-diverging replica transitions to ``QUARANTINED`` through
the fleet's counted ``_set_state`` choke point (``quarantine=1``): the
router excludes it, its in-flight requests re-admit on survivors via
the existing failover machinery (stitched output bit-identical), and
it is never restarted. The ``flip@replica=K[:step=N]`` chaos spec
perturbs one decode-step token to drive the end-to-end drill
(``scripts/obs_audit.py --selftest``).

Arming: ``TPUNN_AUDIT=`` (chaos-style spec grammar):

    TPUNN_AUDIT=1                              # defaults
    TPUNN_AUDIT=sample=1.0:probe_every_s=0.5   # shadow all, fast probes

Design contract (the chaos/watchtower/trace/meter lint rules, enforced
by tests/test_quality.py):

- **Inert when unset.** Every ``on_*`` hook opens with the literal
  ``if _audit is None: return`` — an unset ``TPUNN_AUDIT`` costs one
  global load + one comparison per hook and performs ZERO registry or
  flight-ring writes (instruments are registered at arm time), and no
  ``fp`` key appears on any wire record.
- **Emit-first.** Every audit observation lands in the flight ring
  before the registry sees it (:meth:`AuditEngine._emit`'s first
  statement).
- **Single-homed fingerprints.** The engine folds a request's chain in
  exactly one call site (``_finish_record`` → :func:`on_retire`).

Stdlib-only (no jax, no numpy): ``fleet_worker.py`` imports this
before deciding whether to touch a backend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time

from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.runtime import failure

log = logging.getLogger(__name__)

ENV_AUDIT = "TPUNN_AUDIT"

# the chain seed of a fresh request (no emitted tokens yet)
GENESIS = "0" * 40

# reserved tenant for shadow/probe legs: the scheduler counts it like
# any tenant, but Abacus drops it (the customer is never double-billed
# for an audit duplicate) and the engine skips its TTFT observation
SHADOW_TENANT = "audit-shadow"

# canned golden-probe workload: tiny fixed prompt + budget, token ids
# low enough for every test vocab; greedy decode makes the fingerprint
# deterministic per (model, params)
PROBE_PROMPT = (3, 1, 4, 1, 5)
PROBE_BUDGET = 4


def chain(seed: str, tokens) -> str:
    """Rolling sha1 fingerprint chain over emitted token ids.

    Token-by-token fold so the chain is resumable across request
    rewrites: ``chain(chain(s, a), b) == chain(s, list(a) + list(b))``
    — a re-admitted/handed-off leg seeded with the chain over its
    carried prefix ends at exactly the fingerprint one uninterrupted
    leg would have produced (tests/test_audit.py)."""
    fp = seed or GENESIS
    for t in tokens:
        fp = hashlib.sha1(f"{fp}:{int(t)}".encode("ascii")).hexdigest()
    return fp


@dataclasses.dataclass
class AuditConfig:
    """``TPUNN_AUDIT`` spec knobs (chaos-grammar ``key=value:...``)."""

    sample: float = 0.25     # shadow-replay fraction (request-id hash)
    shadow: int = 1          # 0 disables shadow replay entirely
    probe_every_s: float = 0.0  # golden-probe idle cadence (0 = off)
    quarantine: int = 1      # 0 = page on divergence but never isolate


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(AuditConfig)}


def parse_spec(spec: str) -> AuditConfig:
    """``TPUNN_AUDIT`` spec → :class:`AuditConfig`. ``"1"`` / ``"on"``
    mean defaults; otherwise ``:``-separated ``key=value`` overrides.
    Unknown keys raise (a typo'd audit spec must fail loudly, not
    silently audit nothing — the chaos-spec contract)."""
    cfg = AuditConfig()
    spec = (spec or "").strip()
    if spec in ("", "1", "on", "true"):
        return cfg
    for field in filter(None, spec.split(":")):
        key, eq, value = field.partition("=")
        key = key.strip()
        if not eq or key not in _FIELD_TYPES:
            raise ValueError(
                f"unknown audit key {key!r} in {spec!r}; have "
                f"{sorted(_FIELD_TYPES)}")
        try:
            kind = _FIELD_TYPES[key]
            setattr(cfg, key,
                    value if kind in (str, "str")
                    else int(value) if kind in (int, "int")
                    else float(value))
        except ValueError:
            raise ValueError(
                f"bad value for audit key {key!r}: {value!r}") from None
    if not 0.0 <= cfg.sample <= 1.0:
        raise ValueError(f"sample must be in [0, 1], got {cfg.sample}")
    if cfg.shadow not in (0, 1):
        raise ValueError(f"shadow must be 0 or 1, got {cfg.shadow}")
    if cfg.probe_every_s < 0:
        raise ValueError(
            f"probe_every_s must be >= 0, got {cfg.probe_every_s}")
    if cfg.quarantine not in (0, 1):
        raise ValueError(
            f"quarantine must be 0 or 1, got {cfg.quarantine}")
    return cfg


class AuditEngine:
    """Per-process audit state. One instance per armed process (module
    singleton); an in-process fleet's engines all record into the same
    audit, and the store transport makes worker fingerprints comparable
    coordinator-side."""

    def __init__(self, config: AuditConfig, *, rank: int = 0,
                 metrics=None) -> None:
        self.cfg = config
        self.rank = int(rank)
        self.metrics = metrics  # MetricsLogger | None
        # request_id -> {fp, n, replica} (latest leg wins; the final
        # record IS the full chain because legs are seeded)
        self.fingerprints: dict[str, dict] = {}
        self.goldens: dict[str, str] = {}  # probe key -> golden fp
        self.divergences: list[dict] = []
        self.quarantines: list[dict] = []
        self.probes = 0
        self.probe_failures = 0
        self.last_fp_t = 0.0
        self._published = 0
        reg = get_registry()
        self._c_fps = reg.counter(
            "audit_fingerprints_total",
            "request fingerprints recorded (one per completed leg)")
        self._c_div = reg.counter(
            "audit_divergence_total",
            "confirmed output divergences", labels=("kind",))
        self._c_probe_fail = reg.counter(
            "audit_probe_failures_total",
            "golden-probe fingerprint mismatches")

    # -- the one ring choke point (emit-first, lint-enforced) --------------

    def _emit(self, op: str, *, note: str = "") -> None:
        flight.record("audit", op, note=note)

    # -- fingerprints ------------------------------------------------------

    def sampled(self, request_id: str) -> bool:
        """Deterministic shadow sample: same sha1-hash draw as
        Causeway's sampler, so a request is in or out identically on
        every process that asks."""
        if self.cfg.sample >= 1.0:
            return True
        if self.cfg.sample <= 0.0:
            return False
        h = int(hashlib.sha1(request_id.encode()).hexdigest()[:8], 16)
        return h / float(0xFFFFFFFF) < self.cfg.sample

    def record(self, request_id: str, fp: str, *, n: int = 0,
               replica: str = "") -> None:
        self._emit("fingerprint",
                   note=f"{request_id} {fp[:12]} n={n} {replica}".strip())
        self.fingerprints[request_id] = dict(fp=fp, n=int(n),
                                             replica=str(replica))
        self.last_fp_t = time.time()
        self._c_fps.inc()

    def fingerprint_of(self, request_id: str) -> str | None:
        rec = self.fingerprints.get(request_id)
        return None if rec is None else rec["fp"]

    # -- divergences / probes / quarantine ---------------------------------

    def divergence(self, kind: str, *, request_id: str = "",
                   pair=(), suspect: str = "", note: str = "") -> dict:
        rec = dict(kind=str(kind), request_id=str(request_id),
                   pair=[str(p) for p in pair], suspect=str(suspect))
        self._emit("divergence",
                   note=f"{kind} {request_id} pair={rec['pair']} "
                        f"suspect={suspect} {note}".strip())
        self.divergences.append(rec)
        self._c_div.inc(kind=str(kind))
        if self.metrics is not None:
            self.metrics.emit("audit_divergence", **rec)
        log.warning("audit divergence: %s %s pair=%s suspect=%s",
                    kind, request_id, rec["pair"], suspect)
        return rec

    def probe_result(self, key: str, replica: str, fp: str) -> bool:
        """Compare one probe completion against the golden. The first
        fingerprint observed per probe key BECOMES the golden (greedy
        decode is deterministic per (model, params), so any honest
        replica produces it)."""
        golden = self.goldens.get(key)
        if golden is None:
            self.goldens[key] = fp
            ok = True
        else:
            ok = fp == golden
        self._emit("probe", note=f"{key} {replica} ok={int(ok)}")
        self.probes += 1
        if not ok:
            self.probe_failures += 1
            self._c_probe_fail.inc()
        if self.metrics is not None:
            self.metrics.emit("audit_probe", key=key,
                              replica=str(replica), ok=int(ok))
        return ok

    def quarantined(self, replica: str, reason: str) -> None:
        """Bookkeeping only — the state change itself goes through the
        fleet's counted ``_set_state`` choke point."""
        self._emit("quarantine", note=f"{replica} {reason}".strip())
        self.quarantines.append(dict(replica=str(replica),
                                     reason=str(reason)))

    def summary(self) -> dict:
        return dict(
            fingerprints=len(self.fingerprints),
            divergences=len(self.divergences),
            probes=self.probes,
            probe_failures=self.probe_failures,
            quarantines=list(self.quarantines),
            rank=self.rank,
        )


# ---------------------------------------------------------------------------
# Module singleton + the inert hooks (chaos-style lint contract)
# ---------------------------------------------------------------------------

_audit: AuditEngine | None = None


def maybe_init(spec: str | None = None, *, rank: int | None = None,
               metrics=None,
               config: AuditConfig | None = None) -> AuditEngine | None:
    """Arm the process audit from ``TPUNN_AUDIT`` (or an explicit
    ``spec``/``config``). No-op beyond one env read when unset or
    ``"0"``; idempotent when armed."""
    global _audit
    if _audit is not None:
        return _audit
    spec = os.environ.get(ENV_AUDIT) if spec is None else spec
    if not spec or spec == "0":
        return None
    _audit = AuditEngine(
        config if config is not None else parse_spec(spec),
        rank=flight.default_rank() if rank is None else rank,
        metrics=metrics,
    )
    log.warning("audit armed: %s (rank %d)", spec, _audit.rank)
    return _audit


def enabled() -> bool:
    return _audit is not None


def spec() -> str:
    """The armed config re-serialized as a spec string — what a
    coordinator exports into worker-process environments so a
    programmatically-armed fleet arms its subprocesses too. Empty when
    unarmed (callers leave the env var unset)."""
    if _audit is None:
        return ""
    c = _audit.cfg
    return (f"sample={c.sample}:shadow={c.shadow}:"
            f"probe_every_s={c.probe_every_s}:quarantine={c.quarantine}")


def audit() -> AuditEngine | None:
    return _audit


def reset() -> None:
    """Disarm (test isolation)."""
    global _audit
    _audit = None


def attach_metrics(metrics) -> None:
    """Late-bind the JSONL sink (engines/fleets construct after
    arming). Not a hot-path hook, but still inert-guarded."""
    if _audit is None:
        return
    if metrics is not None:
        _audit.metrics = metrics


def summary() -> dict | None:
    """Fingerprint/divergence/probe tallies; None when unarmed
    (consumers key their sections off the None)."""
    if _audit is None:
        return None
    return _audit.summary()


# -- policy accessors (inert-guarded, cheap) --------------------------------


def shadow_sampled(request_id: str) -> bool:
    """Should the fleet duplicate this request onto a shadow replica?
    Deterministic per request id; always False unarmed."""
    if _audit is None:
        return False
    if not _audit.cfg.shadow:
        return False
    return _audit.sampled(request_id)


def probe_interval() -> float:
    """Golden-probe cadence in seconds; 0.0 = no probing (or unarmed)."""
    if _audit is None:
        return 0.0
    return _audit.cfg.probe_every_s


def quarantine_enabled() -> bool:
    if _audit is None:
        return False
    return bool(_audit.cfg.quarantine)


def seed_of(tokens) -> str:
    """Chain seed for a leg that carries ``tokens`` as its already-
    emitted prefix (failover re-admission, disagg handoff, process
    dispatch). Empty string when unarmed — so wire records stay
    key-absent and byte-identical."""
    if _audit is None:
        return ""
    return chain("", tokens)


def fingerprint_of(request_id: str) -> str | None:
    if _audit is None:
        return None
    return _audit.fingerprint_of(request_id)


# -- hooks (every one: inert fast path, lint-enforced) ----------------------


def on_retire(request_id: str, tokens, *, seed: str = "",
              replica: str = "") -> str | None:
    """Engine retire (``ServingEngine._finish_record`` — the single
    lint-pinned fingerprint call site): fold the leg's emitted tokens
    onto its chain seed. Returns the fingerprint, or None unarmed (the
    ``fp`` key stays absent from every record)."""
    if _audit is None:
        return None
    fp = chain(seed, tokens)
    _audit.record(request_id, fp, n=len(tokens), replica=replica)
    return fp


def on_worker_done(rec: dict, tokens, *, host: int) -> dict | None:
    """fleet_worker completion: the leg fingerprint, seeded by the
    chain the coordinator dispatched (``rec["fp"]``, key-absent
    unarmed). Returns the ``fp/<rid>`` payload to publish, or None."""
    if _audit is None:
        return None
    seed = rec.get("fp", "")
    fp = chain(seed, tokens)
    rid = str(rec.get("request_id", ""))
    _audit.record(rid, fp, n=len(tokens), replica=f"proc{host}")
    return dict(fp=fp, n=len(tokens), replica=int(host),
                life=int(rec.get("life", 0)))


def on_divergence(kind: str, *, request_id: str = "", pair=(),
                  suspect: str = "", note: str = "") -> dict | None:
    if _audit is None:
        return None
    return _audit.divergence(kind, request_id=request_id, pair=pair,
                             suspect=suspect, note=note)


def on_probe_result(key: str, replica: str, fp: str) -> bool:
    """True = probe matched golden (or audit unarmed — never a false
    alarm on an unarmed process)."""
    if _audit is None:
        return True
    return _audit.probe_result(key, replica, fp)


def on_quarantine(replica: str, reason: str) -> None:
    if _audit is None:
        return
    _audit.quarantined(replica, reason)


def maybe_publish(client, *, rank: int) -> bool:
    """Publish this process's audit summary at ``audit/<rank>`` (the
    fleet_deploy status + coordinator rollup feed). Inert no-op when
    unarmed or nothing new since the last publish; never raises into
    the serve loop."""
    if _audit is None:
        return False
    n = len(_audit.fingerprints) + len(_audit.divergences) + _audit.probes
    if n == _audit._published:
        return False
    payload = dict(_audit.summary(), last_fp_t=_audit.last_fp_t)
    wire = json.dumps(payload, sort_keys=True).encode()
    out = failure.store_call(
        lambda: (client.set(f"audit/{rank}", wire), True)[-1],
        op="audit_publish", deadline_s=0.5, fallback=None)
    if out is None:
        log.warning("audit publish failed (rank %d)", rank)
        return False
    _audit._published = n
    return True
