"""Skyline capacity frontier: offered-load sweeps judged by the
watchtower's burn-rate signal.

The capacity question — "how many replicas does this SLO need under a
flash crowd?" — answered by measurement, not folklore: sweep offered
load (``rps_scale`` rungs of one seeded :mod:`serve.traffic` trace)
against a fleet, judge every rung with the watchtower's existing
multi-window TTFT / per-token burn-rate machinery (the rung's request
stream is replayed through a fresh :class:`obs.watchtower.Watchtower`
in event time — no new transport, no new detectors), and emit the
**capacity frontier**: the max sustainable request rate per SLO class
per traffic shape per replica count, plus the goodput-saturation knee
where marginal tokens/s per offered req/s collapses.

Two ways to produce a rung's request stream:

- :func:`simulate_fleet` — a deterministic discrete-event service
  model (per-replica decode slots, FIFO queueing, admission shedding,
  chaos ``kill_replica@`` faults with re-admission penalties). Pure in
  the trace: same spec + seed → byte-identical events → **identical
  capacity report**, with no accelerator in the loop. This is what
  ``bench.py --capacity --selftest`` and tier-1 exercise, and what the
  planning report defaults to.
- a real :class:`serve.fleet.Fleet` driven by
  :func:`serve.traffic.replay_trace` (``bench.py --capacity``), whose
  completion records feed the same judge, and whose service-time
  parameters calibrate the simulator.

Chaos composes: the simulator accepts a ``TPUNN_CHAOS``-grammar spec
(parsed by :func:`runtime.chaos.parse_spec` — the real grammar, not a
clone) so a replica kill lands mid-flash-crowd; the report names the
failover window it carved out of the frontier.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import logging
from typing import Callable, Optional, Sequence

from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.obs.stats import median
from pytorch_distributed_nn_tpu.obs.watchtower import (
    PAGE,
    WatchConfig,
    Watchtower,
)
from pytorch_distributed_nn_tpu.serve import traffic

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class SloClass:
    """One SLO class to judge every rung against."""

    name: str
    ttft_s: float
    token_s: float
    objective: float = 0.9

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


DEFAULT_SLOS = (
    SloClass("interactive", ttft_s=0.5, token_s=0.1, objective=0.9),
    SloClass("batch", ttft_s=2.0, token_s=0.5, objective=0.95),
)


def _skyline_gauges():
    reg = get_registry()
    return {
        "offered": reg.gauge(
            "skyline_offered_rps", "offered request rate at the last "
            "judged rung", labels=("shape", "replicas")),
        "goodput": reg.gauge(
            "skyline_goodput_tps", "generated tokens/s at the last "
            "judged rung", labels=("shape", "replicas")),
        "attain": reg.gauge(
            "skyline_slo_attainment", "in-SLO fraction at the last "
            "judged rung", labels=("shape", "replicas", "slo")),
        "frontier": reg.gauge(
            "skyline_sustainable_rps", "capacity frontier: max offered "
            "req/s the SLO survives", labels=("shape", "replicas",
                                              "slo")),
    }


# ---------------------------------------------------------------------------
# Deterministic service model
# ---------------------------------------------------------------------------


def _chaos_kills(chaos_spec: Optional[str]) -> list[tuple[float, int, int]]:
    """``kill_replica@`` faults from a real TPUNN_CHAOS-grammar spec →
    ``(after_s, arrival_index_gate, replica)`` kill points. ``after_s``
    is virtual trace time; a fault with only ``step=`` fires when that
    many requests have arrived (the simulator has no replica rounds)."""
    if not chaos_spec:
        return []
    from pytorch_distributed_nn_tpu.runtime import chaos

    kills = []
    for fault in chaos.parse_spec(chaos_spec):
        if fault.kind != "kill_replica":
            log.info("capacity simulator ignores chaos fault %s "
                     "(only kill_replica is modeled)", fault.spec)
            continue
        kills.append((float(fault.after_s or 0.0),
                      int(fault.step or 0), int(fault.replica)))
    return kills


def simulate_fleet(trace: list[dict], *, replicas: int, slots: int = 4,
                   prefill_tps: float = 2000.0,
                   decode_tps: float = 200.0, max_wait_s: float = 2.0,
                   readmit_s: float = 0.05,
                   chaos_spec: Optional[str] = None,
                   duration_s: Optional[float] = None) -> dict:
    """Discrete-event model of the fleet serving a trace, entirely in
    virtual time. Each replica owns ``slots`` concurrent decode slots;
    a request occupies one for ``prompt_len/prefill_tps +
    max_new/decode_tps`` seconds, TTFT = queue wait + prefill. An
    arrival that would wait longer than ``max_wait_s`` is shed
    (``queue_full``) — the admission-control analogue. A chaos kill
    removes the replica and re-admits its unfinished requests on
    survivors after ``readmit_s``, TTFT still charged from the
    *original* arrival (what the client experienced).

    Returns ``{"events", "goodput_tps", "offered_rps", "requests",
    "rejects", "failover_windows"}`` — events are watchtower-shaped
    (``serve_request`` / ``serve_reject`` / ``replica_down`` /
    ``serve_round``), sorted by event time, pure in the inputs."""
    if replicas < 1:
        raise ValueError("simulate_fleet needs replicas >= 1")
    kills = _chaos_kills(chaos_spec)
    alive = set(range(replicas))
    slot_ends = {r: [0.0] * slots for r in alive}
    # per-replica ledger of assigned-but-maybe-unfinished requests
    assigned: dict[int, list[dict]] = {r: [] for r in alive}

    # one heap of timed work: kills sort before arrivals at equal time
    _KILL, _ARRIVE = 0, 1
    heap: list[tuple[float, int, int, dict]] = []
    seq = 0
    arrivals_seen = 0
    kill_by_index = []
    for after_s, step_gate, rep in kills:
        if after_s > 0:
            heap.append((after_s, _KILL, seq, {"replica": rep}))
            seq += 1
        else:
            kill_by_index.append((step_gate, rep))
    for rec in trace:
        heap.append((float(rec["t"]), _ARRIVE, seq,
                     {"rec": rec, "t_orig": float(rec["t"]),
                      "failovers": []}))
        seq += 1
    heapq.heapify(heap)

    events: list[tuple[float, int, dict]] = []  # (t, order, event)
    eseq = 0
    completed_tokens = 0
    n_rejects = 0
    failover_windows: list[dict] = []

    def _emit(ev: dict) -> None:
        nonlocal eseq
        events.append((float(ev["t"]), eseq, ev))
        eseq += 1

    def _kill(t_kill: float, rep: int) -> None:
        nonlocal seq
        if rep not in alive:
            return
        alive.discard(rep)
        stranded = [w for w in assigned.pop(rep) if w["end"] > t_kill]
        ids = [w["id"] for w in stranded]
        _emit({"ev": "replica_down", "t": round(t_kill, 6),
               "replica": rep, "reason": "chaos_kill",
               "stranded": ids})
        for w in stranded:
            entry = dict(w["entry"])
            entry["failovers"] = entry["failovers"] + [{
                "from_replica": rep, "reason": "chaos_kill",
                "t": round(t_kill, 6), "readmit_s": readmit_s}]
            heapq.heappush(heap, (t_kill + readmit_s, _ARRIVE, seq,
                                  entry))
            seq += 1
        failover_windows.append({
            "replica": rep, "t_down": round(t_kill, 6),
            "readmitted": len(stranded), "t_recovered": None})

    while heap:
        t, kind, _, payload = heapq.heappop(heap)
        if kind == _KILL:
            _kill(t, payload["replica"])
            continue
        rec = payload["rec"]
        rid = f"t{int(rec['i']):05d}"
        arrivals_seen += 1
        while kill_by_index and kill_by_index[0][0] <= arrivals_seen:
            _, rep = kill_by_index.pop(0)
            _kill(t, rep)
        if not alive:
            n_rejects += 1
            _emit({"ev": "serve_reject", "t": round(t, 6),
                   "request_id": rid, "reason": "no_replicas"})
            continue
        # earliest-start placement, replica index breaks ties
        best_r, best_start = None, None
        for r in sorted(alive):
            start = max(t, min(slot_ends[r]))
            if best_start is None or start < best_start:
                best_r, best_start = r, start
        if best_start - t > max_wait_s:
            n_rejects += 1
            _emit({"ev": "serve_reject", "t": round(t, 6),
                   "request_id": rid, "reason": "queue_full"})
            continue
        prefill_s = float(rec["prompt_len"]) / prefill_tps
        decode_s = float(rec["max_new"]) / decode_tps
        end = best_start + prefill_s + decode_s
        ttft = (best_start - payload["t_orig"]) + prefill_s
        ends = slot_ends[best_r]
        ends[ends.index(min(ends))] = end
        work = {"id": rid, "end": end, "entry": payload}
        assigned[best_r].append(work)
        per_token = decode_s / max(int(rec["max_new"]), 1)
        ev = {"ev": "serve_request", "t": round(end, 6), "ok": True,
              "request_id": rid, "ttft_s": round(ttft, 6),
              "per_token_s": round(per_token, 6),
              "tenant": rec.get("tenant", "default"),
              "new_tokens": int(rec["max_new"]),
              "replica": f"r{best_r}",
              "failovers": payload["failovers"]}
        work["event"] = ev

    # finalize: only requests still on a live replica's ledger
    # completed (a kill popped its ledger and re-admitted the rest)
    done = [w for per in assigned.values() for w in per]
    for w in done:
        _emit(w["event"])
    completed_tokens = sum(int(w["entry"]["rec"]["max_new"])
                           for w in done)
    for win in failover_windows:
        ends = [w["end"] for w in done if w["entry"]["failovers"]
                and any(f["from_replica"] == win["replica"]
                        for f in w["entry"]["failovers"])]
        win["t_recovered"] = round(max(ends), 6) if ends else None
    # a per-token latency sample per completion, through the same
    # serve_round path the live engine feeds (wall per decoded token)
    for i, w in enumerate(sorted(done, key=lambda w: w["end"])):
        _emit({"ev": "serve_round", "t": round(w["end"], 6),
               "round": i, "wall_s": w["event"]["per_token_s"]})

    events.sort(key=lambda e: (e[0], e[1]))
    window = duration_s or 0.0
    if events:
        window = max(window, events[-1][0])
    offered = len(trace) / window if window > 0 else 0.0
    return {
        "events": [e for _, _, e in events],
        "goodput_tps": round(completed_tokens / window, 4)
        if window > 0 else 0.0,
        "offered_rps": round(offered, 4),
        "requests": len(trace),
        "rejects": n_rejects,
        "failover_windows": failover_windows,
    }


def simulate_autoscaled_fleet(
        trace: list[dict], *, controller, replicas: int,
        slots: int = 4, prefill_tps: float = 2000.0,
        decode_tps: float = 200.0, max_wait_s: float = 2.0,
        readmit_s: float = 0.05, warmup_s: float = 0.25,
        tick_s: float = 0.5, chaos_spec: Optional[str] = None,
        duration_s: Optional[float] = None,
        tail_s: float = 10.0) -> dict:
    """:func:`simulate_fleet` with the replica set under closed-loop
    control — the no-backend validation path for Helm
    (:mod:`serve.autoscale`, ``bench.py --autoscale --selftest``).

    ``controller`` is duck-typed (so this module never imports the
    autoscaler; serve code reaches obs, not the reverse):
    ``feed(event)`` receives every completion's ``serve_request`` /
    ``serve_round`` event *causally* (flushed in event-time order
    before anything later happens), and
    ``desired(t, ready, queue_frac=..., kv_free_frac=...)`` is called
    once per ``tick_s`` of virtual time and returns the new replica
    target — or None to hold. Pressure evidence is the service model's
    own: ``queue_frac`` is the best-case placement wait as a fraction
    of the shed line ``max_wait_s``, ``kv_free_frac`` the fraction of
    placeable decode slots free at the tick.

    Control actions mirror the live fleet's semantics: a scale-up adds
    fresh replicas (monotonic indexes) that only become placeable
    ``warmup_s`` later (the join gate); a scale-down retires the
    highest-index replicas — immediately unplaceable, but their
    in-flight work still completes, so scaling down rejects nothing.
    Chaos ``kill_replica@`` kills compose exactly as in
    :func:`simulate_fleet`; the controller sees the resulting burn and
    is expected to buy the capacity back. Ticks continue ``tail_s``
    past the horizon so post-spike scale-downs land inside the run.

    Pure in the inputs (given a deterministic controller): returns the
    :func:`simulate_fleet` report plus ``replica_series`` (per tick:
    ``t`` / ``ready`` / ``target``), ``scale_events``, and
    ``final_target``."""
    if replicas < 1:
        raise ValueError("simulate_autoscaled_fleet needs replicas >= 1")
    kills = _chaos_kills(chaos_spec)
    members: dict[int, dict] = {}
    slot_ends: dict[int, list[float]] = {}
    assigned: dict[int, list[dict]] = {}
    next_index = 0

    def _add_replica(warm_at: float) -> int:
        nonlocal next_index
        r = next_index
        next_index += 1
        members[r] = {"warm_at": warm_at, "retiring": False,
                      "killed": False}
        slot_ends[r] = [0.0] * slots
        assigned[r] = []
        return r

    for _ in range(replicas):
        _add_replica(0.0)

    def _placeable(t: float) -> list[int]:
        return sorted(
            r for r, m in members.items()
            if not m["killed"] and not m["retiring"]
            and m["warm_at"] <= t)

    # one heap of timed work; at equal times kills land first, then
    # control ticks, then arrivals (a decision never sees the future)
    _KILL, _TICK, _ARRIVE = 0, 1, 2
    heap: list[tuple[float, int, int, dict]] = []
    seq = 0
    arrivals_seen = 0
    kill_by_index = []
    for after_s, step_gate, rep in kills:
        if after_s > 0:
            heap.append((after_s, _KILL, seq, {"replica": rep}))
            seq += 1
        else:
            kill_by_index.append((step_gate, rep))
    horizon = duration_s if duration_s is not None else (
        max((float(rec["t"]) for rec in trace), default=0.0))
    n_ticks = int((horizon + tail_s) / tick_s) + 1
    for i in range(n_ticks):
        heap.append((i * tick_s, _TICK, seq, {}))
        seq += 1
    for rec in trace:
        heap.append((float(rec["t"]), _ARRIVE, seq,
                     {"rec": rec, "t_orig": float(rec["t"]),
                      "failovers": []}))
        seq += 1
    heapq.heapify(heap)

    events: list[tuple[float, int, dict]] = []
    eseq = 0
    rounds = 0
    completed_tokens = 0
    n_rejects = 0
    failover_windows: list[dict] = []
    replica_series: list[dict] = []
    scale_events: list[dict] = []
    target = replicas
    # completion queue: works flush (emit + controller.feed) in end-
    # time order before any later pop — the controller is causal
    pending: list[tuple[float, int, dict]] = []
    pseq = 0

    def _emit(ev: dict) -> None:
        nonlocal eseq
        events.append((float(ev["t"]), eseq, ev))
        eseq += 1

    def _flush(t: float) -> None:
        nonlocal rounds, completed_tokens
        while pending and pending[0][0] <= t + 1e-12:
            _, _, w = heapq.heappop(pending)
            if w.get("stranded"):
                continue  # re-admitted by a kill; a later life flushes
            w["flushed"] = True
            _emit(w["event"])
            controller.feed(w["event"])
            rev = {"ev": "serve_round", "t": w["event"]["t"],
                   "round": rounds,
                   "wall_s": w["event"]["per_token_s"]}
            rounds += 1
            _emit(rev)
            controller.feed(rev)
            completed_tokens += int(w["entry"]["rec"]["max_new"])

    def _kill(t_kill: float, rep: int) -> None:
        nonlocal seq
        m = members.get(rep)
        if m is None or m["killed"]:
            return
        m["killed"] = True
        stranded = [w for w in assigned[rep]
                    if not w.get("flushed") and w["end"] > t_kill]
        ids = [w["id"] for w in stranded]
        ev = {"ev": "replica_down", "t": round(t_kill, 6),
              "replica": rep, "reason": "chaos_kill", "stranded": ids}
        _emit(ev)
        controller.feed(ev)
        for w in stranded:
            w["stranded"] = True
            entry = dict(w["entry"])
            entry["failovers"] = entry["failovers"] + [{
                "from_replica": rep, "reason": "chaos_kill",
                "t": round(t_kill, 6), "readmit_s": readmit_s}]
            heapq.heappush(heap, (t_kill + readmit_s, _ARRIVE, seq,
                                  entry))
            seq += 1
        failover_windows.append({
            "replica": rep, "t_down": round(t_kill, 6),
            "readmitted": len(stranded), "t_recovered": None})

    while heap:
        t, kind, _, payload = heapq.heappop(heap)
        _flush(t)
        if kind == _KILL:
            _kill(t, payload["replica"])
            continue
        if kind == _TICK:
            cands = _placeable(t)
            ready = len(cands)
            if cands:
                waits = [max(0.0, min(slot_ends[r]) - t)
                         for r in cands]
                queue_frac = (min(1.0, min(waits) / max_wait_s)
                              if max_wait_s > 0 else 0.0)
                free = sum(1 for r in cands
                           for e in slot_ends[r] if e <= t)
                kv_free_frac = free / (len(cands) * slots)
            else:
                queue_frac, kv_free_frac = 1.0, 0.0
            n = controller.desired(
                round(t, 6), ready,
                queue_frac=round(queue_frac, 6),
                kv_free_frac=round(kv_free_frac, 6))
            cur = sum(1 for m in members.values()
                      if not m["killed"] and not m["retiring"])
            if n is not None and n != cur:
                if n > cur:
                    for _ in range(n - cur):
                        r = _add_replica(round(t + warmup_s, 6))
                        scale_events.append(
                            {"t": round(t, 6), "op": "add",
                             "replica": r,
                             "warm_at": members[r]["warm_at"]})
                else:
                    live = sorted(
                        (r for r, m in members.items()
                         if not m["killed"] and not m["retiring"]),
                        reverse=True)
                    for r in live[:cur - n]:
                        members[r]["retiring"] = True
                        scale_events.append(
                            {"t": round(t, 6), "op": "retire",
                             "replica": r})
            if n is not None:
                target = n
            replica_series.append({"t": round(t, 6), "ready": ready,
                                   "target": target})
            continue
        rec = payload["rec"]
        rid = f"t{int(rec['i']):05d}"
        arrivals_seen += 1
        while kill_by_index and kill_by_index[0][0] <= arrivals_seen:
            _, rep = kill_by_index.pop(0)
            _kill(t, rep)
        cands = _placeable(t)
        if not cands:
            n_rejects += 1
            rej = {"ev": "serve_reject", "t": round(t, 6),
                   "request_id": rid, "reason": "no_replicas"}
            _emit(rej)
            controller.feed(rej)
            continue
        best_r, best_start = None, None
        for r in cands:
            start = max(t, min(slot_ends[r]))
            if best_start is None or start < best_start:
                best_r, best_start = r, start
        if best_start - t > max_wait_s:
            n_rejects += 1
            rej = {"ev": "serve_reject", "t": round(t, 6),
                   "request_id": rid, "reason": "queue_full"}
            _emit(rej)
            controller.feed(rej)
            continue
        prefill_s = float(rec["prompt_len"]) / prefill_tps
        decode_s = float(rec["max_new"]) / decode_tps
        end = best_start + prefill_s + decode_s
        ttft = (best_start - payload["t_orig"]) + prefill_s
        ends = slot_ends[best_r]
        ends[ends.index(min(ends))] = end
        per_token = decode_s / max(int(rec["max_new"]), 1)
        work = {"id": rid, "end": end, "entry": payload,
                "event": {"ev": "serve_request", "t": round(end, 6),
                          "ok": True, "request_id": rid,
                          "ttft_s": round(ttft, 6),
                          "per_token_s": round(per_token, 6),
                          "tenant": rec.get("tenant", "default"),
                          "new_tokens": int(rec["max_new"]),
                          "replica": f"r{best_r}",
                          "failovers": payload["failovers"]}}
        assigned[best_r].append(work)
        heapq.heappush(pending, (end, pseq, work))
        pseq += 1

    _flush(float("inf"))
    for win in failover_windows:
        ends = [w["end"] for per in assigned.values() for w in per
                if w.get("flushed") and w["entry"]["failovers"]
                and any(f["from_replica"] == win["replica"]
                        for f in w["entry"]["failovers"])]
        win["t_recovered"] = round(max(ends), 6) if ends else None

    events.sort(key=lambda e: (e[0], e[1]))
    window = duration_s or 0.0
    if events:
        window = max(window, events[-1][0])
    offered = len(trace) / window if window > 0 else 0.0
    return {
        "events": [e for _, _, e in events],
        "goodput_tps": round(completed_tokens / window, 4)
        if window > 0 else 0.0,
        "offered_rps": round(offered, 4),
        "requests": len(trace),
        "rejects": n_rejects,
        "failover_windows": failover_windows,
        "replica_series": replica_series,
        "scale_events": scale_events,
        "final_target": target,
    }


# ---------------------------------------------------------------------------
# The judge: watchtower burn over a rung's event stream
# ---------------------------------------------------------------------------


def judge_rung(events: Sequence[dict], *, slo: SloClass,
               duration_s: float) -> dict:
    """Replay a rung's request stream through a fresh
    :class:`Watchtower` (event time only) configured for this SLO
    class, windows scaled to the rung. Sustainable = the burn-rate
    detector never paged AND the raw in-SLO fraction meets the
    objective — the same multi-window signal production paging uses,
    so the frontier and the pager can never disagree."""
    window = max(float(duration_s), 1e-3)
    cfg = WatchConfig(
        ttft_slo_s=slo.ttft_s, token_slo_s=slo.token_s,
        slo_objective=slo.objective,
        burn_fast_s=max(window / 4.0, 1e-3), burn_slow_s=window,
        burn_threshold=2.0, burn_min_events=5)
    tower = Watchtower(cfg, dump_on_page=False)
    total = 0
    in_slo = 0
    for ev in events:
        kind = ev.get("ev")
        if kind == "serve_request":
            total += 1
            if ev.get("ok", True) and float(ev["ttft_s"]) <= slo.ttft_s:
                in_slo += 1
        elif kind == "serve_reject":
            total += 1
        tower.observe(ev)
    attainment = in_slo / total if total else 1.0
    burn_pages = [a for a in tower.alerts
                  if a.kind == "slo_burn_rate" and a.severity == PAGE]
    return {
        "slo": slo.name,
        "attainment": round(attainment, 4),
        "objective": slo.objective,
        "burn_pages": len(burn_pages),
        "burned_slos": sorted({a.attribution.get("slo", "?")
                               for a in burn_pages}),
        "sustainable": (not burn_pages
                        and attainment >= slo.objective),
    }


# ---------------------------------------------------------------------------
# Sweep + frontier + knee
# ---------------------------------------------------------------------------


def sweep_rates(spec: traffic.TrafficSpec, *,
                rates: Sequence[float], run_rung: Callable[..., dict],
                slos: Sequence[SloClass] = DEFAULT_SLOS,
                seed: int = 0) -> list[dict]:
    """One replica count's sweep: for each ``rps_scale`` rung,
    regenerate the trace at that offered load (same seed — the rungs
    are the *same* traffic shape, scaled) and judge it against every
    SLO class. ``run_rung(trace, duration_s)`` produces the rung's
    event stream (simulator or a live fleet driver)."""
    rungs = []
    for scale in rates:
        trace = traffic.generate_trace(spec, seed=seed,
                                       rps_scale=scale)
        run = run_rung(trace, spec.duration_s)
        rung = {
            "rate_scale": scale,
            "offered_rps": run["offered_rps"],
            "requests": run["requests"],
            "rejects": run["rejects"],
            "goodput_tps": run["goodput_tps"],
            "failover_windows": run.get("failover_windows", []),
            "slo": {s.name: judge_rung(run["events"], slo=s,
                                       duration_s=spec.duration_s)
                    for s in slos},
        }
        rungs.append(rung)
        log.info("capacity rung x%.2f: offered %.2f rps, goodput "
                 "%.1f tok/s, sustainable=%s", scale,
                 rung["offered_rps"], rung["goodput_tps"],
                 {k: v["sustainable"] for k, v in rung["slo"].items()})
    return rungs


def frontier_of(rungs: Sequence[dict],
                slos: Sequence[SloClass] = DEFAULT_SLOS) -> dict:
    """Max sustainable offered rate per SLO class (None when even the
    lowest rung burned)."""
    out = {}
    for s in slos:
        ok = [r["offered_rps"] for r in rungs
              if r["slo"][s.name]["sustainable"]]
        out[s.name] = max(ok) if ok else None
    return out


def knee_of(rungs: Sequence[dict]) -> Optional[float]:
    """The goodput-saturation knee: the offered rate where marginal
    goodput per offered req/s first drops under half the reference
    slope (median of the early slopes — heavy-tail-robust, the
    obs.stats helpers). None when the sweep never saturates."""
    pts = sorted((r["offered_rps"], r["goodput_tps"]) for r in rungs)
    slopes = []
    for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
        if x1 > x0:
            slopes.append(((y1 - y0) / (x1 - x0), x1))
    if len(slopes) < 2:
        return None
    head = [s for s, _ in slopes[:max(1, len(slopes) // 2)]]
    ref = median(head)
    if ref <= 0:
        return None
    for slope, x in slopes:
        if slope < 0.5 * ref:
            return x
    return None


def plan_capacity(spec: traffic.TrafficSpec, *,
                  replica_counts: Sequence[int],
                  rates: Sequence[float],
                  make_run_rung: Callable[[int], Callable[..., dict]],
                  slos: Sequence[SloClass] = DEFAULT_SLOS,
                  seed: int = 0, target_rps: Optional[float] = None,
                  chaos_spec: Optional[str] = None,
                  price_per_replica_hour: float = 0.0) -> dict:
    """The full capacity-planning sweep: replica counts x offered-load
    rungs x SLO classes → the frontier surface and the headline table
    "replicas needed per SLO per traffic shape" (min replica count
    whose frontier covers ``target_rps``, default the spec's base
    rate). Pure in (spec, seed, service model): generating the report
    twice yields identical JSON — the determinism contract tier-1
    asserts.

    ``price_per_replica_hour`` > 0 prices every rung (the Abacus
    showback bridge, obs/meter.py): ``cost_per_1k_tokens = replicas x
    price / 3600 x 1000 / goodput_tps`` — the planner's answer to
    "which replica count serves this shape CHEAPEST per token while
    holding the SLO", not just "which is smallest". Keys are absent at
    the default 0.0 so unpriced reports stay byte-identical."""
    target = float(target_rps if target_rps is not None
                   else spec.base_rps)
    gauges = _skyline_gauges()
    shape = spec.shape_name
    sweeps = {}
    for n in replica_counts:
        rungs = sweep_rates(spec, rates=rates,
                            run_rung=make_run_rung(n), slos=slos,
                            seed=seed)
        if price_per_replica_hour > 0:
            for rung in rungs:
                tps = rung["goodput_tps"]
                rung["cost_per_1k_tokens"] = (
                    round(n * price_per_replica_hour / 3600.0
                          * 1000.0 / tps, 6) if tps > 0 else None)
        front = frontier_of(rungs, slos)
        sweeps[str(n)] = {"rungs": rungs, "frontier": front,
                          "knee_rps": knee_of(rungs)}
        last = rungs[-1]
        gauges["offered"].set(last["offered_rps"], shape=shape,
                              replicas=str(n))
        gauges["goodput"].set(last["goodput_tps"], shape=shape,
                              replicas=str(n))
        for s in slos:
            gauges["attain"].set(last["slo"][s.name]["attainment"],
                                 shape=shape, replicas=str(n),
                                 slo=s.name)
            gauges["frontier"].set(front[s.name] or 0.0, shape=shape,
                                   replicas=str(n), slo=s.name)
    needed = {}
    for s in slos:
        counts = [n for n in sorted(replica_counts)
                  if (sweeps[str(n)]["frontier"][s.name] or 0.0)
                  >= target]
        needed[s.name] = {"target_rps": round(target, 4),
                          "replicas": min(counts) if counts else None}
    report = {
        "shape": shape,
        "spec": spec.describe(),
        "seed": seed,
        "chaos": chaos_spec or "",
        "slos": [s.as_dict() for s in slos],
        "replica_counts": sorted(int(n) for n in replica_counts),
        "sweeps": sweeps,
        "replicas_needed": needed,
    }
    if price_per_replica_hour > 0:
        report["price_per_replica_hour"] = round(
            float(price_per_replica_hour), 6)
    return report


def simulated_run_rung(replicas: int, *, slots: int = 4,
                       prefill_tps: float = 2000.0,
                       decode_tps: float = 200.0,
                       max_wait_s: float = 2.0,
                       readmit_s: float = 0.05,
                       chaos_spec: Optional[str] = None
                       ) -> Callable[..., dict]:
    """``make_run_rung`` for :func:`plan_capacity` backed by the
    deterministic service model."""
    def run(trace: list[dict], duration_s: float) -> dict:
        return simulate_fleet(
            trace, replicas=replicas, slots=slots,
            prefill_tps=prefill_tps, decode_tps=decode_tps,
            max_wait_s=max_wait_s, readmit_s=readmit_s,
            chaos_spec=chaos_spec, duration_s=duration_s)
    return run


# ---------------------------------------------------------------------------
# Serialization (byte-identical report contract) + JSONL events
# ---------------------------------------------------------------------------


def report_to_json(report: dict) -> str:
    """Canonical serialization — same spec + seed + service model →
    the same bytes twice in a row."""
    return json.dumps(report, sort_keys=True)


def report_events(report: dict) -> list[dict]:
    """Flatten a capacity report into JSONL-able events
    (``capacity_rung`` / ``capacity_frontier``) for the metrics stream
    ``scripts/obs_report.py --capacity`` renders."""
    out = []
    for n, sweep in sorted(report["sweeps"].items(),
                           key=lambda kv: int(kv[0])):
        for rung in sweep["rungs"]:
            out.append({
                "event": "capacity_rung", "shape": report["shape"],
                "replicas": int(n),
                "offered_rps": rung["offered_rps"],
                "goodput_tps": rung["goodput_tps"],
                "rejects": rung["rejects"],
                "requests": rung["requests"],
                "slo": {name: {"attainment": j["attainment"],
                               "sustainable": j["sustainable"],
                               "burn_pages": j["burn_pages"]}
                        for name, j in rung["slo"].items()},
                "failover_windows": rung["failover_windows"],
            })
        out.append({
            "event": "capacity_frontier", "shape": report["shape"],
            "replicas": int(n), "frontier": sweep["frontier"],
            "knee_rps": sweep["knee_rps"], "chaos": report["chaos"],
        })
    out.append({
        "event": "capacity_plan", "shape": report["shape"],
        "spec": report["spec"], "seed": report["seed"],
        "chaos": report["chaos"],
        "replicas_needed": report["replicas_needed"],
    })
    return out
