"""Collective flight recorder: a bounded, always-on per-host ring.

PR 1 built *live* telemetry (registry, spans, goodput). This module is
the *post-mortem* counterpart: when a pod run dies — and at scale the
dominant failure is not a stack trace but a silent hang, one rank
stalled in ``psum``/``ppermute`` with every other rank blocked behind
it (the pjit-on-TPUv4 / MPMD-pipeline operational cost, PAPERS.md) —
nothing in a log says *which collective, which rank, which step*. The
flight recorder does: every process keeps the last ``capacity`` comm /
step / checkpoint / data events in a fixed-size ring, and dump
triggers (fatal signals, unhandled exceptions, the progress watchdog,
a supervisor request over the native store — see
:mod:`runtime.failure` and :mod:`launch`) write it to
``flight_rank<k>.json`` next to the run's JSONL.
``obs/forensics.py`` + ``scripts/obs_doctor.py`` merge the per-rank
dumps, align collectives by sequence, and name the first divergence.

Cost model (why it can stay always-on):

- collective records from :func:`ops.collectives._record` fire at
  *trace* time — once per compiled program, not per step;
- per-step cost is two ring appends (step marker + dispatch event): a
  lock acquire and a ``deque.append`` each, ~1 µs against millisecond
  steps — not measurable in ``bench.py --goodput``;
- the ring is bounded (``deque(maxlen=...)``), so memory is O(capacity)
  forever.

Event kinds:

- ``collective`` — a comm op. ``note="trace"`` marks trace-time records
  (program structure: op/axis/bytes/shape/dtype at the step being
  traced); ``note="dispatch"`` marks host-driven runtime dispatches
  (the :func:`collective` context manager — enqueue ``t0``, complete
  ``t1``; ``t1 = None`` means *enqueued, never completed*: the smoking
  gun of a hang);
- ``dispatch`` — one fused step program handed to the device (Trainer);
- ``step`` — step-boundary marker (Trainer); per-rank step timestamps
  drive the doctor's straggler percentiles;
- ``checkpoint`` / ``data`` — save/restore and loader hand-off events;
- ``chaos`` — an injected fault (runtime/chaos.py): every TPUNN_CHAOS
  injection lands here so forensics can't misattribute it;
- ``preempt`` — preemption-notice markers (SIGTERM → graceful exit);
- ``serve`` — serving-engine lifecycle (serve/): one event per decode
  round plus admit/reject/retire/drain markers, so the doctor can see
  a wedged decode loop or shed traffic post-mortem;
- ``alert`` — a watchtower alert (obs/watchtower.py): every online
  detection lands here emit-first, and page-severity alerts trigger an
  automatic :func:`dump_now` — the ring that reaches disk already
  names what the run knew was wrong;
- ``fleet`` — replica-fleet lifecycle (serve/fleet.py): counted state
  transitions (``state:<s>``), ``replica_down`` (with the stranded
  request ids in the note), failover ``readmit`` markers, and rolling
  ``reload`` completions — a dead replica's dump names its victims;
- ``xray`` — profiler lifecycle (obs/xray.py): ``capture`` /
  ``capture_done`` markers (the note names the trigger and the capture
  directory) and per-compilation ``compile`` breadcrumbs, so a dump
  names the captures that exist for the incident;
- ``audit`` — Lighthouse output-integrity observations (obs/audit.py):
  ``fingerprint`` / ``divergence`` / ``probe`` / ``quarantine``
  markers, emit-first — a divergence dump already names the
  disagreeing replicas and the suspect.

Stdlib-only on purpose: dump paths run inside signal handlers and
heartbeat daemon threads of processes whose main thread is wedged
inside XLA — they must not touch jax.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import os
import signal
import socket
import sys
import tempfile
import threading
import time

ENV_FLIGHT = "TPUNN_FLIGHT"          # "0" disables recording entirely
ENV_FLIGHT_DIR = "TPUNN_FLIGHT_DIR"  # where dumps land (agent contract)
ENV_FLIGHT_RING = "TPUNN_FLIGHT_RING"  # ring capacity override

DEFAULT_CAPACITY = 4096

DUMP_VERSION = 1


def flight_path(directory, rank: int) -> str:
    """The per-rank dump filename contract (doctor globs on it)."""
    return os.path.join(str(directory), f"flight_rank{rank}.json")


def default_rank() -> int:
    """This process's rank from the launch env contract (no jax import:
    dumps must work from signal handlers under a wedged main thread)."""
    for var in ("PROCESS_ID", "RANK"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


@dataclasses.dataclass
class FlightEvent:
    """One ring entry. ``t0``/``t1`` are wall-clock (``time.time()``) so
    per-rank dumps on one host align exactly and cross-host dumps align
    to NTP precision — good enough to order steps, which is all the
    doctor needs. ``t1 is None`` = begun, never completed."""

    seq: int
    kind: str  # collective | dispatch | step | checkpoint | data
    #          # | chaos | preempt | serve | alert | fleet | xray
    #          # | audit
    op: str
    step: int
    t0: float
    t1: float | None
    axis: str = ""
    nbytes: int = 0
    shape: tuple = ()
    dtype: str = ""
    note: str = ""

    def as_dict(self) -> dict:
        return {
            "seq": self.seq, "kind": self.kind, "op": self.op,
            "step": self.step, "t0": self.t0, "t1": self.t1,
            "axis": self.axis, "nbytes": self.nbytes,
            "shape": list(self.shape), "dtype": self.dtype,
            "note": self.note,
        }


class FlightRecorder:
    """The bounded ring. Thread-safe: records come from the main loop,
    the loader producer thread, and trace-time hooks concurrently;
    dumps come from heartbeat daemon threads and signal handlers."""

    def __init__(self, capacity: int | None = None, *,
                 enabled: bool | None = None) -> None:
        if capacity is None:
            capacity = int(os.environ.get(ENV_FLIGHT_RING,
                                          DEFAULT_CAPACITY))
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        if enabled is None:
            enabled = os.environ.get(ENV_FLIGHT, "1") != "0"
        self.capacity = capacity
        self.enabled = enabled
        self._events: collections.deque[FlightEvent] = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._total = 0
        self._step = -1  # last step marker (trace-time records inherit)
        self._last_event_t: float | None = None
        self._dump_dir: str | None = None
        self._dump_reasons: list[str] = []

    # -- recording -------------------------------------------------------

    def record(self, kind: str, op: str, *, step: int | None = None,
               axis: str = "", nbytes: int = 0, shape: tuple = (),
               dtype: str = "", note: str = "",
               complete: bool = True) -> FlightEvent | None:
        """Append one event; ``complete=False`` leaves ``t1`` open for a
        later :meth:`complete` (the enqueue/complete pair)."""
        if not self.enabled:
            return None
        now = time.time()
        with self._lock:
            ev = FlightEvent(
                seq=self._seq, kind=kind, op=op,
                step=self._step if step is None else int(step),
                t0=now, t1=now if complete else None,
                axis=axis, nbytes=int(nbytes), shape=tuple(shape),
                dtype=dtype, note=note,
            )
            self._seq += 1
            self._total += 1
            self._events.append(ev)
            self._last_event_t = now
        return ev

    def complete(self, ev: FlightEvent | None) -> None:
        if ev is None or not self.enabled:
            return
        now = time.time()
        ev.t1 = now
        with self._lock:
            self._last_event_t = now

    @contextlib.contextmanager
    def collective(self, op: str, *, step: int | None = None,
                   axis: str = "", nbytes: int = 0, note: str = "dispatch",
                   **fields):
        """Host-driven collective dispatch window: enqueue on enter,
        complete on exit. A rank that hangs inside leaves ``t1=None``
        in its dump — "enqueued, never completed"."""
        ev = self.record("collective", op, step=step, axis=axis,
                         nbytes=nbytes, note=note, complete=False,
                         **fields)
        try:
            yield ev
        finally:
            self.complete(ev)

    @contextlib.contextmanager
    def dispatch(self, op: str, *, step: int | None = None,
                 note: str = ""):
        """One fused step program handed to the device (async: complete
        = dispatch returned, not device finished)."""
        ev = self.record("dispatch", op, step=step, note=note,
                         complete=False)
        try:
            yield ev
        finally:
            self.complete(ev)

    def mark_step(self, step: int, note: str = "") -> None:
        """Step-boundary marker; later trace-time collective records
        inherit this step number."""
        if not self.enabled:
            return
        with self._lock:
            self._step = int(step)
        self.record("step", "start", step=step, note=note)

    def on_collective(self, op: str, *, axis: str, nbytes: int,
                      shape: tuple = (), dtype: str = "") -> None:
        """Trace-time hook (called from ``ops.collectives._record`` and
        the fake world): records program structure, not a dispatch."""
        self.record("collective", op, axis=axis, nbytes=nbytes,
                    shape=shape, dtype=dtype, note="trace")

    # -- introspection ---------------------------------------------------

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [ev.as_dict() for ev in self._events]

    def last_age_s(self) -> float | None:
        """Seconds since the last recorded event (None = never armed) —
        the progress-watchdog signal."""
        with self._lock:
            last = self._last_event_t
        return None if last is None else time.time() - last

    @property
    def total_events(self) -> int:
        return self._total

    def set_dump_dir(self, directory) -> None:
        """Default dump location ("next to the run's JSONL"); the
        agent's ``TPUNN_FLIGHT_DIR`` env wins over this."""
        self._dump_dir = str(directory)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0
            self._total = 0
            self._step = -1
            self._last_event_t = None
            self._dump_reasons = []

    # -- dumping ---------------------------------------------------------

    def _resolve_dir(self, directory=None) -> str:
        d = (directory or os.environ.get(ENV_FLIGHT_DIR)
             or self._dump_dir)
        if d:
            return str(d)
        # Last resort is a stable tmp location, NOT the CWD: an
        # unconfigured process (tests, ad-hoc scripts) must never
        # litter whatever directory it happens to run from.
        d = os.path.join(tempfile.gettempdir(), "tpunn-flight")
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            return tempfile.gettempdir()
        return d

    def dump(self, reason: str, *, directory=None, rank: int | None = None,
             force: bool = False) -> str | None:
        """Write ``flight_rank<k>.json``. One dump per distinct reason
        unless ``force`` (a watchdog that keeps tripping must not spin
        on disk); a later dump overwrites with fresher events and the
        accumulated reason history. Never raises — dump paths run under
        dying processes."""
        if not self.enabled:
            return None
        with self._lock:
            if reason in self._dump_reasons and not force:
                return None
            self._dump_reasons.append(reason)
            reasons = list(self._dump_reasons)
        rank = default_rank() if rank is None else rank
        path = flight_path(self._resolve_dir(directory), rank)
        payload = {
            "version": DUMP_VERSION,
            "rank": rank,
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "incarnation": int(os.environ.get("TPUNN_RESTART", "0")),
            "reason": reason,
            "reasons": reasons,
            "dumped_at": time.time(),
            "capacity": self.capacity,
            "total_events": self._total,
            "dropped": max(self._total - len(self._events), 0),
            "events": self.snapshot(),
        }
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)  # readers never see a torn dump
            return path
        except OSError:
            return None


_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """The process-wide ring."""
    return _recorder


def reset_recorder(capacity: int | None = None, *,
                   enabled: bool | None = None) -> FlightRecorder:
    """Swap in a fresh ring (test isolation)."""
    global _recorder
    _recorder = FlightRecorder(capacity, enabled=enabled)
    return _recorder


# module-level conveniences bound to the live recorder (late-bound so
# reset_recorder takes effect everywhere)

def record(kind: str, op: str, **kw) -> FlightEvent | None:
    return _recorder.record(kind, op, **kw)


def complete(ev: FlightEvent | None) -> None:
    _recorder.complete(ev)


def mark_step(step: int, note: str = "") -> None:
    _recorder.mark_step(step, note)


def collective(op: str, **kw):
    return _recorder.collective(op, **kw)


def dispatch(op: str, **kw):
    return _recorder.dispatch(op, **kw)


def on_collective(op: str, **kw) -> None:
    _recorder.on_collective(op, **kw)


def set_dump_dir(directory) -> None:
    _recorder.set_dump_dir(directory)


def resolve_dump_dir(directory=None) -> str:
    """Where post-mortem artifacts land right now (explicit arg >
    ``TPUNN_FLIGHT_DIR`` > :func:`set_dump_dir` > a stable tmp dir).
    Companion artifacts (xray capture dirs) use this to land next to
    the flight dump."""
    return _recorder._resolve_dir(directory)


def dump_now(reason: str, *, directory=None, force: bool = False
             ) -> str | None:
    return _recorder.dump(reason, directory=directory, force=force)


# ---------------------------------------------------------------------------
# Dump triggers: crash hooks + progress watchdog
# ---------------------------------------------------------------------------

_hooks_installed = False
_watchdog_started = False


def install_crash_hooks() -> None:
    """Dump on fatal signals (SIGTERM/SIGABRT) and unhandled
    exceptions, chaining to whatever handler was there. Idempotent.
    Signal handlers need the main thread; elsewhere only the
    excepthook installs (the supervisor-request path still covers
    signal-class deaths there)."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_excepthook = sys.excepthook

    def _excepthook(tp, value, tb):
        dump_now(f"exception:{tp.__name__}", force=True)
        prev_excepthook(tp, value, tb)

    sys.excepthook = _excepthook

    for signum in (signal.SIGTERM, signal.SIGABRT):
        try:
            prev = signal.getsignal(signum)

            def _handler(got, frame, *, signum=signum, prev=prev):
                dump_now(f"signal:{signal.Signals(got).name}", force=True)
                if callable(prev) and prev not in (signal.SIG_IGN,
                                                   signal.SIG_DFL):
                    prev(got, frame)
                else:
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), got)

            signal.signal(signum, _handler)
        except ValueError:
            # not the main thread: excepthook-only installation
            break


def start_watchdog(window_s: float) -> bool:
    """Daemon thread that dumps when NO flight event has been recorded
    for ``window_s`` (armed by the first event, so an arbitrarily long
    first-step trace+compile can't trip it before anything ran). One
    instance per process; dumps once (the dedupe in :meth:`dump`
    absorbs re-trips)."""
    global _watchdog_started
    if _watchdog_started or window_s <= 0:
        return False
    _watchdog_started = True

    def _run() -> None:
        poll = max(min(window_s / 4.0, 1.0), 0.05)
        while True:
            time.sleep(poll)
            age = _recorder.last_age_s()
            if age is not None and age > window_s:
                dump_now("flight_watchdog")
                return

    threading.Thread(target=_run, name="flight-watchdog",
                     daemon=True).start()
    return True
