"""Watchtower: online anomaly detection + SLO burn-rate alerting.

PRs 1–2 built the *passive* observability floor (registry, spans,
goodput, flight ring, forensics) and PR 5 the SLO-instrumented serving
engine — but nothing watches those signals: a straggler drifting 20%
slower, a loss spike, a TTFT SLO burning down or KV-pool pressure all
sit silently in histograms until a human runs ``obs_report.py`` after
the fact. This module is the detection layer: a streaming engine that
subscribes to the stack's event feed (hooks in the Trainer step loop,
the serving engine/scheduler/server, and the elastic agent's watch
loop) and to the metric registry, and raises structured
:class:`Alert`\\ s:

- ``step_time_outlier`` — EWMA center + MAD scale over train-step wall
  times (a stddev would be dragged by the very outliers being hunted);
- ``loss_spike`` / ``loss_nonfinite`` — loss above its EWMA by a
  factor (warn) or NaN/inf (page: the run is wasting accelerator time
  from this step on);
- ``straggler_drift`` — supervisor-side: per-rank step-progress rates
  from the aggregate snapshots (``train_steps_total`` per rank over the
  native store); a rank progressing slower than the leave-one-out
  median of its peers by ``drift_factor`` pages *with the rank named*;
- ``queue_pressure`` / ``kv_pressure`` — serving admission queue near
  ``max_queue`` / KV-pool headroom below a floor (the early-warning
  signals ahead of ``backpressure`` rejects);
- ``slo_burn_rate`` — SRE-style multi-window burn rate (fast/slow
  window pair, default 5m/1h) over the TTFT and per-token-latency SLOs
  (``serve_ttft_seconds`` / ``serve_token_latency_seconds`` feeds; a
  rejected request spends TTFT error budget too — load shedding IS an
  SLO violation to the client) — pages only when BOTH windows burn,
  so a blip can't page and a slow leak still does;
- ``goodput_drop`` — goodput fraction under a floor at log cadence;
- ``replica_down`` — fleet feed (serve/fleet.py): a serving replica
  crashed or went heartbeat-stale; pages with the replica index and
  the stranded request ids being re-admitted on survivors;
- ``recompile_storm`` — compile-telemetry feed (obs/xray.py): the same
  jitted function re-compiling ``recompile_min`` times inside
  ``recompile_window_s`` mid-run (shape churn, cache-key drift) warns
  with the re-traced function named and the seconds lost;
- ``cost_anomaly`` — Abacus feed (obs/meter.py): a tenant whose billed
  FLOPs-per-token jumps ``cost_band_k``x above its own EWMA — a
  runaway decode budget or a prefix-cache-miss regression showing up
  as money before it shows up as latency. Warns with the tenant and
  the triggering request named; ``meter_cost_anomalies_total{tenant}``
  counts fires.

Page-severity alerts also start one bounded :mod:`obs.xray` profiler
capture when ``TPUNN_XRAY`` is armed — the alert's attribution then
names the capture directory next to the flight dump.

Every alert is a first-class event (:meth:`Watchtower._emit`, lint:
flight-ring record FIRST): ``watchtower_alerts_total{kind,severity}``
in the registry, a ``watchtower_alert`` JSONL record, an ``alert``
event in the flight ring, and — for page severity — an automatic
flight dump plus an inline :func:`obs.forensics.attribute`
classification so the alert names the suspect rank / collective /
request, not just the symptom.

Design contract (lint-enforced by tests/test_quality.py, mirroring
:mod:`runtime.chaos`):

- **inert when unset**: every module-level ``on_*`` hook opens with the
  literal ``if _tower is None: return`` fast path — an unset
  ``TPUNN_WATCH`` costs one global load + one comparison per hook, no
  allocation, no env read;
- **deterministic on replay**: detectors take time exclusively from
  the event's ``t`` field (never a wall clock), so replaying the same
  event stream twice yields byte-identical alert sequences
  (tests/test_watchtower.py) — the live ``on_*`` adapters stamp
  ``time.time()`` exactly once at the hook boundary;
- **emit-first**: :meth:`Watchtower._emit`'s first statement is the
  flight-ring record, so post-mortems can never miss an alert that
  fired before a crash.

Env contract: ``TPUNN_WATCH=1`` arms the defaults;
``TPUNN_WATCH=ttft_slo_s=0.25:burn_threshold=4`` overrides
:class:`WatchConfig` fields (``:``-separated ``key=value``; a typo'd
key fails loudly). ``scripts/obs_watch.py`` tails a live JSONL (or
replays one) and renders active alerts / burn rates.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import math
import os
import time
from typing import Optional

from pytorch_distributed_nn_tpu.obs import flight, forensics, xray
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.obs.stats import Ewma, mad, median

log = logging.getLogger(__name__)

ENV_WATCH = "TPUNN_WATCH"

WARN = "warn"
PAGE = "page"

ALERT_KINDS = ("step_time_outlier", "loss_spike", "loss_nonfinite",
               "straggler_drift", "queue_pressure", "kv_pressure",
               "slo_burn_rate", "goodput_drop", "replica_down",
               "recompile_storm", "cost_anomaly", "output_divergence")


@dataclasses.dataclass
class WatchConfig:
    """Detector thresholds; every field is overridable through the
    ``TPUNN_WATCH`` spec (see :func:`parse_spec`)."""

    # step-time outlier: EWMA center, MAD scale over a trailing window
    step_warmup: int = 20          # samples before the detector arms
    step_ewma_alpha: float = 0.1
    step_mad_k: float = 6.0        # threshold in MADs above the EWMA
    step_window: int = 64          # trailing samples feeding the MAD
    # loss
    loss_warmup: int = 5
    loss_ewma_alpha: float = 0.2
    loss_spike_factor: float = 2.0
    # straggler drift (supervisor feed: per-rank step totals over time)
    drift_factor: float = 1.5      # leave-one-out median rate ratio
    drift_min_samples: int = 3     # snapshots per rank before judging
    drift_history: int = 8         # retained snapshots per rank
    # serving pressure
    queue_frac: float = 0.9        # queue_depth / max_queue warn line
    kv_free_frac: float = 0.1      # free/total KV blocks page-ahead line
    # SLO burn rate (SRE multi-window: page when BOTH windows burn)
    ttft_slo_s: float = 0.5
    token_slo_s: float = 0.1
    slo_objective: float = 0.9     # success objective (error budget 10%)
    burn_fast_s: float = 300.0     # 5m fast window
    burn_slow_s: float = 3600.0    # 1h slow window
    burn_threshold: float = 2.0
    burn_min_events: int = 10      # samples in the fast window to judge
    # goodput
    goodput_floor: float = 0.5
    goodput_warmup: int = 2        # windows before the floor applies
    # recompile storm (compile-telemetry feed from obs/xray.py)
    recompile_min: int = 3         # same-function compiles to alert
    recompile_window_s: float = 120.0  # trailing window per function
    # cost anomaly (Abacus feed from obs/meter.py: billed FLOPs/token)
    cost_warmup: int = 8           # requests per tenant before judging
    cost_ewma_alpha: float = 0.2
    cost_band_k: float = 4.0       # threshold as a multiple of the EWMA


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(WatchConfig)}


def parse_spec(spec: str) -> WatchConfig:
    """``TPUNN_WATCH`` spec → :class:`WatchConfig`. ``"1"`` / ``"on"``
    mean defaults; otherwise ``:``-separated ``key=value`` overrides.
    Unknown keys raise (a typo'd watch spec must fail loudly, not
    silently watch nothing — the chaos-spec contract)."""
    cfg = WatchConfig()
    spec = (spec or "").strip()
    if spec in ("", "1", "on", "true"):
        return cfg
    for field in filter(None, spec.split(":")):
        key, eq, value = field.partition("=")
        key = key.strip()
        if not eq or key not in _FIELD_TYPES:
            raise ValueError(
                f"unknown watchtower key {key!r} in {spec!r}; have "
                f"{sorted(_FIELD_TYPES)}")
        try:
            kind = _FIELD_TYPES[key]
            setattr(cfg, key,
                    int(value) if kind in (int, "int") else float(value))
        except ValueError:
            raise ValueError(f"bad value for watchtower key {key!r}: "
                             f"{value!r}") from None
    return cfg


@dataclasses.dataclass
class Alert:
    """One structured alert. ``t`` / ``value`` / ``threshold`` derive
    from the triggering event only (replay-deterministic); ``seq`` is
    the position in this tower's alert stream."""

    seq: int
    kind: str
    severity: str  # WARN | PAGE
    t: float       # event time that triggered it
    step: int
    value: float
    threshold: float
    detail: str
    attribution: dict

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def as_json(self) -> str:
        """Canonical serialization — the byte-identical-replay unit."""
        return json.dumps(self.as_dict(), sort_keys=True)


class _BurnWindow:
    """One SLO's good/bad sample stream, pruned to the slow window;
    burn = error_fraction / error_budget over a trailing window, all in
    event time."""

    def __init__(self, objective: float, slow_s: float) -> None:
        self.budget = max(1.0 - objective, 1e-6)
        self.slow_s = slow_s
        self.samples: collections.deque[tuple[float, bool]] = \
            collections.deque()

    def add(self, t: float, bad: bool) -> None:
        self.samples.append((float(t), bool(bad)))
        while self.samples and self.samples[0][0] < t - self.slow_s:
            self.samples.popleft()

    def burn(self, window_s: float, now: float,
             min_events: int = 1) -> float:
        xs = [bad for (t, bad) in self.samples if t >= now - window_s]
        if len(xs) < min_events:
            return 0.0
        return (sum(xs) / len(xs)) / self.budget


class Watchtower:
    """The streaming detector engine. Feed it normalized events via
    :meth:`observe` (the module ``on_*`` hooks do, stamping wall time;
    replay feeds recorded times) — every detector is pure in the event
    stream."""

    def __init__(self, config: Optional[WatchConfig] = None, *,
                 rank: int = 0, metrics=None,
                 dump_on_page: bool = True) -> None:
        self.cfg = config or WatchConfig()
        self.rank = rank
        self.metrics = metrics  # MetricsLogger or None
        self.dump_on_page = dump_on_page
        self.alerts: list[Alert] = []
        reg = get_registry()
        self._c_alerts = reg.counter(
            "watchtower_alerts_total", "alerts raised",
            labels=("kind", "severity"))
        self._g_burn = reg.gauge(
            "watchtower_burn_rate", "SLO error-budget burn rate",
            labels=("slo", "window"))
        # -- detector state (event-time only) --
        cfg = self.cfg
        self._step_ewma = Ewma(cfg.step_ewma_alpha)
        self._step_window: collections.deque[float] = collections.deque(
            maxlen=cfg.step_window)
        self._loss_ewma = Ewma(cfg.loss_ewma_alpha)
        self._loss_spiking = False
        self._goodput_windows = 0
        self._goodput_low = False
        self._queue_high = False
        self._kv_low = False
        self._burn_active: set[str] = set()
        self._burns = {
            "ttft": _BurnWindow(cfg.slo_objective, cfg.burn_slow_s),
            "token_latency": _BurnWindow(cfg.slo_objective,
                                         cfg.burn_slow_s),
        }
        # rank -> trailing (t, steps_total) snapshots (supervisor feed)
        self._rank_hist: dict[int, collections.deque] = {}
        self._drifting: set[int] = set()
        # function name -> trailing (t, seconds) compile events
        self._compile_hist: dict[str, collections.deque] = {}
        # tenant -> billed-FLOPs-per-token EWMA (Abacus cost band);
        # the fires counter lives HERE, not in obs/meter — the meter
        # stays a pure ledger, the tower owns anomaly judgment
        self._cost_ewma: dict[str, Ewma] = {}
        self._cost_high: set[str] = set()
        self._c_cost_anomalies = reg.counter(
            "meter_cost_anomalies_total",
            "per-tenant cost-per-token anomalies (Abacus band breaks)",
            labels=("tenant",))
        # recent finished requests, worst-TTFT-first attribution feed
        self._recent_reqs: collections.deque[dict] = collections.deque(
            maxlen=32)
        # TTFT-budget idempotency (fleet failover): each request id
        # spends the TTFT error budget at most once — a request
        # re-admitted after a replica death, or rejected then retried,
        # must not burn the budget again (rejects already spend it
        # once). Bounded set: deque evicts the oldest charged id.
        self._ttft_charged: set[str] = set()
        self._ttft_charged_q: collections.deque[str] = \
            collections.deque(maxlen=4096)

    # -- the alert choke point -------------------------------------------

    def _emit(self, alert: Alert) -> None:
        """Every alert lands in the flight ring FIRST (lint-enforced:
        a crash right after an alert must still show it post-mortem),
        then the registry counter, the JSONL stream, and — page
        severity — the automatic flight dump."""
        flight.record("alert", alert.kind, step=alert.step,
                      note=f"{alert.severity} {alert.detail} "
                           f"attribution={json.dumps(alert.attribution, sort_keys=True)}")
        self._c_alerts.inc(kind=alert.kind, severity=alert.severity)
        self.alerts.append(alert)
        if self.metrics is not None:
            self.metrics.emit("watchtower_alert", **alert.as_dict())
        log.warning("watchtower %s alert: %s — %s", alert.severity,
                    alert.kind, alert.detail)
        if alert.severity == PAGE and self.dump_on_page:
            flight.dump_now(f"alert:{alert.kind}", force=True)

    def _raise(self, kind: str, severity: str, t: float, *,
               step: int = -1, value: float = 0.0,
               threshold: float = 0.0, detail: str = "",
               attribution: Optional[dict] = None) -> Alert:
        attribution = dict(attribution or {})
        if severity == PAGE:
            # inline forensics: the page names a suspect, not a symptom
            attribution.setdefault("forensics", forensics.attribute(
                flight.get_recorder().snapshot()))
            # anomaly-triggered profiling: a page starts one bounded
            # xray capture (rate limiter permitting) and the alert
            # names where it landed — inert no-op when TPUNN_XRAY is
            # unset, so replayed streams stay byte-identical
            cap = xray.on_page(kind, step=step)
            if cap:
                attribution.setdefault("xray_capture", cap)
        alert = Alert(
            seq=len(self.alerts), kind=kind, severity=severity,
            t=round(float(t), 6), step=int(step),
            value=round(float(value), 6),
            threshold=round(float(threshold), 6),
            detail=detail, attribution=attribution,
        )
        self._emit(alert)
        return alert

    # -- event intake ----------------------------------------------------

    def observe(self, event: dict) -> None:
        """Dispatch one normalized event (must carry ``ev`` and ``t``)
        to its detector. Unknown kinds are ignored (a newer stream must
        replay on an older tower)."""
        handler = self._HANDLERS.get(event.get("ev", ""))
        if handler is not None:
            handler(self, event)

    def _obs_train_step(self, ev: dict) -> None:
        cfg, w, t = self.cfg, float(ev["wall_s"]), float(ev["t"])
        step = int(ev.get("step", -1))
        center = self._step_ewma.value
        if (center is not None
                and len(self._step_window) >= cfg.step_warmup):
            scale = max(mad(self._step_window), 0.05 * center, 1e-6)
            thr = center + cfg.step_mad_k * scale
            if w > thr:
                self._raise(
                    "step_time_outlier", WARN, t, step=step, value=w,
                    threshold=thr,
                    detail=f"step {step} took {w:.4f}s vs EWMA "
                           f"{center:.4f}s (> {cfg.step_mad_k:g} MADs)")
        # update AFTER the check: an outlier must not mask itself
        self._step_window.append(w)
        self._step_ewma.update(w)

    def _obs_loss(self, ev: dict) -> None:
        cfg, t = self.cfg, float(ev["t"])
        step = int(ev.get("step", -1))
        loss = float(ev["loss"])
        if not math.isfinite(loss):
            self._raise(
                "loss_nonfinite", PAGE, t, step=step, value=loss,
                detail=f"loss is {loss!r} at step {step}: every step "
                       f"from here is wasted accelerator time")
            return
        center = self._loss_ewma.value
        if (center is not None and center > 0
                and self._loss_ewma.count >= cfg.loss_warmup):
            thr = cfg.loss_spike_factor * center
            if loss > thr and not self._loss_spiking:
                self._loss_spiking = True
                self._raise(
                    "loss_spike", WARN, t, step=step, value=loss,
                    threshold=thr,
                    detail=f"loss {loss:.4f} at step {step} is "
                           f">{cfg.loss_spike_factor:g}x its EWMA "
                           f"{center:.4f}")
            elif loss <= center:
                self._loss_spiking = False  # re-arm after recovery
        self._loss_ewma.update(loss)

    def _obs_goodput(self, ev: dict) -> None:
        cfg, t = self.cfg, float(ev["t"])
        frac = float(ev["goodput_frac"])
        self._goodput_windows += 1
        if self._goodput_windows <= cfg.goodput_warmup:
            return
        if frac < cfg.goodput_floor and not self._goodput_low:
            self._goodput_low = True
            self._raise(
                "goodput_drop", WARN, t, step=int(ev.get("step", -1)),
                value=frac, threshold=cfg.goodput_floor,
                detail=f"goodput fraction {frac:.3f} under the "
                       f"{cfg.goodput_floor:g} floor")
        elif frac >= cfg.goodput_floor:
            self._goodput_low = False

    def _obs_serve_round(self, ev: dict) -> None:
        cfg, t = self.cfg, float(ev["t"])
        rnd = int(ev.get("round", -1))
        wall = float(ev.get("wall_s", 0.0))
        bw = self._burns["token_latency"]
        bw.add(t, wall > cfg.token_slo_s)
        self._check_burn("token_latency", cfg.token_slo_s, t, step=rnd)
        self._obs_serve_queue(ev)
        kv_total = int(ev.get("kv_total", 0))
        if kv_total > 0:
            free = int(ev.get("kv_free", 0)) / kv_total
            if free <= cfg.kv_free_frac and not self._kv_low:
                self._kv_low = True
                self._raise(
                    "kv_pressure", WARN, t, step=rnd, value=free,
                    threshold=cfg.kv_free_frac,
                    detail=f"KV-pool headroom {free:.2%} at round "
                           f"{rnd} — admissions will stall next")
            elif free > 2 * cfg.kv_free_frac:
                self._kv_low = False

    def _obs_serve_queue(self, ev: dict) -> None:
        cfg, t = self.cfg, float(ev["t"])
        qmax = int(ev.get("queue_max", 0))
        if qmax <= 0:
            return
        frac = int(ev.get("queue_depth", 0)) / qmax
        if frac >= cfg.queue_frac and not self._queue_high:
            self._queue_high = True
            self._raise(
                "queue_pressure", WARN, t,
                step=int(ev.get("round", -1)), value=frac,
                threshold=cfg.queue_frac,
                detail=f"admission queue at {frac:.0%} of max_queue="
                       f"{qmax} — backpressure rejects are imminent")
        elif frac < 0.5 * cfg.queue_frac:
            self._queue_high = False

    def _obs_serve_request(self, ev: dict) -> None:
        cfg, t = self.cfg, float(ev["t"])
        ok = bool(ev.get("ok", True))
        ttft = float(ev.get("ttft_s", 0.0))
        rid = str(ev.get("request_id", ""))
        tenant = str(ev.get("tenant", "default"))
        entry = {
            "request_id": rid, "tenant": tenant,
            "ttft_s": round(ttft, 6), "ok": ok,
            "waterfall": ev.get("waterfall"),
        }
        # Causeway (obs/trace.py): a traced request carries its
        # trace_id into the worst-offender attribution, so an SLO-burn
        # page names the exact trace to pull the waterfall for. Key
        # absent when untraced — replaying an untraced stream stays
        # byte-identical.
        if ev.get("trace"):
            entry["trace"] = ev["trace"]
        self._recent_reqs.append(entry)
        # one budget sample per request id (set-based, so replaying the
        # same stream stays byte-identical): the first terminal outcome
        # — reject or completion — is the one the client experienced;
        # a fleet re-admission of the same id must not charge twice
        if rid and rid in self._ttft_charged:
            return
        if rid:
            if len(self._ttft_charged_q) == self._ttft_charged_q.maxlen:
                self._ttft_charged.discard(self._ttft_charged_q[0])
            self._ttft_charged_q.append(rid)
            self._ttft_charged.add(rid)
        bad = (not ok) or ttft > cfg.ttft_slo_s
        self._burns["ttft"].add(t, bad)
        self._check_burn("ttft", cfg.ttft_slo_s, t)
        # per-tenant TTFT window, created lazily on first sight: one
        # tenant burning its whole budget must page WITH THE TENANT
        # NAMED even while healthy neighbors keep the global window
        # under the threshold (the noisy-neighbor blind spot). The
        # default tenant IS the global window — no second window, so a
        # single-tenant burn still raises exactly one page.
        if tenant != "default":
            key = f"ttft:{tenant}"
            if key not in self._burns:
                self._burns[key] = _BurnWindow(cfg.slo_objective,
                                               cfg.burn_slow_s)
            self._burns[key].add(t, bad)
            self._check_burn(key, cfg.ttft_slo_s, t)

    def _obs_serve_reject(self, ev: dict) -> None:
        # a shed request spends TTFT error budget: the client saw an
        # error, not a fast first token
        ev = dict(ev, ok=False, ttft_s=math.inf)
        self._obs_serve_request(ev)

    def _obs_rank_progress(self, ev: dict) -> None:
        """Supervisor feed: {rank: train_steps_total} snapshots. A rank
        whose progress *rate* falls under the leave-one-out median of
        its peers by ``drift_factor`` pages with the rank named."""
        cfg, t = self.cfg, float(ev["t"])
        for rank, steps in ev.get("steps", {}).items():
            rank = int(rank)
            hist = self._rank_hist.setdefault(
                rank, collections.deque(maxlen=cfg.drift_history))
            hist.append((t, float(steps)))
        rates: dict[int, float] = {}
        for rank, hist in self._rank_hist.items():
            if len(hist) < cfg.drift_min_samples:
                continue
            (t0, s0), (t1, s1) = hist[0], hist[-1]
            if t1 > t0:
                rates[rank] = max(s1 - s0, 0.0) / (t1 - t0)
        if len(rates) < 2:
            return
        for rank, rate in sorted(rates.items()):
            others = [r for rk, r in rates.items() if rk != rank]
            base = median(others)
            if base <= 0:
                continue
            if rate < base / cfg.drift_factor:
                if rank not in self._drifting:
                    self._drifting.add(rank)
                    self._raise(
                        "straggler_drift", PAGE, t, value=rate,
                        threshold=base / cfg.drift_factor,
                        detail=f"rank {rank} progresses at "
                               f"{rate:.2f} steps/s vs peer median "
                               f"{base:.2f} (≥{cfg.drift_factor:g}x "
                               f"drift)",
                        attribution={"rank": rank,
                                     "rate_steps_per_s": round(rate, 4),
                                     "peer_median_steps_per_s":
                                         round(base, 4)})
            elif rate >= base:
                self._drifting.discard(rank)

    def _obs_replica_down(self, ev: dict) -> None:
        """Fleet feed (serve/fleet.py): a serving replica crashed or
        went heartbeat-stale. Always a page — every stream it held is
        mid-failover and its capacity is gone until a restart."""
        t = float(ev["t"])
        replica = int(ev.get("replica", -1))
        reason = str(ev.get("reason", ""))
        stranded = [str(r) for r in ev.get("stranded", [])]
        self._raise(
            "replica_down", PAGE, t, value=float(len(stranded)),
            detail=f"replica {replica} down ({reason}); "
                   f"{len(stranded)} in-flight request(s) re-admitted "
                   f"on survivors",
            attribution={"replica": replica, "reason": reason,
                         "stranded_requests": stranded})

    def _obs_output_divergence(self, ev: dict) -> None:
        """Lighthouse feed (obs/audit.py): two legs of the same request
        — or a golden probe — produced different fingerprint chains.
        Always a page: every metric around the diverging replica is
        green by construction (that is the failure mode), so this
        alert is the ONLY line of defense. Names the disagreeing pair
        and the suspected replica; the page auto-dump + Xray capture
        preserve the evidence before quarantine tears the replica out
        of the fleet."""
        t = float(ev["t"])
        kind = str(ev.get("check", ""))
        rid = str(ev.get("request_id", ""))
        pair = [str(p) for p in ev.get("pair", [])]
        suspect = str(ev.get("suspect", ""))
        self._raise(
            "output_divergence", PAGE, t, value=1.0,
            detail=f"output divergence ({kind}) on {rid or 'probe'}: "
                   f"replicas {pair} disagree; suspect {suspect or '?'}",
            attribution={"check": kind, "request_id": rid,
                         "pair": pair, "suspect": suspect})

    def _obs_compile(self, ev: dict) -> None:
        """Compile-telemetry feed (obs/xray.py log watch): the same
        function re-compiling ``recompile_min`` times inside a
        ``recompile_window_s`` trailing window is a jit cache-miss
        storm — shape churn or cache-key drift stalling the very steps
        it lands on. Warns with the re-traced function NAMED; firing
        clears that function's history, so re-alerting needs a whole
        fresh storm (hysteresis)."""
        cfg, t = self.cfg, float(ev["t"])
        name = str(ev.get("name", ""))
        hist = self._compile_hist.setdefault(name, collections.deque())
        hist.append((t, float(ev.get("seconds", 0.0))))
        while hist and hist[0][0] < t - cfg.recompile_window_s:
            hist.popleft()
        if len(hist) < cfg.recompile_min:
            return
        n, total_s = len(hist), sum(s for _, s in hist)
        hist.clear()
        self._raise(
            "recompile_storm", WARN, t, value=float(n),
            threshold=float(cfg.recompile_min),
            detail=f"{name!r} re-compiled {n}x within "
                   f"{cfg.recompile_window_s:g}s ({total_s:.2f}s lost "
                   f"to compilation) — jit cache misses mid-run",
            attribution={"function": name, "count": n,
                         "compile_seconds": round(total_s, 4)})

    def _obs_tenant_cost(self, ev: dict) -> None:
        """Abacus feed: one finished request's billed FLOPs-per-token
        vs the tenant's own EWMA. A tenant is its own baseline — a
        genuinely expensive tenant settles into a high center and stays
        quiet; the alert is for a *change* (decode-budget runaway,
        prefix-cache-miss regression). Hysteresis per tenant: re-arms
        only after the cost falls back to the center."""
        cfg, t = self.cfg, float(ev["t"])
        tenant = str(ev.get("tenant", "default"))
        cost = float(ev["cost_per_token"])
        ew = self._cost_ewma.setdefault(tenant, Ewma(cfg.cost_ewma_alpha))
        center = ew.value
        if (center is not None and center > 0
                and ew.count >= cfg.cost_warmup):
            thr = cfg.cost_band_k * center
            if cost > thr and tenant not in self._cost_high:
                self._cost_high.add(tenant)
                self._c_cost_anomalies.inc(tenant=tenant)
                self._raise(
                    "cost_anomaly", WARN, t, value=cost, threshold=thr,
                    detail=f"tenant {tenant!r} billed {cost:.0f} "
                           f"FLOPs/token vs its EWMA {center:.0f} "
                           f"(>{cfg.cost_band_k:g}x band) — runaway "
                           f"budget or cache-miss regression",
                    attribution={
                        "tenant": tenant,
                        "request_id": str(ev.get("request_id", "")),
                        "cost_per_token": round(cost, 4),
                        "ewma_cost_per_token": round(center, 4)})
            elif cost <= center:
                self._cost_high.discard(tenant)  # re-arm on recovery
        # update AFTER the check: an anomaly must not mask itself
        ew.update(cost)

    _HANDLERS = {
        "train_step": _obs_train_step,
        "loss": _obs_loss,
        "goodput": _obs_goodput,
        "serve_round": _obs_serve_round,
        "serve_queue": _obs_serve_queue,
        "serve_request": _obs_serve_request,
        "serve_reject": _obs_serve_reject,
        "rank_progress": _obs_rank_progress,
        "replica_down": _obs_replica_down,
        "compile": _obs_compile,
        "tenant_cost": _obs_tenant_cost,
        "output_divergence": _obs_output_divergence,
    }

    # -- burn-rate core --------------------------------------------------

    def _check_burn(self, slo: str, slo_s: float, t: float, *,
                    step: int = -1) -> None:
        cfg = self.cfg
        bw = self._burns[slo]
        fast = bw.burn(cfg.burn_fast_s, t,
                       min_events=cfg.burn_min_events)
        slow = bw.burn(cfg.burn_slow_s, t,
                       min_events=cfg.burn_min_events)
        self._g_burn.set(round(fast, 4), slo=slo, window="fast")
        self._g_burn.set(round(slow, 4), slo=slo, window="slow")
        firing = (fast >= cfg.burn_threshold
                  and slow >= cfg.burn_threshold)
        if firing and slo not in self._burn_active:
            self._burn_active.add(slo)
            base, _, tenant = slo.partition(":")
            worst = max((r for r in self._recent_reqs
                         if (not r["ok"] or r["ttft_s"] > slo_s)
                         and (not tenant or r.get("tenant") == tenant)),
                        key=lambda r: r["ttft_s"],
                        default=None) if base == "ttft" else None
            attribution = {"slo": slo,
                           "burn_fast": round(fast, 4),
                           "burn_slow": round(slow, 4)}
            if tenant:
                attribution["tenant"] = tenant
            if worst is not None:
                attribution["request"] = worst
            self._raise(
                "slo_burn_rate", PAGE, t, step=step, value=fast,
                threshold=cfg.burn_threshold,
                detail=f"{slo} SLO ({slo_s:g}s @ "
                       f"{cfg.slo_objective:.0%}) burning "
                       f"{fast:.1f}x budget over the fast window and "
                       f"{slow:.1f}x over the slow window",
                attribution=attribution)
        elif slo in self._burn_active and fast < cfg.burn_threshold:
            self._burn_active.discard(slo)  # re-arm after recovery

    def burn_rates(self, now: float) -> dict:
        """Exported burn-rate accessor (the Helm autoscaler's input,
        serve/autoscale.py): per-SLO fast/slow burn at event time
        ``now``, computed by the very windows :meth:`_check_burn` pages
        from — the autoscaler and the pager can never disagree about
        how hard the error budget is burning. Pure in the observed
        event stream (no wall clock), so replaying a recorded run
        reproduces the exact evidence every decision journaled."""
        cfg = self.cfg
        return {
            slo: {
                "fast": round(bw.burn(cfg.burn_fast_s, now,
                                      min_events=cfg.burn_min_events),
                              6),
                "slow": round(bw.burn(cfg.burn_slow_s, now,
                                      min_events=cfg.burn_min_events),
                              6),
            }
            for slo, bw in sorted(self._burns.items())
        }

    # -- registry subscription -------------------------------------------

    def poll_registry(self, t: float, registry=None) -> None:
        """Pull-side feed for processes that own a registry but no
        serve/train hook path (the supervisor between snapshots): maps
        the live gauges onto the same detectors the push hooks drive."""
        reg = registry if registry is not None else get_registry()
        flat = reg.snapshot()
        if "goodput_frac" in flat:
            self.observe({"ev": "goodput", "t": t,
                          "goodput_frac": flat["goodput_frac"]})
        if "serve_queue_depth" in flat:
            self.observe({
                "ev": "serve_queue", "t": t,
                "queue_depth": flat["serve_queue_depth"],
                "queue_max": flat.get("serve_queue_max", 0)})

    # -- rendering --------------------------------------------------------

    def summary(self) -> dict:
        """Active/total alert snapshot (obs_watch + tests)."""
        counts: dict[str, int] = {}
        for a in self.alerts:
            counts[a.kind] = counts.get(a.kind, 0) + 1
        return {
            "alerts_total": len(self.alerts),
            "by_kind": counts,
            "pages": sum(a.severity == PAGE for a in self.alerts),
            "burns_active": sorted(self._burn_active),
            "drifting_ranks": sorted(self._drifting),
        }


def events_from_jsonl(rec: dict) -> list[dict]:
    """Map one JSONL record from a run's metrics stream onto normalized
    watchtower events (``scripts/obs_watch.py`` replay/tail path).
    ``MetricsLogger.emit`` stamps ``time`` on every record, so replay
    is exact in event time."""
    ev = rec.get("event")
    t = float(rec.get("time", 0.0))
    out: list[dict] = []
    if ev == "train_step" and "loss" in rec:
        out.append({"ev": "loss", "t": t,
                    "step": int(rec.get("step", -1)),
                    "loss": float(rec["loss"])})
        if rec.get("seconds"):
            out.append({"ev": "train_step", "t": t,
                        "step": int(rec.get("step", -1)),
                        "wall_s": float(rec["seconds"])})
    elif ev == "goodput" and rec.get("goodput_frac") is not None:
        g = {"ev": "goodput", "t": t,
             "step": int(rec.get("step", -1)),
             "goodput_frac": float(rec["goodput_frac"])}
        out.append(g)
        wall, steps = rec.get("wall_s"), rec.get("steps")
        if wall and steps:
            out.append({"ev": "train_step", "t": t,
                        "step": int(rec.get("step", -1)),
                        "wall_s": float(wall) / max(int(steps), 1)})
    elif ev == "serve_request":
        e = {"ev": "serve_request", "t": t, "ok": True,
             "request_id": rec.get("request_id", ""),
             "ttft_s": float(rec.get("ttft_s", 0.0)),
             "waterfall": rec.get("waterfall")}
        if "tenant" in rec:
            e["tenant"] = rec["tenant"]
        out.append(e)
    elif ev == "serve_reject":
        e = {"ev": "serve_reject", "t": t,
             "request_id": rec.get("request_id", ""),
             "reason": rec.get("reason", "")}
        if "tenant" in rec:
            e["tenant"] = rec["tenant"]
        out.append(e)
    elif ev == "fleet_replica_down":
        out.append({"ev": "replica_down", "t": t,
                    "replica": int(rec.get("replica", -1)),
                    "reason": rec.get("reason", ""),
                    "stranded": rec.get("stranded", [])})
    elif ev == "audit_divergence":
        # Lighthouse replay: a recorded divergence re-raises the page
        out.append({"ev": "output_divergence", "t": t,
                    "check": rec.get("kind", ""),
                    "request_id": rec.get("request_id", ""),
                    "pair": rec.get("pair", []),
                    "suspect": rec.get("suspect", "")})
    elif ev == "meter_request":
        # Abacus replay: a recorded run's per-request billing drives
        # the cost band exactly as the live on_tenant_cost hook did
        toks = int(rec.get("tokens", 0))
        if toks > 0:
            out.append({"ev": "tenant_cost", "t": t,
                        "tenant": rec.get("tenant", "default"),
                        "cost_per_token": float(rec.get("flops", 0))
                        / toks,
                        "request_id": rec.get("request_id", "")})
    return out


# ---------------------------------------------------------------------------
# Module singleton + the inert hot-path hooks (chaos-style lint contract)
# ---------------------------------------------------------------------------

_tower: Watchtower | None = None


def maybe_init(spec: str | None = None, *, metrics=None,
               rank: int | None = None,
               config: WatchConfig | None = None) -> Watchtower | None:
    """Arm the process tower from ``TPUNN_WATCH`` (or an explicit
    ``spec``/``config``). No-op beyond one env read when unset or
    ``"0"``; idempotent when armed."""
    global _tower
    if _tower is not None:
        return _tower
    spec = os.environ.get(ENV_WATCH) if spec is None else spec
    if not spec or spec == "0":
        return None
    _tower = Watchtower(
        config if config is not None else parse_spec(spec),
        rank=flight.default_rank() if rank is None else rank,
        metrics=metrics,
    )
    log.warning("watchtower armed: %s (rank %d)", spec, _tower.rank)
    return _tower


def enabled() -> bool:
    return _tower is not None


def tower() -> Watchtower | None:
    return _tower


def reset() -> None:
    """Disarm (test isolation)."""
    global _tower
    _tower = None


def on_train_step(step: int, wall_s: float) -> None:
    """Trainer step-loop hook (step-time outlier)."""
    if _tower is None:
        return
    _tower.observe({"ev": "train_step", "t": time.time(),
                    "step": int(step), "wall_s": float(wall_s)})


def on_loss(step: int, loss: float) -> None:
    """Trainer log-cadence hook (loss spike / NaN-inf page)."""
    if _tower is None:
        return
    _tower.observe({"ev": "loss", "t": time.time(), "step": int(step),
                    "loss": float(loss)})


def on_goodput(step: int, goodput_frac: float) -> None:
    """Trainer telemetry-flush hook (goodput floor)."""
    if _tower is None:
        return
    _tower.observe({"ev": "goodput", "t": time.time(),
                    "step": int(step),
                    "goodput_frac": float(goodput_frac)})


def on_serve_round(round_: int, wall_s: float, *, queue_depth: int,
                   queue_max: int, kv_free: int, kv_total: int) -> None:
    """Serving-engine per-round hook (token-latency SLO, queue/KV
    pressure). Called from ``ServingEngine.step`` — never from the
    ``_decode_round`` hot loop (its lint bans extra work there)."""
    if _tower is None:
        return
    _tower.observe({"ev": "serve_round", "t": time.time(),
                    "round": int(round_), "wall_s": float(wall_s),
                    "queue_depth": int(queue_depth),
                    "queue_max": int(queue_max),
                    "kv_free": int(kv_free),
                    "kv_total": int(kv_total)})


def on_serve_request(rec: dict) -> None:
    """Request-retire hook (TTFT SLO burn; ``rec`` is the engine's
    ``serve_request`` record, waterfall included)."""
    if _tower is None:
        return
    _tower.observe({"ev": "serve_request", "t": time.time(), "ok": True,
                    "request_id": rec.get("request_id", ""),
                    "tenant": rec.get("tenant", "default"),
                    "ttft_s": float(rec.get("ttft_s", 0.0)),
                    "waterfall": rec.get("waterfall")})


def on_serve_reject(request_id: str, reason: str,
                    tenant: str = "default") -> None:
    """Scheduler rejection hook — shed traffic burns TTFT budget (the
    rejected tenant's, so a quota-capped flood burns its own window)."""
    if _tower is None:
        return
    _tower.observe({"ev": "serve_reject", "t": time.time(),
                    "request_id": request_id, "reason": reason,
                    "tenant": str(tenant)})


def on_serve_submit(request_id: str, queue_depth: int,
                    queue_max: int) -> None:
    """Server submission-path hook: queue pressure stays visible from
    client threads even when the engine loop itself is wedged."""
    if _tower is None:
        return
    _tower.observe({"ev": "serve_queue", "t": time.time(),
                    "queue_depth": int(queue_depth),
                    "queue_max": int(queue_max)})


def on_rank_progress(steps_by_rank: dict) -> None:
    """Elastic-agent hook (straggler drift from aggregate snapshots)."""
    if _tower is None:
        return
    _tower.observe({"ev": "rank_progress", "t": time.time(),
                    "steps": dict(steps_by_rank)})


def on_replica_down(replica: int, reason: str,
                    stranded: list | None = None) -> None:
    """Fleet supervisor hook (serve/fleet.py): a replica crashed or
    went stale; ``stranded`` lists the request ids being re-admitted."""
    if _tower is None:
        return
    _tower.observe({"ev": "replica_down", "t": time.time(),
                    "replica": int(replica), "reason": str(reason),
                    "stranded": list(stranded or [])})


def on_output_divergence(kind: str, *, request_id: str = "",
                         pair=(), suspect: str = "") -> None:
    """Lighthouse hook (obs/audit.py): a confirmed fingerprint
    divergence — shadow-replay mismatch or golden-probe failure.
    ``pair`` names the disagreeing replicas, ``suspect`` the one the
    tie-break blamed. Both layers armed independently (the audit
    records the divergence either way; the page needs the tower)."""
    if _tower is None:
        return
    _tower.observe({"ev": "output_divergence", "t": time.time(),
                    "check": str(kind),
                    "request_id": str(request_id),
                    "pair": [str(p) for p in pair],
                    "suspect": str(suspect)})


def on_compile(name: str, seconds: float) -> None:
    """Compile-telemetry hook (obs/xray.py log watch): one observed
    XLA compilation of ``name`` feeds the recompile_storm detector."""
    if _tower is None:
        return
    _tower.observe({"ev": "compile", "t": time.time(),
                    "name": str(name), "seconds": float(seconds)})


def on_tenant_cost(tenant: str, cost_per_token: float,
                   request_id: str = "") -> None:
    """Abacus hook (obs/meter.py request accounting): one finished
    request's billed FLOPs-per-token feeds the per-tenant cost band.
    Both layers armed independently — metering without watching (pure
    showback) and watching without metering (no cost feed) are valid."""
    if _tower is None:
        return
    _tower.observe({"ev": "tenant_cost", "t": time.time(),
                    "tenant": str(tenant),
                    "cost_per_token": float(cost_per_token),
                    "request_id": str(request_id)})
