"""Goodput accounting: decompose wall step time into phases.

The trainer can report *that* a step took 41 ms; this meter reports
where it went: ``data`` (host batch wait), ``compute`` (dispatch +
device fence), ``collective`` (trace-derived share of compute, when a
profile is available), ``checkpoint``, ``eval``, and ``other`` (the
unattributed remainder — Python loop overhead, logging). The breakdown
is what the EQuARX / pjit-scaling style of perf work needs: you cannot
shrink a phase you cannot see.

Accounting contract:

- phases are measured on the host with ``perf_counter`` inside
  :meth:`GoodputMeter.phase` blocks nested in a
  :meth:`step_start`/:meth:`step_end` window;
- ``other = wall − Σ(measured phases)`` per step, so the published
  breakdown sums to wall by construction; ``accounted_frac`` (measured
  phases / wall) is reported alongside so "other" can never silently
  swallow the step;
- async dispatch: device execution hides behind the dispatch queue, so
  host-side "compute" is dispatch time plus whatever fence the loop
  performs (device_get of the loss at log cadence). Per-window sums are
  honest — within a window the device cannot outrun the host by more
  than the queue depth;
- the collective share cannot be host-timed inside one fused step; it
  is either trace-derived (``utils.profiling.collective_trace_seconds``
  over an xprof capture) or estimated downstream from the recorded
  ``wire_bytes_per_step`` (``ops.collectives.CommRecorder``) — the
  meter carries both so ``scripts/obs_report.py`` can cross-check one
  against the other.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time

from pytorch_distributed_nn_tpu.obs import span as _span

PHASES = ("data", "compute", "collective", "checkpoint", "eval", "other")


@dataclasses.dataclass
class StepBreakdown:
    """One step (or one fused window) decomposed into phase seconds."""

    step: int
    wall_s: float
    phases: dict[str, float]  # measured phases + computed "other"
    accounted_frac: float  # measured (non-other) phases / wall

    def as_fields(self) -> dict:
        """Flat JSONL-able fields (the ``goodput`` event payload)."""
        out = {"step": self.step, "wall_s": round(self.wall_s, 6),
               "accounted_frac": round(self.accounted_frac, 4)}
        for name in PHASES:
            out[f"{name}_s"] = round(self.phases.get(name, 0.0), 6)
        return out


class GoodputMeter:
    """Per-step phase accumulator + running totals.

    One instance per training loop. Every :meth:`phase` block also
    emits an obs span (same names), so a trace capture and the JSONL
    breakdown describe the same windows.
    """

    def __init__(self) -> None:
        self.totals: dict[str, float] = {p: 0.0 for p in PHASES}
        self.total_wall_s = 0.0
        self.steps = 0
        self.wire_bytes_per_step: float | None = None
        self._win_totals: dict[str, float] = {p: 0.0 for p in PHASES}
        self._win_wall_s = 0.0
        self._win_steps = 0
        self._step_t0: float | None = None
        self._step_phases: dict[str, float] = {}

    # -- per-step window -------------------------------------------------

    def step_start(self) -> None:
        self._step_t0 = time.perf_counter()
        self._step_phases = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time one phase of the current step (nested spans allowed;
        unknown names raise so breakdowns stay schema-stable)."""
        if name not in PHASES or name == "other":
            raise ValueError(f"unknown goodput phase {name!r}")
        t0 = time.perf_counter()
        with _span.span(f"goodput/{name}", cat="goodput"):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self._step_phases[name] = (
                    self._step_phases.get(name, 0.0) + dt
                )

    def add_phase_seconds(self, name: str, seconds: float) -> None:
        """Attribute already-measured seconds (e.g. a trace-derived
        collective share) to the current step."""
        if name not in PHASES or name == "other":
            raise ValueError(f"unknown goodput phase {name!r}")
        self._step_phases[name] = (
            self._step_phases.get(name, 0.0) + float(seconds)
        )

    def step_end(self, step: int = -1, *,
                 steps_covered: int = 1) -> StepBreakdown:
        """Close the window opened by :meth:`step_start`. A fused
        multistep dispatch passes ``steps_covered=k`` so throughput
        totals stay per-optimizer-step comparable."""
        if self._step_t0 is None:
            raise RuntimeError("step_end without step_start")
        wall = time.perf_counter() - self._step_t0
        self._step_t0 = None
        measured = sum(self._step_phases.values())
        phases = dict(self._step_phases)
        # collective time is a SHARE of compute when trace-derived;
        # never let the remainder go negative from double counting
        phases["other"] = max(wall - measured, 0.0)
        bd = StepBreakdown(
            step=step, wall_s=wall, phases=phases,
            accounted_frac=min(measured / wall, 1.0) if wall > 0 else 0.0,
        )
        self.steps += steps_covered
        self.total_wall_s += wall
        self._win_steps += steps_covered
        self._win_wall_s += wall
        for name, v in phases.items():
            self.totals[name] = self.totals.get(name, 0.0) + v
            self._win_totals[name] = self._win_totals.get(name, 0.0) + v
        return bd

    # -- windows / summaries ---------------------------------------------

    def window_summary(self, *, reset: bool = True) -> dict:
        """Aggregate since the last window flush (the log-cadence
        ``goodput`` JSONL event payload)."""
        out = self._summarize(self._win_totals, self._win_wall_s,
                              self._win_steps)
        if reset:
            self._win_totals = {p: 0.0 for p in PHASES}
            self._win_wall_s = 0.0
            self._win_steps = 0
        return out

    def summary(self) -> dict:
        """Whole-run aggregate."""
        return self._summarize(self.totals, self.total_wall_s, self.steps)

    def _summarize(self, totals: dict, wall: float, steps: int) -> dict:
        out = {"steps": steps, "wall_s": round(wall, 6)}
        for name in PHASES:
            v = totals.get(name, 0.0)
            out[f"{name}_s"] = round(v, 6)
            out[f"{name}_frac"] = round(v / wall, 4) if wall > 0 else 0.0
        measured = sum(totals.get(p, 0.0) for p in PHASES if p != "other")
        out["accounted_frac"] = (round(min(measured / wall, 1.0), 4)
                                 if wall > 0 else 0.0)
        # goodput in the step-time sense: the share of wall doing the
        # actual training work (device compute incl. collectives)
        out["goodput_frac"] = (
            round((totals.get("compute", 0.0)
                   + totals.get("collective", 0.0)) / wall, 4)
            if wall > 0 else 0.0
        )
        if self.wire_bytes_per_step is not None:
            out["wire_bytes_per_step"] = round(self.wire_bytes_per_step, 1)
        return out


def restart_context() -> dict:
    """Restart/backoff accounting to attach to a goodput summary: which
    incarnation this process is (``TPUNN_RESTART``), whether a chaos
    engine is armed, and — when the elastic agent shares this process's
    registry (in-process ``launch()``) — the agent's restart, backoff,
    and preemption gauges. Interrupted runs thereby account their lost
    time instead of silently reporting only the surviving window."""
    import os

    from pytorch_distributed_nn_tpu.obs.registry import get_registry

    out: dict = {"incarnation": int(os.environ.get("TPUNN_RESTART", "0")
                                    or 0)}
    try:  # lazy: goodput must not drag runtime/ in at import time
        from pytorch_distributed_nn_tpu.runtime import chaos

        out["chaos_enabled"] = chaos.enabled()
    except Exception:  # pragma: no cover - import cycles in stubs
        pass
    snap = get_registry().snapshot()
    for key in ("agent_incarnations_total", "agent_restarts_total",
                "agent_preempt_restarts_total",
                "agent_backoff_seconds_total"):
        if key in snap:
            out[key] = snap[key]
    return out
