"""Cross-host metric aggregation through the native rendezvous store.

Per-host registries are process-local; a pod-level view needs one place
to read. The job's C++ store (native/store.cpp — already connected for
rendezvous + heartbeats) doubles as the transport: each host publishes
its flat registry snapshot under ``obs/<incarnation>/<rank>`` at log
cadence, and the coordinator pulls and merges them. No new service, no
listener ports on workers.

Merging semantics: counters (``*_total``) sum across hosts; everything
else (gauges, histogram sums/counts are also summed — a histogram count
IS a counter) keeps per-host values under a ``rank`` label in
:func:`merge_snapshots`'s ``per_rank`` view, with sums in ``summed``.
"""

from __future__ import annotations

import json
import logging

from pytorch_distributed_nn_tpu.obs.registry import (
    MetricRegistry,
    get_registry,
)

log = logging.getLogger(__name__)

_KEY_FMT = "obs/{incarnation}/{rank}"


def publish_snapshot(client, *, rank: int, incarnation: int = 0,
                     registry: MetricRegistry | None = None) -> str:
    """Write this host's flat snapshot to the store; returns the key."""
    reg = registry or get_registry()
    key = _KEY_FMT.format(incarnation=incarnation, rank=rank)
    client.set(key, json.dumps(reg.snapshot()).encode())
    return key


def maybe_publish(registry: MetricRegistry | None = None) -> bool:
    """Publish through the heartbeat reporter's live store connection
    (the one :func:`runtime.failure.maybe_start_heartbeat` opened).
    No-op outside the elastic agent; never raises into the train loop —
    a flaky store must not kill training for a metrics push."""
    from pytorch_distributed_nn_tpu.runtime import failure

    rep = failure.reporter()
    if rep is None:
        return False
    try:
        publish_snapshot(rep.client, rank=rep.rank,
                         incarnation=rep.incarnation, registry=registry)
        return True
    except (OSError, TimeoutError) as e:
        # counted retry, not just a log line: a store partition during
        # a publish window must be visible in the registry it failed
        # to ship (store_errors_total{op="publish"}), and the next
        # log-cadence tick retries naturally
        failure.count_store_error("publish")
        log.warning("metric snapshot publish failed: %s", e)
        return False


def collect_snapshots(client, ranks, *, incarnation: int = 0,
                      timeout_ms: int = 1000) -> dict[int, dict]:
    """Coordinator pull: each rank's latest snapshot (absent ranks are
    skipped — a worker that has not published yet is not an error)."""
    out: dict[int, dict] = {}
    for rank in ranks:
        key = _KEY_FMT.format(incarnation=incarnation, rank=rank)
        raw = _counted_pull(client, key, op="collect_snapshot",
                            timeout_ms=timeout_ms)
        if raw is None:
            continue
        try:
            out[rank] = json.loads(raw.decode())
        except ValueError as e:
            log.warning("snapshot for rank %d undecodable: %s", rank, e)
    return out


def _counted_pull(client, key: str, *, op: str, timeout_ms: int):
    """One coordinator-side store read through the counted retry
    helper (:func:`runtime.failure.store_call`): a partition degrades
    the pull to an absent entry (skipped, ``store_errors_total{op}``
    bumped per failure) — an aggregation sweep never dies of an
    uncounted store error, and never wedges past its deadline."""
    from pytorch_distributed_nn_tpu.runtime import failure

    def read():
        if not client.check(key):
            return None
        return client.get(key, timeout_ms=timeout_ms)

    return failure.store_call(
        read, op=op, deadline_s=max(timeout_ms / 1000.0, 0.5),
        fallback=None)


_TRACE_KEY_FMT = "trace/{rank}"


def publish_spans(client, *, rank: int, spans: list[dict]) -> str:
    """Causeway span transport (obs/trace.py): write this process's
    span buffer under ``trace/<rank>`` — same store, same
    last-writer-wins snapshot semantics as the metric snapshots.
    Canonical sort_keys JSON (the byte-determinism contract)."""
    key = _TRACE_KEY_FMT.format(rank=rank)
    client.set(key, json.dumps(spans, sort_keys=True).encode())
    return key


def collect_spans(client, ranks, *, timeout_ms: int = 1000) -> list[dict]:
    """Coordinator pull: every published per-host span buffer, joined
    into one flat list (absent ranks are skipped — a worker that has
    not traced anything yet is not an error). obs/critpath.py
    assembles the result into per-trace waterfalls."""
    out: list[dict] = []
    for rank in ranks:
        key = _TRACE_KEY_FMT.format(rank=rank)
        raw = _counted_pull(client, key, op="collect_spans",
                            timeout_ms=timeout_ms)
        if raw is None:
            continue
        try:
            out.extend(json.loads(raw.decode()))
        except ValueError as e:
            log.warning("trace spans for rank %d undecodable: %s",
                        rank, e)
    return out


_METER_KEY_FMT = "meter/{rank}"


def publish_ledgers(client, *, rank: int,
                    ledgers: dict[str, dict]) -> str:
    """Abacus ledger transport (obs/meter.py): write this process's
    per-tenant ledgers under ``meter/<rank>`` — same store, same
    last-writer-wins snapshot semantics as the metric snapshots.
    Canonical sort_keys JSON (the byte-determinism contract)."""
    key = _METER_KEY_FMT.format(rank=rank)
    client.set(key, json.dumps(ledgers, sort_keys=True).encode())
    return key


def collect_ledgers(client, ranks, *,
                    timeout_ms: int = 1000) -> dict[str, dict]:
    """Coordinator pull: every published per-rank ledger, merged into
    one per-tenant view by exact integer summation (absent ranks are
    skipped — an unarmed worker that never published is not an
    error)."""
    from pytorch_distributed_nn_tpu.obs import meter

    parts: list[dict] = []
    for rank in ranks:
        key = _METER_KEY_FMT.format(rank=rank)
        raw = _counted_pull(client, key, op="collect_ledgers",
                            timeout_ms=timeout_ms)
        if raw is None:
            continue
        try:
            parts.append(json.loads(raw.decode()))
        except ValueError as e:
            log.warning("meter ledger for rank %d undecodable: %s",
                        rank, e)
    return meter.merge_ledgers(parts)


def merge_snapshots(snapshots: dict[int, dict]) -> dict:
    """{"summed": {metric: Σ across hosts}, "per_rank": {metric:
    {rank: value}}} — counters read from "summed", gauges from
    "per_rank" (summing a per-host gauge like heartbeat age would be
    meaningless)."""
    summed: dict[str, float] = {}
    per_rank: dict[str, dict[int, float]] = {}
    for rank, snap in sorted(snapshots.items()):
        for metric, value in snap.items():
            summed[metric] = summed.get(metric, 0.0) + float(value)
            per_rank.setdefault(metric, {})[rank] = float(value)
    return {"summed": summed, "per_rank": per_rank,
            "hosts": len(snapshots)}
